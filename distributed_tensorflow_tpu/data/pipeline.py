"""Host input pipeline: shuffle examples, then batch.

Deliberately fixes two reference quirks (SURVEY.md §2.4(5)): the reference
batches *before* shuffling (so it shuffles batches, reference
initializer.py:44-45) and reads the shard count from a fork-inherited module
global (reference initializer.py:44 vs :119).  Here shuffling is
example-level with a per-epoch deterministic permutation, and all sharding
parameters are explicit.

Batches are yielded as (x, y, mask): ``mask`` flags padding rows added so
every batch divides evenly over the device mesh — eval stays exact without
dropping the remainder (the reference's server-side eval also uses the full
test set, reference server.py:24-37, 179-180).

Iterator contract (shared by this module, native.batcher, and any custom
producer): one epoch of ``(x, y, mask)`` host-numpy batches, every batch the
same leading size (padded+masked final batch unless ``drop_remainder``), and
an optional ``close()`` for early release (plain generators have one; the
native batcher's epoch iterator uses it to free its busy claim).  Consumers
that read AHEAD of the training loop — data.device_prefetch, which stages
batches on device so transfer overlaps compute — rely on exactly this
surface and must call ``close()`` when stopping early.

Elastic resume extends the contract two ways (elastic/data_state.py):
``start_batch`` skips the first N batches of an epoch WITHOUT changing the
epoch's shuffle permutation — a stream resumed at ``start_batch=N``
continues the identical batch sequence the uninterrupted epoch would have
produced from its N-th batch on — and producers MAY expose
``state() -> DataState`` reporting their (epoch, batch) position
(``ResumableBatches`` is the reference implementation).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

Batch = tuple[np.ndarray, np.ndarray, np.ndarray]


def iter_batches(
    x: np.ndarray,
    y: np.ndarray,
    batch_size: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    drop_remainder: bool = False,
    start_batch: int = 0,
) -> Iterator[Batch]:
    if start_batch < 0:
        raise ValueError(f"start_batch must be >= 0, got {start_batch}")
    n = len(x)
    idx = np.arange(n)
    if shuffle:
        # the permutation depends only on (seed, epoch) — never on
        # start_batch — so a resumed stream yields exactly the batches the
        # uninterrupted epoch would have yielded from start_batch on
        rng = np.random.default_rng((seed, epoch))
        rng.shuffle(idx)
    for start in range(start_batch * batch_size, n, batch_size):
        take = idx[start : start + batch_size]
        if len(take) < batch_size:
            if drop_remainder:
                return
            bx, by = x[take], y[take]
            mask = np.ones(len(take), dtype=np.float32)
            pad = batch_size - len(take)
            bx = np.concatenate([bx, np.zeros((pad, *x.shape[1:]), x.dtype)])
            # labels may be multi-dim (LM next-token targets are (B, L))
            by = np.concatenate([by, np.zeros((pad, *y.shape[1:]), y.dtype)])
            mask = np.concatenate([mask, np.zeros(pad, np.float32)])
            yield bx, by, mask
            return
        yield x[take], y[take], np.ones(batch_size, dtype=np.float32)


def steps_per_epoch(n: int, batch_size: int, drop_remainder: bool = False) -> int:
    return n // batch_size if drop_remainder else -(-n // batch_size)
