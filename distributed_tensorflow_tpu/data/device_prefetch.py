"""Async host→device prefetch over any host batch iterator.

The steady-state half of the input path: ``iter_batches`` / the native C++
batcher produce host numpy batches, and this wrapper keeps a small buffer of
batches *already placed on device* with the engine's input ``NamedSharding``
(via the engine's ``shard_batch``, i.e. a non-blocking ``jax.device_put``),
so the host→device transfer for batch N+1 overlaps the device compute of
batch N.  The reference has no counterpart — its input prep, TCP transfer
and training interleave serially on one Python thread (reference
initializer.py:24-55, client.py:78-95).

Iterator contract (shared with data.pipeline / native.batcher): the wrapped
``batches`` iterable yields host batches (any tuple shape — the ``place``
callable owns the interpretation) and MAY expose ``close()`` (generators do;
the native batcher's epoch iterator does, to release its busy claim).  The
prefetcher reads ahead of its consumer, so when the consumer stops early
(max_steps, early-stop, an exception) it must be ``close()``d — which closes
the source — rather than abandoned to GC timing.
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Iterable, Iterator


class DevicePrefetch:
    """Iterator of device-placed batches with a bounded read-ahead buffer.

    ``place`` maps one host batch to its device form (typically
    ``engine.shard_batch``); placement is issued eagerly for up to ``depth``
    batches beyond the one the consumer holds.  ``jax.device_put`` is
    asynchronous, so issuing the placement *is* starting the transfer —
    no thread is needed, the XLA transfer engine does the overlap.

    Telemetry (read by the Trainer at chunk boundaries and rolled into the
    run report) — two complementary signals with DIFFERENT sensitivities:

    * ``fill_wait_s`` is the load-bearing slow-input signal: the
      consumer-path seconds spent inside the synchronous refill waiting on
      host batch production — time a slow input pipeline steals from
      dispatch regardless of depth.  A healthy run keeps it a small
      fraction of elapsed.
    * ``starvation`` counts hand-offs that left ZERO batches staged ahead
      — the read-ahead margin hit bottom.  Because the refill runs to
      ``depth`` before every hand-off, this is structurally a
      depth-sizing signal (``depth == 1`` runs with no margin and counts
      every hand-off; ``depth >= 2`` counts only source exhaustion), NOT
      a slow-source detector — that is ``fill_wait_s``'s job.
    * ``queue_depth`` is the staged-batch gauge — a consumer slower than
      the source sees it pinned at ``depth``.
    """

    def __init__(self, batches: Iterable, place: Callable, depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._source: Iterator | None = iter(batches)
        self._place = place
        self._depth = depth
        self._buf: collections.deque = collections.deque()
        self.starvation = 0
        self.fill_wait_s = 0.0
        # batches handed to the CONSUMER (not pulled from the source): the
        # exactly-once resume position — batches still staged in the
        # buffer were read ahead but never trained on, so a checkpointed
        # data state built from `consumed` (elastic/data_state.py
        # consumer_state) replays none of them and drops none either
        self.consumed = 0
        self._fill()  # constructor prefill is not consumer wait time
        self.fill_wait_s = 0.0

    def _fill(self) -> None:
        t0 = time.perf_counter()
        while self._source is not None and len(self._buf) < self._depth:
            try:
                host = next(self._source)
            except StopIteration:
                self._release_source()
                break
            self._buf.append(self._place(host))
        self.fill_wait_s += time.perf_counter() - t0

    def __iter__(self) -> "DevicePrefetch":
        return self

    def __next__(self):
        if not self._buf:
            self._fill()
        if not self._buf:
            raise StopIteration
        out = self._buf.popleft()
        self.consumed += 1
        if not self._buf and self._source is not None:
            # nothing staged ahead of the batch just handed out: the next
            # transfer starts cold instead of overlapping compute
            self.starvation += 1
        # issue the replacement transfer BEFORE handing the batch to the
        # consumer: the device computes on `out` while this one stages
        self._fill()
        return out

    @property
    def queue_depth(self) -> int:
        """Batches currently staged on device ahead of the consumer."""
        return len(self._buf)

    @property
    def depth(self) -> int:
        """Configured read-ahead bound (the --prefetch knob)."""
        return self._depth

    def stats(self) -> dict:
        """Gauge snapshot for the run report / trace timeline."""
        return {"depth": self._depth, "queue_depth": len(self._buf),
                "starvation": self.starvation,
                "fill_wait_s": self.fill_wait_s,
                "consumed": self.consumed}

    def take(self, n: int) -> list:
        """Up to ``n`` next batches (fewer at exhaustion, [] when done) —
        the chunk-assembly call of the Trainer's multi-step drain."""
        out: list = []
        while n > 0 and len(out) < n:
            try:
                out.append(next(self))
            except StopIteration:
                break
        return out

    def _release_source(self) -> None:
        src, self._source = self._source, None
        if src is not None:
            close = getattr(src, "close", None)
            if close is not None:
                close()

    def close(self) -> None:
        """Drop buffered batches and close the source iterator (releases a
        native batcher's busy claim; see module docstring)."""
        self._buf.clear()
        self._release_source()

    def __del__(self):  # pragma: no cover - GC-timing safety net
        try:
            self.close()
        except Exception:
            pass


def device_prefetch(batches: Iterable, place: Callable,
                    depth: int = 2) -> DevicePrefetch:
    """Wrap a host batch iterator in a :class:`DevicePrefetch`."""
    return DevicePrefetch(batches, place, depth=depth)
