"""L3 data plug-in point.

The reference's contract is ``dataset_fn(batch_size, type='train'|'test',
shard=True, index=0, buffer_size=10000, reshape=True) -> tf.data.Dataset``
(reference initializer.py:24-55).  Here the same signature yields a
:class:`Dataset` of host numpy arrays; batching/sharding happens in the
pipeline (shuffle *examples* then batch — deliberately fixing the
reference's batch-before-shuffle quirk, reference initializer.py:44-45 /
SURVEY.md §2.4(5)) and device placement happens in the engine via
``NamedSharding`` rather than per-process `.shard()` calls.
"""

from __future__ import annotations

from typing import Callable

from distributed_tensorflow_tpu.data.device_prefetch import (  # noqa: F401
    DevicePrefetch,
    device_prefetch,
)
from distributed_tensorflow_tpu.data.loaders import (
    Dataset,
    load_dataset,
)
from distributed_tensorflow_tpu.data.pipeline import iter_batches  # noqa: F401


def make_dataset_fn(name: str, **load_kw) -> Callable[..., Dataset]:
    """Build a reference-signature dataset_fn for a named dataset.

    ``shard``/``index`` reproduce `tf.data ... .shard(n_nodes, index)`
    semantics (reference initializer.py:44) for multi-host runs, but with the
    shard count passed explicitly (``n_shards``) instead of the reference's
    fork-inherited module global (SURVEY.md §2.4(5)).
    """

    def dataset_fn(
        batch_size: int,
        type: str = "train",
        shard: bool = False,
        index: int = 0,
        buffer_size: int = 10000,
        reshape: bool = True,
        n_shards: int = 1,
        process: bool = False,
    ) -> Dataset:
        ds = load_dataset(name, split=type, reshape=reshape, **load_kw)
        if shard and n_shards > 1:
            if process:
                # one shard PER JAX PROCESS feeding lock-step training:
                # even shards + the process_shard marker the Trainer reads
                # to assemble global batches from local rows.  n_shards
                # must equal jax.process_count() (the Trainer validates).
                ds = ds.process_shard_of(n_shards, index)
            else:
                # reference semantics: every n-th example, no truncation
                ds = ds.shard(n_shards, index)
        ds = ds.with_batching(batch_size=batch_size, buffer_size=buffer_size)
        return ds

    dataset_fn.__name__ = f"dataset_fn_{name}"
    return dataset_fn
