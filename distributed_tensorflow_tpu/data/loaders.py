"""Dataset loading: local archives when present, deterministic synthetic
fallback otherwise (this environment has zero egress — nothing downloads).

Real-data formats understood:
  mnist / fashion_mnist  — keras-style ``.npz`` with x_train/y_train/x_test/y_test
  cifar10                — either ``cifar10.npz`` (same keys) or the original
                           ``cifar-10-batches-py`` pickle directory

Search order: $DTF_TPU_DATA_DIR, ~/.keras/datasets, ./datasets, /root/data.
The synthetic fallback draws each example from a fixed per-class prototype
plus noise, so models genuinely *learn* (accuracy targets in tests are
meaningful), and is deterministic in (name, split).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
from pathlib import Path

import numpy as np

_SHAPES = {
    "mnist": ((28, 28), 10),
    "fashion_mnist": ((28, 28), 10),
    "cifar10": ((32, 32, 3), 10),
}


@dataclasses.dataclass
class Dataset:
    """Host-side dataset: plain numpy, batched lazily by the pipeline."""

    x: np.ndarray
    y: np.ndarray
    num_classes: int
    name: str = "dataset"
    synthetic: bool = False
    batch_size: int | None = None
    buffer_size: int = 10000
    # (index, count) when this dataset is one PROCESS's shard of a larger
    # logical dataset (multi-host input sharding): the Trainer then treats
    # batches as process-local rows of a global batch (engines/allreduce.py)
    process_shard: tuple[int, int] | None = None

    def __len__(self) -> int:
        return len(self.x)

    def shard(self, n_shards: int, index: int, even: bool = False) -> "Dataset":
        """Every n-th example, like `tf.data .shard` (reference initializer.py:44).

        ``even=True`` truncates every shard to ``len // n_shards`` so all
        shards are the same size — required when shards drive lock-step
        SPMD processes (unequal batch counts would deadlock collectives)."""
        x, y = self.x[index::n_shards], self.y[index::n_shards]
        if even:
            m = len(self.x) // n_shards
            x, y = x[:m], y[:m]
        return dataclasses.replace(self, x=x, y=y)

    def process_shard_of(self, n_procs: int, index: int) -> "Dataset":
        """This process's shard for multi-host training: EVEN shards (all
        processes must run the same batch count — uneven ones would wedge
        lock-step collectives) plus the ``process_shard`` marker the
        Trainer reads to assemble global batches from process-local rows.
        The two must always travel together; use this, not bare shard()."""
        return dataclasses.replace(
            self.shard(n_procs, index, even=True),
            process_shard=(index, n_procs))

    def with_batching(self, batch_size: int, buffer_size: int = 10000) -> "Dataset":
        return dataclasses.replace(
            self, batch_size=batch_size, buffer_size=buffer_size
        )

    def batches(self, batch_size: int | None = None, *, shuffle: bool = True,
                seed: int = 0, epoch: int = 0, drop_remainder: bool = False,
                native: bool | None = None, start_batch: int = 0):
        """Iterate (x, y, mask) batches for one epoch.

        ``native=None`` (default) uses the C++ prefetching pipeline when the
        native library is available AND the host has >1 core (the prefetch
        thread needs a core of its own to overlap with the training step;
        measured a wash on 1-core hosts), falling back to the pure-Python
        path; True requires the native path; False forces Python.  Both
        paths yield byte-identical batches (tests/test_native.py) and honor
        the shared iterator contract (data/pipeline.py module docstring):
        same-size (x, y, mask) batches plus ``close()`` for early release —
        what data.device_prefetch wraps to stage batches on device ahead
        of the training loop.

        ``start_batch`` > 0 resumes the epoch at its N-th batch (elastic
        restore, elastic/data_state.py): the shuffle permutation depends
        only on (seed, epoch), so the resumed stream continues the exact
        batch sequence the uninterrupted epoch would have produced.  The
        C++ pipeline stages from batch 0 only, so a mid-epoch resume takes
        the Python path (byte-identical batches either way); ``native=True``
        is rejected rather than silently replaying the skipped prefix.
        """
        from distributed_tensorflow_tpu.data.pipeline import iter_batches

        bs = batch_size or self.batch_size
        if bs is None:
            raise ValueError("batch_size not set; pass it or use with_batching()")
        if start_batch:
            if native:
                raise RuntimeError(
                    "the native pipeline has no mid-epoch resume (its C++ "
                    "cursor starts at batch 0); start_batch > 0 requires "
                    "the Python path")
            native = False
        if getattr(self.y, "ndim", 1) > 1:
            # the C++ gather stages SCALAR labels (native/batcher.py fills
            # a (batch,) int32 buffer): an LM dataset's (B, L) next-token
            # targets would silently flatten to (B,) garbage — gate to the
            # Python path, loudly when the caller forced native
            if native:
                raise RuntimeError(
                    "the native pipeline gathers scalar labels only; "
                    f"this dataset's targets are {self.y.ndim - 1}-D per "
                    "row (LM next-token layout) — use the Python path")
            native = False
        if native is None and (os.cpu_count() or 1) < 2:
            native = False
        if native is not False:
            try:
                nb = self._native_batcher(bs)
                return nb.epoch(shuffle=shuffle, seed=seed, epoch=epoch,
                                drop_remainder=drop_remainder)
            except RuntimeError:
                if native:
                    raise
        return iter_batches(
            self.x, self.y, bs, shuffle=shuffle, seed=seed, epoch=epoch,
            drop_remainder=drop_remainder, start_batch=start_batch,
        )

    def _native_batcher(self, batch_size: int):
        """Cached per-batch-size native pipeline — reusing it across epochs
        keeps one C++ worker pool + staging buffers (and, for sharded
        datasets, one contiguous copy) alive for the whole run.  If the
        cached pipeline is mid-epoch (a concurrent iterator is active), a
        fresh uncached one preserves the independent-iterators contract of
        the Python path."""
        from distributed_tensorflow_tpu.native.batcher import NativeBatcher

        cache = self.__dict__.setdefault("_batcher_cache", {})
        nb = cache.get(batch_size)
        if nb is None:
            nb = NativeBatcher(self.x, self.y, batch_size)
            cache[batch_size] = nb
        elif nb.busy:
            nb = NativeBatcher(self.x, self.y, batch_size)
        return nb


def _search_dirs() -> list[Path]:
    dirs = []
    if os.environ.get("DTF_TPU_DATA_DIR"):
        dirs.append(Path(os.environ["DTF_TPU_DATA_DIR"]))
    dirs += [
        Path.home() / ".keras" / "datasets",
        Path("datasets"),
        Path("/root/data"),
    ]
    return [d for d in dirs if d.is_dir()]


def _find(*names: str) -> Path | None:
    for d in _search_dirs():
        for n in names:
            p = d / n
            if p.exists():
                return p
    return None


def _load_npz(path: Path, split: str):
    with np.load(path, allow_pickle=False) as f:
        if split == "train":
            return f["x_train"], f["y_train"]
        return f["x_test"], f["y_test"]


def _load_cifar_batches(path: Path, split: str):
    def one(p: Path):
        with open(p, "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x, np.asarray(d[b"labels"])

    if split == "train":
        parts = [one(path / f"data_batch_{i}") for i in range(1, 6)]
        return (np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]))
    return one(path / "test_batch")


def synthetic_classification(
    shape: tuple[int, ...],
    num_classes: int,
    n: int,
    seed: int,
    split: str = "train",
    noise: float = 0.35,
):
    """Per-class Gaussian prototypes + noise: learnable, deterministic.

    Prototypes depend only on ``seed`` (shared across splits); the noise and
    label draws are keyed by (seed, split) so train/test are disjoint samples
    of the same underlying task.
    """
    proto_rng = np.random.default_rng(seed)
    protos = proto_rng.normal(0.5, 0.25, size=(num_classes, *shape)).clip(0, 1)
    rng = np.random.default_rng((seed, 0 if split == "train" else 1))
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    x = protos[y] + rng.normal(0.0, noise, size=(n, *shape))
    return x.clip(0.0, 1.0).astype(np.float32), y


def synthetic_text_classification(
    n: int,
    seq_len: int = 128,
    vocab_size: int = 1024,
    num_classes: int = 2,
    seed: int = 0,
    split: str = "train",
):
    """Topic-model synthetic text: each class draws tokens from its own
    Zipf-reweighted vocabulary distribution (BERT-tiny learns it quickly —
    the GLUE-stand-in for the zero-egress environment).  Token id 0 is
    reserved for padding; sequences are full-length."""
    proto_rng = np.random.default_rng(seed)
    base = 1.0 / np.arange(1, vocab_size)  # ids 1..V-1, Zipf-ish
    class_logits = np.stack([
        np.log(base) + 0.75 * proto_rng.normal(size=vocab_size - 1)
        for _ in range(num_classes)
    ])
    probs = np.exp(class_logits)
    probs /= probs.sum(axis=1, keepdims=True)
    rng = np.random.default_rng((seed, 0 if split == "train" else 1))
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    x = np.stack([
        rng.choice(vocab_size - 1, size=seq_len, p=probs[c]) + 1 for c in y
    ]).astype(np.int32)
    x[:, 0] = 1  # fixed [CLS]-like token at position 0
    return x, y


def synthetic_lm(
    n: int,
    seq_len: int = 128,
    vocab_size: int = 128,
    seed: int = 0,
    split: str = "train",
    concentration: float = 0.1,
):
    """First-order Markov-chain token streams for language modeling.

    Each row of the transition matrix is a Dirichlet(concentration) draw —
    low concentration makes transitions peaked, so an LM that learns the
    chain reaches high next-token accuracy while an untrained one sits at
    ~1/vocab: the gap is what tests assert.  Deterministic in (seed, split);
    the chain (like the classification prototypes above) is shared across
    splits while the trajectories are disjoint.

    Returns ``(x, y)`` with x = tokens[:, :-1] and y = tokens[:, 1:] —
    next-token targets are materialized by the DATASET, so models never
    shift internally and every engine's (input, label) contract is identical
    to classification (just with (B, L)-shaped labels).
    """
    proto_rng = np.random.default_rng(seed)
    trans = proto_rng.dirichlet(
        np.full(vocab_size, concentration), size=vocab_size)
    cdf = np.cumsum(trans, axis=1)
    rng = np.random.default_rng((seed, 0 if split == "train" else 1))
    seq = np.empty((n, seq_len + 1), np.int64)
    seq[:, 0] = rng.integers(0, vocab_size, size=n)
    for t in range(1, seq_len + 1):
        u = rng.random(n)
        # inverse-CDF sampling, vectorized over rows; clip guards the float
        # edge where a row's cumsum tops out below 1.0 and a draw lands past
        # it — unclipped that yields the out-of-range id == vocab_size
        seq[:, t] = np.minimum(
            (cdf[seq[:, t - 1]] < u[:, None]).sum(axis=1), vocab_size - 1)
    seq = seq.astype(np.int32)
    return seq[:, :-1], seq[:, 1:]


def load_lm_dataset(
    name: str = "lm_synth",
    split: str = "train",
    seq_len: int = 128,
    vocab_size: int | None = None,
    n_train: int = 4096,
    n_test: int = 1024,
    holdout: float = 0.1,
) -> Dataset:
    """Language-modeling workload: (B, L) token inputs with (B, L)
    next-token targets (``num_classes`` = vocab size, so the engines' loss —
    which broadcasts over label dims, engines/base.py — trains it unchanged).

    Real corpora: a local ``<name>.bin`` (or ``lm_tokens.bin``) in the data
    search path — the standard flat binary of uint16 token ids (nanoGPT-
    style) — is memory-mapped and windowed into non-overlapping seq_len
    chunks with the final ``holdout`` fraction as the test split; the
    window arrays are materialized (one contiguous read), so the engines
    see plain numpy either way.  Pass ``vocab_size`` for large corpora —
    when omitted it is derived with a full-file max scan (per split).
    Otherwise the deterministic Markov-chain synthetic corpus (zero-egress
    environment)."""
    path = _find(f"{name}.bin", "lm_tokens.bin")
    if path is not None:
        tokens = np.memmap(path, dtype=np.uint16, mode="r")
        cut = int(len(tokens) * (1.0 - holdout))
        lo, hi = (0, cut) if split == "train" else (cut, len(tokens))
        n = (hi - lo - 1) // seq_len
        if n < 1:
            # clamping to one window would read past the region (train
            # would silently leak held-out tokens; test past EOF)
            raise ValueError(
                f"{split} region of {path.name} has {hi - lo} tokens — "
                f"fewer than seq_len + 1 = {seq_len + 1}; shrink seq_len "
                f"or holdout")
        base = lo + np.arange(n * seq_len)
        x = np.asarray(tokens[base]).reshape(n, seq_len).astype(np.int32)
        y = np.asarray(tokens[base + 1]).reshape(n, seq_len).astype(np.int32)
        vocab = (vocab_size if vocab_size is not None
                 else int(tokens.max()) + 1)
        if vocab_size is not None:
            # an undersized explicit vocab would otherwise be silently
            # clamped downstream (nn.Embed gather + CE label gather) and
            # train on corrupted ids (ADVICE r3)
            top = int(max(x.max(), y.max()))
            if top >= vocab_size:
                raise ValueError(
                    f"vocab_size {vocab_size} does not cover {path.name}: "
                    f"{split} split contains token id {top}; pass "
                    f"vocab_size >= {top + 1} or omit it to derive from "
                    f"the corpus")
        return Dataset(x=x, y=y, num_classes=vocab, name=name,
                       synthetic=False)
    vocab = vocab_size if vocab_size is not None else 128
    n = n_train if split == "train" else n_test
    x, y = synthetic_lm(n, seq_len=seq_len, vocab_size=vocab,
                        seed=sum(ord(c) for c in name) % (2**31), split=split)
    return Dataset(x=x, y=y, num_classes=vocab, name=name,
                   synthetic=True)


def load_text_dataset(
    name: str = "glue_synth",
    split: str = "train",
    seq_len: int = 128,
    vocab_size: int = 1024,
    n_train: int = 4096,
    n_test: int = 1024,
) -> Dataset:
    """Text workload loader (BASELINE.json BERT-tiny stretch config).
    Currently synthetic-only: real GLUE needs downloads this env can't do."""
    n = n_train if split == "train" else n_test
    x, y = synthetic_text_classification(
        n, seq_len=seq_len, vocab_size=vocab_size,
        seed=sum(ord(c) for c in name) % (2**31), split=split)
    return Dataset(x=x, y=y, num_classes=2, name=name, synthetic=True)


def load_dataset(
    name: str,
    split: str = "train",
    reshape: bool = True,
    n_synthetic_train: int = 8192,
    n_synthetic_test: int = 2048,
) -> Dataset:
    """Load a named dataset; silently fall back to synthetic when no local copy.

    ``reshape`` mirrors the reference's flag (reference initializer.py:28-35):
    True adds a trailing channel dim to 2-D images ((28,28) → (28,28,1)).
    """
    if name in ("glue_synth", "text", "glue"):
        return load_text_dataset(name, split=split)
    if name in ("lm_synth", "lm"):
        return load_lm_dataset(name, split=split)
    if name in ("synthetic", "synth"):
        name, shape, ncls, path = "synthetic", (28, 28), 10, None
    elif name in _SHAPES:
        shape, ncls = _SHAPES[name]
        if name == "mnist":
            path = _find("mnist.npz")
        elif name == "fashion_mnist":
            path = _find("fashion_mnist.npz", "fashion-mnist.npz")
        else:
            path = _find("cifar10.npz") or _find("cifar-10-batches-py")
    else:
        raise KeyError(f"unknown dataset '{name}'; known: {sorted(_SHAPES)} + synthetic")

    if path is not None:
        if path.is_dir():
            x, y = _load_cifar_batches(path, split)
        else:
            x, y = _load_npz(path, split)
        x = x.astype(np.float32) / 255.0
        synthetic = False
    else:
        n = n_synthetic_train if split == "train" else n_synthetic_test
        # stable per-dataset seed (hash() is salted per process — don't use it)
        seed = sum(ord(c) for c in name) * 1000003 % (2**31)
        x, y = synthetic_classification(shape, ncls, n, seed, split=split)
        synthetic = True

    if reshape and x.ndim == 3:  # (N,28,28) → (N,28,28,1), reference initializer.py:28-29
        x = x[..., None]
    return Dataset(
        x=x, y=y.astype(np.int32), num_classes=ncls,
        name=name, synthetic=synthetic,
    )
