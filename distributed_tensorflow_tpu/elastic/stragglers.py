"""Straggler/stall detection: step-time outliers as structured events.

The watchdog (utils/failure.py) catches the binary failure — no progress
at all within a timeout.  Stragglers are the gray zone underneath it: a
chunk that completed but took several times the typical step time (a
contended host, a thermally throttled chip, a slow NFS checkpoint volume
bleeding into the dispatch path).  On a lock-step SPMD program ONE slow
participant sets the pace for everyone, so sustained outliers are the
first observable symptom of a degrading lease — worth surfacing before
the watchdog's hard timeout ever fires.

:class:`StragglerDetector` rides the measurements the Trainer already
makes (the per-chunk step-time averages feeding ``StepTimer``, the same
cadence as the Watchdog's beats): each observation is compared against
the running median of a bounded window, and an outlier beyond
``factor``× the median emits a structured ``straggler`` event on the
trace timeline (the same stream the anomaly/stall events use —
``analyze spans`` and the Perfetto export pick it up unchanged).  The
outlier still enters the window, so a NEW sustained pace stops flagging
once the median catches up — a permanently slower mesh is the new
normal, not an endless alarm.
"""

from __future__ import annotations

import collections
import statistics
from typing import Any


class StragglerDetector:
    """Running-median outlier detector over per-step wall times.

    ``observe(step, step_time_s)`` returns True (and emits a
    ``straggler`` trace event when a tracer is wired) iff at least
    ``min_samples`` observations preceded this one and it exceeds
    ``factor`` × their median.  Pure host-side arithmetic on numbers the
    Trainer already holds — zero device syncs, zero downshift.
    """

    def __init__(self, tracer=None, factor: float = 3.0,
                 min_samples: int = 5, window: int = 64):
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1, got {factor}")
        if min_samples < 2:
            raise ValueError(f"min_samples must be >= 2, got {min_samples}")
        self.tracer = tracer
        self.factor = float(factor)
        self.min_samples = int(min_samples)
        self._times: collections.deque = collections.deque(maxlen=window)
        self.observed = 0
        self.events = 0
        self.max_ratio = 0.0
        self.last_straggler_step: int | None = None

    def observe(self, step: int, step_time_s: float) -> bool:
        flagged = False
        if len(self._times) >= self.min_samples:
            median = statistics.median(self._times)
            if median > 0.0 and step_time_s > self.factor * median:
                flagged = True
                self.events += 1
                self.max_ratio = max(self.max_ratio, step_time_s / median)
                self.last_straggler_step = step
                if self.tracer is not None:
                    self.tracer.event(
                        "straggler", step=step,
                        step_time_s=step_time_s, median_s=median,
                        ratio=step_time_s / median, factor=self.factor)
        self._times.append(step_time_s)
        self.observed += 1
        return flagged

    def report(self) -> dict[str, Any]:
        """The ``stragglers`` section of the fit result / run report."""
        return {
            "events": self.events,
            "observed": self.observed,
            "max_ratio": round(self.max_ratio, 4) if self.events else None,
            "last_straggler_step": self.last_straggler_step,
            "factor": self.factor,
        }
