"""Elastic preemption-tolerant training: a run as a resumable,
mesh-shape-independent object.

Four pillars over PR 5's atomic async checkpoints (ROADMAP item 3):

* **Resharding restore** (``reshard.py``): restore a checkpoint onto a
  different mesh shape — device count AND axis layout — within the GSPMD
  engine family, precision-policy-aware, re-placed under the target
  engine's spec map.
* **Exactly-once data resume** (``data_state.py``): the batch iterator's
  (epoch, offset, seed) position rides each checkpoint as the elastic
  sidecar; resume continues the identical batch sequence, prefetch
  read-ahead drained/discounted.
* **Graceful lease drain** (``lease.py``): ``--max-steps-per-lease`` and
  a SIGTERM preemption-notice handler finish the in-flight chunk, write
  a final checkpoint and exit with a structured ``preempted`` report
  section.
* **Straggler detection + preemption accounting** (``stragglers.py``,
  ``reshard.preemption_lost_s``): step-time outliers as structured
  ``straggler`` trace events; ``preemption_lost_s`` /
  ``resume_replay_steps`` as first-class, ``analyze diff``-gated numbers
  (MLPerf time-to-quality framing, PAPERS.md).
"""

from distributed_tensorflow_tpu.elastic.data_state import (  # noqa: F401
    DATA_STATE_VERSION, DataState, ResumableBatches, consumer_state)
from distributed_tensorflow_tpu.elastic.lease import (  # noqa: F401
    LeaseManager)
from distributed_tensorflow_tpu.elastic.reshard import (  # noqa: F401
    ElasticRestoreError, elastic_restore, place_under_spec_map,
    preemption_lost_s)
from distributed_tensorflow_tpu.elastic.stragglers import (  # noqa: F401
    StragglerDetector)

__all__ = [
    "DATA_STATE_VERSION",
    "DataState",
    "ResumableBatches",
    "consumer_state",
    "LeaseManager",
    "ElasticRestoreError",
    "elastic_restore",
    "place_under_spec_map",
    "preemption_lost_s",
    "StragglerDetector",
]
