"""Exactly-once data resume: the batch stream's position as checkpoint
state.

The training loop's batch sequence is a pure function of (seed, epoch,
batch index): the shuffle permutation is a deterministic draw from
``(seed, epoch)`` (data/pipeline.py) and ``start_batch`` resumes an epoch
mid-stream without changing it.  So the ENTIRE data-iterator state is the
small record below — it rides each checkpoint as the elastic sidecar
(utils/checkpoint.py ``save(..., extra=)``), and a resumed run continues
the *identical* batch sequence: kill-and-resume is bitwise-comparable to
the uninterrupted run on the same mesh, and tolerance-comparable across
meshes (tests/test_elastic.py).

Prefetch discounting: the device prefetcher (data/device_prefetch.py) and
the Trainer's chunk assembly read AHEAD of the steps actually trained.
Batches staged but not yet consumed must be neither replayed (they were
pulled from the source) nor dropped (they were never trained on) — the
position that goes into the checkpoint is the CONSUMER count, not the
producer count.  The Trainer derives it from its own step counter (a
checkpoint boundary's state covers exactly ``steps`` batches);
``consumer_state`` below is the same discount for custom consumers
wrapping a :class:`ResumableBatches` in a ``DevicePrefetch``.

A checkpoint without a data state (written by an older build, or by a run
with different seed/batch-size) still restores — the resumed run then
restarts the batch stream from epoch 0 and reports the unrecoverable
positions as ``resume_replay_steps`` (BASELINE.md "Preemption
accounting").

Scope note (multi-process pods): the state records the PER-PROCESS local
batch size and shard length, so a resume across a different *process*
count fails the match and replay-accounts — deliberately.  Each process
iterates its own dataset shard, and resharding the data across a new
process count changes every shard's content: there is no position in the
new shards that continues the old global sequence, so a claimed "exact"
resume would be a lie.  Exact cross-resize resume covers the
device-count/axis-layout changes of a single-process (or
process-count-preserving) relaunch at equal global batch.
"""

from __future__ import annotations

import dataclasses
from typing import Any

DATA_STATE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class DataState:
    """One batch stream's resume position plus the identity fields that
    decide whether an exact resume is valid: a state recorded under a
    different seed, batch size or dataset length describes a DIFFERENT
    batch sequence, so matching fails and the consumer falls back to
    replay accounting instead of silently training on the wrong stream."""

    epoch: int
    batch_index: int          # batches consumed within `epoch`
    seed: int
    batch_size: int           # the LOCAL batch size the stream was cut at
    dataset_len: int
    dataset: str = "dataset"
    version: int = DATA_STATE_VERSION

    def to_json(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, payload: Any) -> "DataState | None":
        """Tolerant decode: a missing/garbled payload returns None (the
        replay-accounting path), never raises — a checkpoint must stay
        restorable even when its sidecar is from another build."""
        if not isinstance(payload, dict):
            return None
        try:
            return cls(
                epoch=int(payload["epoch"]),
                batch_index=int(payload["batch_index"]),
                seed=int(payload["seed"]),
                batch_size=int(payload["batch_size"]),
                dataset_len=int(payload["dataset_len"]),
                dataset=str(payload.get("dataset", "dataset")),
                version=int(payload.get("version", DATA_STATE_VERSION)))
        except (KeyError, TypeError, ValueError):
            return None

    def matches(self, *, seed: int, batch_size: int, dataset_len: int,
                dataset: str | None = None) -> bool:
        """True iff this state describes the batch sequence the given run
        parameters would produce — the precondition for an exact resume.
        ``dataset`` (the stream's recorded name) participates when given:
        two different datasets can coincide in seed/batch/length (e.g.
        equal-sized synthetic corpora), and resuming one at the other's
        position would silently train the wrong sequence."""
        return (self.seed == seed and self.batch_size == batch_size
                and self.dataset_len == dataset_len
                and (dataset is None or self.dataset == dataset))


class ResumableBatches:
    """The iterator contract's ``state()``/``restore()`` implementation:
    one epoch of ``(x, y, mask)`` batches over a ``Dataset`` that knows
    its own position.

    Satisfies the shared producer contract (data/pipeline.py module
    docstring — same-size batches, ``close()``) and adds ``state()``,
    which reports the PRODUCER position: how many of the epoch's batches
    have been pulled.  A consumer reading ahead (DevicePrefetch) must
    discount its buffer — use :func:`consumer_state` — or, like the
    Trainer, derive the position from its own consumption counter.

    ``ResumableBatches.restore(ds, state)`` continues the identical
    sequence: same (seed, epoch) permutation, skipping ``batch_index``
    batches (tests prove list equality with the uninterrupted stream).
    """

    def __init__(self, dataset, batch_size: int, *, seed: int = 0,
                 epoch: int = 0, start_batch: int = 0,
                 shuffle: bool = True, drop_remainder: bool = True):
        self._dataset = dataset
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.epoch = int(epoch)
        self.start_batch = int(start_batch)
        self._index = int(start_batch)
        self._it = dataset.batches(
            batch_size, shuffle=shuffle, seed=seed, epoch=epoch,
            drop_remainder=drop_remainder, start_batch=start_batch,
            native=False)

    def __iter__(self) -> "ResumableBatches":
        return self

    def __next__(self):
        batch = next(self._it)
        self._index += 1
        return batch

    def state(self) -> DataState:
        """Producer position: batches pulled from this stream so far."""
        return DataState(
            epoch=self.epoch, batch_index=self._index, seed=self.seed,
            batch_size=self.batch_size, dataset_len=len(self._dataset),
            dataset=getattr(self._dataset, "name", "dataset"))

    @classmethod
    def restore(cls, dataset, state: DataState,
                **kwargs) -> "ResumableBatches":
        """Resume the stream ``state`` describes: validates that ``state``
        was recorded over THIS dataset (its length and name — seed and
        batch size come FROM the state, so they cannot mismatch), then
        continues at its batch index."""
        name = getattr(dataset, "name", "dataset")
        if state.dataset_len != len(dataset) or state.dataset != name:
            raise ValueError(
                f"data state (dataset '{state.dataset}', "
                f"len={state.dataset_len}) does not describe this dataset "
                f"('{name}', len={len(dataset)}); an exact resume would "
                f"train the wrong batch sequence")
        return cls(dataset, state.batch_size, seed=state.seed,
                   epoch=state.epoch, start_batch=state.batch_index,
                   **kwargs)

    def close(self) -> None:
        close = getattr(self._it, "close", None)
        if close is not None:
            close()


def consumer_state(source: ResumableBatches, prefetcher) -> DataState:
    """The exactly-once position of a prefetched stream: the source's
    producer index minus everything the prefetcher staged but never handed
    out — i.e. ``start_batch + prefetcher.consumed``.  Checkpointing THIS
    number means a resume neither replays a trained batch nor drops a
    staged-but-untrained one (the prefetch depth is drained/discounted,
    not persisted)."""
    return dataclasses.replace(
        source.state(),
        batch_index=source.start_batch + prefetcher.consumed)
