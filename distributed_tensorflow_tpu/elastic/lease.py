"""Graceful lease drain: preemption as a planned exit, not a corpse.

Production TPU leases end two ways — a preemption notice (SIGTERM from
the scheduler, typically ~30 s before the kill) or a known budget
("this reservation is ours for N steps' worth of wall time").  Both map
to the same drain: finish the in-flight scan chunk, write a final async
checkpoint (with its data state), drain the writer, and return a result
whose ``preempted`` field names why — the harness then emits a
structured ``preempted`` run-report section and exits cleanly, so the
relaunch (``--elastic-restore``) continues the run exactly where the
lease ended.

:class:`LeaseManager` packages both triggers behind the ONE hook
``Trainer.fit`` checks at chunk boundaries (``should_stop``): a signal
handler that flips a flag (signal-safe: the handler does nothing but
assign) and a per-lease step budget (``--max-steps-per-lease``).  The
drain composes with ``steps_per_call > 1`` by construction — the hook is
only consulted where boundary state exists, so a preemption notice
mid-chunk lets the chunk finish (seconds) rather than abandoning it.
"""

from __future__ import annotations

import signal
import threading
from typing import Any


def _signal_name(signum: int | None) -> str | None:
    """Human name of a signal number ('SIGTERM'); the raw number as a
    string for values the platform's Signals enum does not know."""
    if signum is None:
        return None
    try:
        return signal.Signals(signum).name
    except ValueError:
        return str(signum)


class LeaseManager:
    """SIGTERM/step-budget preemption trigger for ``Trainer.fit``'s
    ``should_stop`` hook.

    ``install()`` arms the signal handlers (main thread only — Python
    restricts ``signal.signal`` to it; elsewhere the manager degrades to
    the step budget alone and says so in ``report()``), saving the
    previous dispositions for ``uninstall()``.  The handler only sets a
    flag: the actual drain happens on the training thread at the next
    chunk boundary, where a consistent boundary state exists to
    checkpoint.
    """

    def __init__(self, max_steps_per_lease: int = 0,
                 signals: tuple[int, ...] = (signal.SIGTERM,)):
        if max_steps_per_lease < 0:
            raise ValueError(
                f"max_steps_per_lease must be >= 0 (0 disables the step "
                f"budget), got {max_steps_per_lease}")
        self.max_steps_per_lease = int(max_steps_per_lease)
        self._signals = tuple(signals)
        self._prev: dict[int, Any] = {}
        self.installed = False
        self.was_installed = False  # sticky: survives uninstall(), so a
        # report() taken after the run's teardown still records that the
        # handler WAS armed while training ran
        self.preempt_signal: int | None = None
        # programmatic trigger (serving/fleet.py replica drain, tests):
        # same contract as the signal flag — one assignment under a lock,
        # read at the consumer's next boundary.  The lock matters for
        # trigger/reset pairs racing across threads (a fleet coordinator
        # triggering while a replica worker resets after its drain), not
        # for the flag read itself.
        self._trigger_lock = threading.Lock()
        self.trigger_reason: str | None = None

    # ----------------------------------------------------------- signals
    def _on_signal(self, signum, frame) -> None:  # noqa: ARG002
        # async-signal-safe by doing nothing but an assignment; the
        # training thread reads the flag at its next boundary
        self.preempt_signal = signum

    def install(self) -> "LeaseManager":
        if threading.current_thread() is not threading.main_thread():
            return self  # step budget still works; report() records it
        try:
            for s in self._signals:
                self._prev[s] = signal.signal(s, self._on_signal)
            self.installed = True
            self.was_installed = True
        except (ValueError, OSError):  # embedded interpreters etc.
            self.installed = False
        return self

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self.installed = False

    def __enter__(self) -> "LeaseManager":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -------------------------------------------------------------- hook
    def trigger(self, reason: str) -> None:
        """Programmatic preemption: flip the drain flag as if a notice
        arrived, without a real signal — the fleet supervisor's
        replica-drain path (serving/fleet.py weight hot-swap) and tests
        use this instead of delivering SIGTERM to the whole process.
        Thread-safe; the first reason wins until ``reset_trigger``."""
        if not reason:
            raise ValueError("trigger needs a non-empty reason string")
        with self._trigger_lock:
            if self.trigger_reason is None:
                self.trigger_reason = str(reason)

    def reset_trigger(self) -> None:
        """Re-arm after a programmatic drain completed (a swapped replica
        resumes serving on the same lease).  Only clears the programmatic
        flag — a real preemption signal stays sticky: the process is
        still going away, and un-noticing it would serve requests into
        the kill."""
        with self._trigger_lock:
            self.trigger_reason = None

    def should_stop(self, steps_done: int) -> str | None:
        """The ``Trainer.fit(should_stop=)`` hook: a reason string when
        the lease is over (preemption notice received, programmatic
        ``trigger``, or ``steps_done`` this fit reached the per-lease
        budget), else None."""
        if self.preempt_signal is not None:
            return f"signal:{_signal_name(self.preempt_signal)}"
        if self.trigger_reason is not None:
            return self.trigger_reason
        if (self.max_steps_per_lease
                and steps_done >= self.max_steps_per_lease):
            return f"max_steps_per_lease:{self.max_steps_per_lease}"
        return None

    def report(self) -> dict[str, Any]:
        """Run-report fodder: what the lease was armed with and whether a
        preemption notice arrived."""
        return {
            "max_steps_per_lease": self.max_steps_per_lease or None,
            "signal_handler_installed": self.was_installed,
            "preempt_signal": _signal_name(self.preempt_signal),
            "triggered": self.trigger_reason,
        }
