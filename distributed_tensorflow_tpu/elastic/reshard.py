"""Resharding restore: a checkpoint as a mesh-shape-independent object.

A checkpoint written by PR 5's managers stores FULL host arrays — Orbax
gathers sharded leaves transparently at save, so nothing on disk encodes
the mesh the run trained on.  What pinned restore to the same device
count was the restore path, not the format: nobody re-derived placements
for a different target.  This module closes that gap for the GSPMD
engine family (sync/allreduce, fsdp, tensor-parallel and the composite
axis layouts): restore loads each leaf into the TARGET engine's template
via the policy-aware machinery of ``parallel/precision.py`` (an f32-era
checkpoint adopts into a master policy exactly as on a fixed mesh), then
re-places every leaf under the partition spec the target engine's spec
map (``Engine.state_partition_specs``) assigns it on the NEW mesh —
replicated leaves replicate, fsdp leaves shard over the new 'data' axis,
Megatron leaves land on the new 'model' axis, and a precision policy's
f32 master copies inside ``opt_state`` reshard with the params they
mirror.  Device count and axis layout may both change; only the GLOBAL
shapes must match, which for the GSPMD family they do by construction.

Out of scope, by design: the per-device-STACKED engines (async local
SGD, gossip) carry one model replica per device as a leading state axis,
so their global shapes change with the device count — a cross-count
restore of divergent local replicas has no unique answer (consensus
averaging is a research choice, not a restore).  They restore onto the
count they were saved from; the error below names this instead of
surfacing a raw shape mismatch.
"""

from __future__ import annotations

import time
from typing import Any

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel import precision as precisionlib


class ElasticRestoreError(RuntimeError):
    """A checkpoint could not be restored into the target engine's layout
    (shape/structure mismatch beyond what resharding can bridge)."""


class _StepPinned:
    """Adapter pinning ``restore`` to one step so the policy-aware restore
    helpers (which take only a manager) can restore a non-latest step."""

    def __init__(self, manager, step: int):
        self._manager, self._step = manager, step

    def restore(self, template: Any) -> Any:
        return self._manager.restore(template, self._step)


def place_under_spec_map(state: Any, specs: Any, mesh) -> Any:
    """Re-place every array leaf of ``state`` under ``NamedSharding(mesh,
    spec)`` of its entry in ``specs`` (an ``Engine.state_partition_specs``
    tree).  The explicit resharding step of an elastic restore: leaves a
    same-mesh restore untouched (device_put to the current sharding is the
    identity) and moves cross-mesh leaves onto the new layout.  Leaves
    that are not mesh-placed to begin with (a pure-jit engine's
    single-device arrays, host scalars) are left alone — forcing them
    onto a mesh would CHANGE the engine's execution semantics, not
    restore them."""
    def place(leaf, spec):
        if (isinstance(leaf, jax.Array) and isinstance(spec, P)
                and isinstance(getattr(leaf, "sharding", None),
                               NamedSharding)):
            return jax.device_put(leaf, NamedSharding(mesh, spec))
        return leaf

    # mapping over (state, specs): state's array leaves drive the flatten,
    # so each P entry of the spec tree arrives whole as `spec`
    return jax.tree.map(place, state, specs)


def elastic_restore(manager, engine, template: Any, *,
                    step: int | None = None) -> tuple[Any, dict | None]:
    """Restore a checkpoint onto ``engine``'s mesh, whatever mesh wrote it.

    ``template`` is a fresh ``engine.init_state`` product — it fixes the
    target structure, dtypes (via the engine's precision policy) and spec
    map.  Returns ``(state, extra)``: the restored TrainState placed under
    the target spec map, and the checkpoint's elastic sidecar (data state
    + save wall time; ``None`` for checkpoints that predate it —
    utils/checkpoint.py ``load_extra``).

    Precision crossings follow ``precision.restore_into_policy``: same
    policy restores directly, an f32-era checkpoint adopts into a master
    policy (restored f32 params become the master); other crossings raise.
    """
    policy = getattr(engine, "precision", None)
    if policy is None:
        policy = precisionlib.make_policy("f32")
    source = manager if step is None else _StepPinned(manager, step)
    try:
        state = precisionlib.restore_into_policy(source, template, policy)
    except Exception as e:
        mesh_shape = dict(engine.mesh.shape)
        raise ElasticRestoreError(
            f"elastic restore could not load the checkpoint under "
            f"{manager.directory} into this run's layout (target mesh "
            f"{mesh_shape}, precision '{policy.name}').  Cross-mesh "
            f"restore covers the GSPMD engine family (sync/allreduce, "
            f"fsdp, tensor-parallel and their composites), whose global "
            f"state shapes are mesh-independent; the per-device-stacked "
            f"engines (async/gossip) restore only onto the device count "
            f"they were saved from, and precision crossings other than "
            f"f32 → a master policy need the original --precision.  A "
            f"--health toggle across the resume boundary also changes the "
            f"optimizer tree (capture slots).  Original error: "
            f"{type(e).__name__}: {e}") from e
    specs = engine.state_partition_specs(template)
    state = place_under_spec_map(state, specs, engine.mesh)
    extra = manager.load_extra(step)
    return state, extra


def preemption_lost_s(extra: dict | None,
                      now: float | None = None) -> float | None:
    """Seconds between the restored checkpoint's save and this resume —
    the MLPerf time-to-quality cost of the preemption (nothing trained in
    that window counts; BASELINE.md "Preemption accounting").  ``None``
    when the checkpoint carries no save wall time (older builds) — "not
    measured" stays distinguishable from a measured 0."""
    wall = (extra or {}).get("wall_time")
    if not isinstance(wall, (int, float)) or isinstance(wall, bool):
        return None
    return max((time.time() if now is None else now) - float(wall), 0.0)
