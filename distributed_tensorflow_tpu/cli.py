"""L4 CLI/launcher — flag-compatible with the reference's initializer.py.

Reference surface (reference initializer.py:72-114):
  -m/--mode {c,centralized,d,decentralized}   -cs {sync,async}
  -ds {keras,graph,custom}   -n N   -b B   -tt {server,worker}   -ti I
  -sa ADDR   -ca {y,n}

Mapping to TPU-native engines (no processes are spawned — one SPMD program
owns all local devices; compare reference initializer.py:134-145 which forks
N+1 processes):

  -m c  -cs sync    → sync engine      (parameter-server sync semantics)
  -m c  -cs async   → async engine     (local SGD, periodic averaging)
  -m d  -ds keras   → allreduce engine (RING-allreduce semantics)
  -m d  -ds graph   → gossip engine    (implemented — ref raises
  -m d  -ds custom  → gossip engine     NotImplementedError, init.py:175-181)
  -m d  -ds fsdp    → fsdp engine      (ZeRO sharded params+optimizer — the
                                        ref's single-home optimizer,
                                        server.py:52-55, TPU-first)
  -m t/tpu_pod      → sync engine      (BASELINE.json north-star mode)

``-n`` selects TPU device count (BASELINE.json: "-n maps to device count");
``-b`` stays the per-worker batch, so the global batch is b×n like the
reference's aggregate.  ``-ca`` is accepted-and-ignored: core pinning
simulated "1 node = 1 core" (reference server.py:144-146), and a TPU device
*is* the node here.  ``-tt/-ti/-sa`` become `jax.distributed.initialize`
coordinates for real multi-host pods.
"""

from __future__ import annotations

import argparse
import json
import sys

from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, run


def parse_model_args(pairs: list[str]) -> dict:
    """KEY=VALUE list → kwargs dict with literal-ish value parsing."""
    out = {}
    for pair in pairs:
        if "=" not in pair:
            raise argparse.ArgumentTypeError(
                f"--model-arg expects KEY=VALUE, got '{pair}'")
        k, v = pair.split("=", 1)
        if v.lower() in ("true", "false"):
            out[k] = v.lower() == "true"
        else:
            for cast in (int, float):
                try:
                    out[k] = cast(v)
                    break
                except ValueError:
                    continue
            else:
                out[k] = v
    return out


def str2bool(v: str) -> bool:
    """Parity with reference str2bool (reference initializer.py:59-67)."""
    if isinstance(v, bool):
        return v
    if v.lower() in ("yes", "true", "t", "y", "1"):
        return True
    if v.lower() in ("no", "false", "f", "n", "0"):
        return False
    raise argparse.ArgumentTypeError("Boolean value expected.")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="distributed_tensorflow_tpu",
        description="TPU-native distributed training (reference-flag compatible)")
    p.add_argument("-m", "--mode", default="tpu_pod",
                   choices=["c", "centralized", "d", "decentralized", "t", "tpu_pod"])
    p.add_argument("-cs", "--centralized_strategy", default="sync",
                   choices=["sync", "async"])
    p.add_argument("-ds", "--decentralized_strategy", default="keras",
                   choices=["keras", "graph", "custom", "sync", "fsdp"])
    p.add_argument("-n", "--number_nodes", type=int, default=None,
                   help="TPU device count (default: all local devices)")
    p.add_argument("-b", "--batch_size", type=int, default=32,
                   help="per-worker batch; global batch = b × n")
    p.add_argument("-tt", "--task_type", default=None, choices=["server", "worker"],
                   help="multi-host role (server == coordinator host)")
    p.add_argument("-ti", "--task_index", type=int, default=0)
    p.add_argument("-sa", "--server_address", default=None,
                   help="coordinator address host:port for multi-host")
    p.add_argument("-ca", "--cpu_affinity", type=str2bool, nargs="?", const=True,
                   default=False, help="accepted for compatibility; no-op on TPU")
    # TPU-native additions
    p.add_argument("--model", default="mlp",
                   help="registered model name "
                        "(mlp|cnn|resnet20|bert_tiny|gpt|moe)")
    p.add_argument("--dataset", default="mnist",
                   help="mnist|fashion_mnist|cifar10|synthetic|glue_synth|"
                        "lm_synth")
    p.add_argument("-e", "--epochs", type=int, default=1,
                   help="reference hardwires 1 (SURVEY.md §2.4(6))")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--lr-schedule", default="constant",
                   choices=["constant", "cosine", "linear"],
                   help="LR schedule over epochs × steps-per-epoch; combine "
                        "with --warmup-steps for a linear ramp from 0")
    p.add_argument("--warmup-steps", type=int, default=0,
                   help="linear LR warmup steps (0 disables)")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="microbatches accumulated per optimizer step: ~K× "
                        "less activation memory at identical math.  "
                        "Composes with sync/allreduce/fsdp, -tp, fsdp×tp, "
                        "-sp, -ep, and the tp×sp/ep×sp composites; the "
                        "pipeline modes microbatch via --microbatches, and "
                        "the async/gossip engines reject it (their local "
                        "steps already decouple optimizer cadence)")
    p.add_argument("--weight-decay", type=float, default=0.0,
                   help=">0: AdamW decoupled weight decay")
    p.add_argument("--clip-norm", type=float, default=0.0,
                   help=">0: clip gradients to this global norm before the "
                        "update")
    p.add_argument("--sync-every", type=int, default=10,
                   help="async engine: parameter-averaging period")
    p.add_argument("-d", "--degree", type=int, default=1,
                   help="gossip neighbor degree (the reference's commented-out "
                        "-d flag, initializer.py:90-92)")
    p.add_argument("--num-processes", type=int, default=None,
                   help="multi-host: total process count")
    p.add_argument("-sp", "--seq-parallel", type=int, default=1,
                   help="shard sequences over this many devices (long-context "
                        "mode; requires a sequence model, e.g. --model bert_tiny)")
    p.add_argument("--attention", default="ring",
                   choices=["ring", "ring_flash", "ulysses", "ulysses_flash", "flash"],
                   help="attention strategy: ring/ring_flash/ulysses/"
                        "ulysses_flash shard the sequence over -sp devices "
                        "(the *_flash variants run the Pallas flash kernel "
                        "as the local math inside the ring / Ulysses "
                        "communication schedule); flash = single-device "
                        "Pallas kernel, valid only with -sp 1 (sequence "
                        "models)")
    p.add_argument("--positional", default="learned",
                   choices=["learned", "rope"],
                   help="GPT position encoding: learned table | RoPE "
                        "(rotary, no table — q/k rotated by position)")
    p.add_argument("--kv-heads", type=int, default=None,
                   help="GPT grouped-query attention: K/V head count "
                        "(< --heads; 1 = multi-query).  Shrinks the decode "
                        "KV cache by heads/kv_heads")
    p.add_argument("--remat", action="store_true",
                   help="activation checkpointing: store each transformer "
                        "block's input only, recompute the block in "
                        "backward (~K x less activation memory for ~1/3 "
                        "more FLOPs).  The long-context memory lever.  "
                        "Sequence models only; under pipelines it bounds "
                        "the GPipe tick stash but is a documented no-op "
                        "for --pipeline-schedule 1f1b (the 1F1B stash is "
                        "already bounded at S slots)")
    p.add_argument("--sample", type=int, default=0, metavar="N",
                   help="after training a GPT LM, greedy-decode N tokens "
                        "per prompt from the final params (KV-cache "
                        "sampler, multi-device over the run's mesh; under "
                        "--pipeline-parallel a sequential-forward decode "
                        "over the pipe-stacked stages — dense-FFN stages "
                        "only, MoE stages are rejected with the routing-"
                        "capacity reason) and record prompts+continuations "
                        "in the summary")
    p.add_argument("--sample-prompt-len", type=int, default=8,
                   help="prompt tokens taken from the test split per "
                        "sampled row (--sample)")
    p.add_argument("--serve", type=int, default=0, metavar="N",
                   help="after training a GPT LM, run a continuous-"
                        "batching serving window of N requests through "
                        "the slot-based KV cache + in-flight scheduler "
                        "(distributed_tensorflow_tpu/serving/): requests "
                        "queue into --serve-slots slots, finished slots "
                        "are evicted and refilled between decode "
                        "iterations, and the summary/run report gain a "
                        "'serve' section (requests/sec/chip, TTFT/ITL "
                        "p50/p95 — gated by `analyze diff` like the "
                        "training metrics).  Per-request request/prefill/"
                        "decode spans ride --trace")
    p.add_argument("--serve-slots", type=int, default=4,
                   help="--serve: KV slot table size (requests decoded "
                        "in flight at once; shards over the 'data' mesh "
                        "axis when divisible)")
    p.add_argument("--serve-max-new", type=int, default=16,
                   help="--serve: tokens generated per request")
    p.add_argument("--serve-prompt-len", type=int, default=8,
                   help="--serve: prompt tokens taken from the test "
                        "split per request")
    p.add_argument("--serve-kv-dtype", default=None,
                   choices=["float32", "f32", "bfloat16", "bf16", "int8"],
                   help="--serve: KV slot-table storage dtype (default: "
                        "the model's dtype).  bfloat16 halves the KV "
                        "memory per slot — double the serving slots per "
                        "chip at equal HBM; greedy tokens stay oracle-"
                        "exact on the shipped models.  int8 halves "
                        "bf16's payload again (int8 K/V + one f32 "
                        "max-abs scale per written vector, dequantized "
                        "on the attention read) — token parity vs the "
                        "bf16 oracle is tolerance-based, not bitwise.  "
                        "The dtype and serve_kv_bytes_per_slot ride the "
                        "serve report section (gated by `analyze diff`)")
    p.add_argument("--serve-draft-config", default=None, metavar="SPEC",
                   help="--serve: speculative decoding — a draft GPT "
                        "proposes --serve-draft-k tokens per live slot, "
                        "the served model verifies all k+1 positions in "
                        "ONE batched step, and greedy acceptance keeps "
                        "the emitted stream BITWISE identical to non-"
                        "speculative decode.  SPEC is 'self' (draft = "
                        "the served model + params; accept rate 1) or "
                        "'hidden=64,layers=1,...' GPT size overrides "
                        "(vocab/max_len inherited, fresh-initialized "
                        "from --seed).  Default off: the pre-round-14 "
                        "programs, byte-identical")
    p.add_argument("--serve-draft-k", type=int, default=4, metavar="K",
                   help="--serve-draft-config: draft tokens proposed per "
                        "verify round (capped per round by slot capacity "
                        "and remaining request budgets).  The serve "
                        "section carries serve_accept_rate + the "
                        "proposed/accepted/rejected ledger")
    p.add_argument("--serve-prefill-chunk", type=int, default=0,
                   metavar="T",
                   help="--serve: chunked prefill token budget (Sarathi-"
                        "Serve): admissions prefill in chunks of ≤T "
                        "tokens, at most one chunk per decode iteration, "
                        "so a long prompt cannot stall live slots for "
                        "more than one chunk per token.  0 (default) = "
                        "monolithic prefill (the pre-round-10 programs, "
                        "byte-identical).  Greedy tokens are identical "
                        "either way; TTFT stays arrival→first-token")
    p.add_argument("--serve-prefix-cache", type=int, default=0,
                   metavar="BLOCKS",
                   help="--serve: prefix-cache pool capacity in KV "
                        "blocks (vLLM-style block-granular reuse).  On "
                        "admission the longest cached block-aligned "
                        "prompt prefix is copied into the slot and "
                        "prefill starts at the first uncached block; "
                        "LRU eviction past the bound.  0 (default) = "
                        "off.  hit/miss/evict accounting + "
                        "serve_prefix_cache_hit_rate ride the serve "
                        "section (gated by `analyze diff`)")
    p.add_argument("--serve-prefix-block", type=int, default=16,
                   metavar="T",
                   help="--serve: tokens per prefix-cache block (reuse "
                        "granularity; only full blocks are pooled)")
    p.add_argument("--serve-kv-layout", default="monolithic",
                   choices=["monolithic", "paged"],
                   help="--serve: KV storage layout.  'paged' swaps the "
                        "per-slot max_len rows for ONE refcounted "
                        "physical block pool + per-slot block tables "
                        "(vLLM PagedAttention): prefix-cache hits alias "
                        "pooled blocks by pointer (zero KV bytes "
                        "copied), first write into a shared block "
                        "copies on write, and decode/verify read "
                        "through the table in one fused Pallas kernel "
                        "(in-kernel int8 dequant; token parity vs the "
                        "monolithic oracle is tolerance-based — the "
                        "attention-reassociation caveat, like int8).  "
                        "Default 'monolithic' keeps the pre-round-16 "
                        "programs byte-identical")
    p.add_argument("--serve-paged-block", type=int, default=0,
                   metavar="T",
                   help="--serve-kv-layout paged: tokens per physical "
                        "KV block.  0 (default) inherits --serve-prefix-"
                        "block; with the prefix pool on the two must "
                        "agree (hits alias physical blocks by pointer)")
    p.add_argument("--serve-paged-blocks", type=int, default=0,
                   metavar="N",
                   help="--serve-kv-layout paged: physical block-pool "
                        "capacity.  0 (default) auto-sizes so every "
                        "slot can reach max_len and the prefix pool can "
                        "pin its bound — never exhausts; smaller "
                        "explicit pools defer admissions "
                        "(serve_kv_block_deferrals) when the free list "
                        "cannot cover a request's worst-case need")
    p.add_argument("--serve-shared-prefix", type=int, default=0,
                   metavar="T",
                   help="--serve: prepend a fixed T-token synthetic "
                        "system prompt to every request (the dominant "
                        "real-traffic shape prefix caching exists for); "
                        "deterministic from --seed")
    p.add_argument("--serve-slo-ttft", type=float, default=2.0,
                   metavar="S",
                   help="--serve: TTFT SLO target in seconds — a request "
                        "is goodput only when arrival→first-token (queue "
                        "wait included) meets this AND the ITL target; "
                        "the serve section carries "
                        "serve_goodput_under_slo (gated higher-is-better "
                        "by `analyze diff`)")
    p.add_argument("--serve-slo-itl", type=float, default=0.5,
                   metavar="S",
                   help="--serve: inter-token-latency SLO target in "
                        "seconds, judged at each request's own p99 gap")
    p.add_argument("--serve-queue-cap", type=int, default=0,
                   metavar="N",
                   help="--serve: bounded admission — cap the arrived-"
                        "but-unadmitted backlog at N requests; excess "
                        "sheds with 429 accounting (shed_requests / "
                        "serve_shed_rate + a structured `overload` trace "
                        "event) so overload degrades to bounded queue "
                        "wait instead of unbounded TTFT (0 = admit "
                        "everything)")
    p.add_argument("--serve-replicas", type=int, default=1, metavar="N",
                   help="--serve: run the window through a ReplicaSet "
                        "fleet of N continuous-batching replicas "
                        "(serving/fleet.py), each with its own "
                        "--serve-slots KV table, behind a least-loaded "
                        "router.  A replica failure (crash, watchdog "
                        "stall, detected corruption) requeues its queued "
                        "AND in-flight requests to survivors with "
                        "bounded retry — already-streamed tokens are "
                        "never re-emitted (journal fence; resume "
                        "re-prefills prompt+emitted prefix, greedy-"
                        "exact) and retry TTFT stays charged from the "
                        "original arrival.  The serve section gains "
                        "serve_fleet + serve_failover_recovery_p95_s / "
                        "serve_duplicate_emissions (gated by `analyze "
                        "diff`).  1 (default) = the single-replica "
                        "batcher, byte-identical behavior")
    p.add_argument("--serve-fault-spec", default=None, metavar="SPEC",
                   help="--serve: seeded fault injection into the fleet "
                        "(forces fleet supervision even at 1 replica). "
                        "SPEC is 'kind:key=val,...[;kind:...]' with kind "
                        "crash|stall|nanlogits and keys replica=N plus "
                        "iter=K (K-th decode iteration) / prefill=K / "
                        "verify=K (crash between verify and commit) / "
                        "prob=P (seeded Bernoulli) / stall_s=S.  E.g. "
                        "'crash:replica=0,iter=3'.  The chaos-test "
                        "substrate: every offered request must still "
                        "complete exactly once on the survivors.  NB "
                        "stall faults are only DETECTED (fenced + failed "
                        "over) when --serve-watchdog is set; without it "
                        "the stall just runs its course")
    p.add_argument("--serve-watchdog", type=float, default=0.0,
                   metavar="S",
                   help="--serve-replicas: supervisor watchdog — fail "
                        "over a replica that made no token progress for "
                        "S seconds while busy (the zombie is FENCED, "
                        "not killed: its late emissions are rejected by "
                        "the journal).  Set S above worst-case first-"
                        "program compile time — the watchdog cannot "
                        "tell a stall from an XLA compile.  0 (default) "
                        "= off")
    p.add_argument("--serve-hot-swap", action="store_true",
                   help="--serve: zero-downtime weight hot-swap drill — "
                        "after half the window completes, each replica "
                        "in turn stops admitting, finishes in-flight, "
                        "swaps the served params between compiled-"
                        "program dispatches (never recompiles, fleet "
                        "never below N-1 admitting replicas) and "
                        "resumes; swap_generations >= 1 in serve_fleet "
                        "proves it.  The drill re-installs the same "
                        "trained params so greedy tokens are unchanged; "
                        "a real rollout passes a new checkpoint")
    p.add_argument("--serve-disaggregate", default=None, metavar="P:D",
                   help="--serve: disaggregated prefill/decode fleet — "
                        "P prefill replicas (admission + chunked "
                        "prefill only) hand finished KV to D decode "
                        "replicas via serialized-block transfer "
                        "(extract_handoff/restore_handoff; works for "
                        "monolithic and paged layouts, int8 scales "
                        "ride along), so decode replicas never share "
                        "an iteration with a long prompt.  Overrides "
                        "--serve-replicas with P+D; the prefix pool "
                        "stays prefill-side.  TTFT is still charged "
                        "arrival -> first token INCLUDING the handoff. "
                        "The serve section gains serve_disagg (handoff "
                        "+ per-role conservation counters)")
    p.add_argument("--serve-routing", default="least-loaded",
                   choices=("least-loaded", "affinity"),
                   help="--serve: fleet router policy.  'affinity' "
                        "keys each request on its first prefix-block "
                        "digest (the prefix pool's chained SHA-256 "
                        "keys) and routes repeats to the replica whose "
                        "pool is already warm, falling back to least-"
                        "loaded for new/short prompts; the serve "
                        "section gains serve_fleet_prefix_hit_rate "
                        "(needs --serve-prefix-cache > 0).  Default "
                        "'least-loaded' is the round-17 router, "
                        "byte-identical")
    p.add_argument("--serve-autoscale", default=None, metavar="MIN:MAX",
                   help="--serve: queue-driven autoscaling — the fleet "
                        "starts MIN serving replicas (the rest of "
                        "--serve-replicas dormant: KV allocated, no "
                        "requests routed) and wakes one when arrived "
                        "queue depth crosses the high-watermark, "
                        "draining one back down when idle.  MAX caps "
                        "serving replicas (0 = fleet size); MAX must "
                        "fit inside --serve-replicas.  The serve "
                        "section gains autoscale (scale events) + "
                        "serve_replica_seconds, the efficiency ledger "
                        "`analyze diff` gates lower-is-better.  "
                        "Composes with --serve-disaggregate: the "
                        "MIN:MAX range drives each role pool "
                        "independently (clamped to the pool's size) "
                        "and serve_replica_seconds splits per role")
    p.add_argument("--serve-multi-step", type=int, default=None,
                   metavar="K",
                   help="--serve: fuse K decode iterations into one "
                        "device dispatch (on-device token feedback + "
                        "EOS/budget deactivation under lax.scan) and "
                        "pipeline the next round's dispatch ahead of "
                        "the current round's token materialization.  "
                        "Greedy streams are bitwise identical to K=1; "
                        "admissions wait at most K fused iterations "
                        "(the staleness trade).  The serve section "
                        "gains serve_dispatches + serve_host_gap_s "
                        "(both gated lower-is-better by `analyze "
                        "diff`).  Default None keeps the per-iteration "
                        "loop, program- and key-identical to round 19")
    p.add_argument("--model-arg", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="extra model constructor field (repeatable), e.g. "
                        "--model-arg hidden=256 --model-arg layers=4; "
                        "values parse as int/float/bool when they look "
                        "like one, else string")
    p.add_argument("-tp", "--tensor-parallel", type=int, default=1,
                   help="shard weight matrices over this many devices "
                        "(Megatron-style TP; MLP family)")
    p.add_argument("-pp", "--pipeline-parallel", type=int, default=1,
                   help="shard model stages over this many devices "
                        "(GPipe-style microbatched pipeline)")
    p.add_argument("--microbatches", type=int, default=4,
                   help="pipeline microbatches per step (bubble = (S-1)/(M+S-1))")
    p.add_argument("--pipeline-schedule", default="gpipe",
                   choices=["gpipe", "1f1b"],
                   help="gpipe: all-fwd-then-all-bwd (AD through the scan); "
                        "1f1b: interleaved fwd/bwd with a fixed S-slot "
                        "activation stash (PipeDream-flush)")
    p.add_argument("--pipeline-hidden", type=int, default=128,
                   help="pipeline stage hidden width")
    p.add_argument("-ep", "--expert-parallel", type=int, default=1,
                   help="shard MoE experts over this many devices "
                        "(GShard/Switch-style EP; --model moe)")
    p.add_argument("--num-experts", type=int, default=8,
                   help="MoE expert count (must divide by -ep)")
    p.add_argument("--aux-weight", type=float, default=0.01,
                   help="MoE load-balance auxiliary loss weight")
    p.add_argument("--router-top-k", type=int, default=1, choices=[1, 2],
                   help="MoE routing: 1 = Switch top-1, 2 = GShard top-2 "
                        "(renormalized gates, priority capacity positions)")
    p.add_argument("--router-z-weight", type=float, default=0.0,
                   help="MoE router z-loss weight (0 disables; ~1e-3 "
                        "stabilizes router logits on long runs)")
    p.add_argument("--grad-compression", default="none",
                   choices=["none", "bf16", "int8"],
                   help="compress the cross-device gradient/parameter "
                        "exchange (parallel/compression.py): bf16 halves "
                        "the collective wire bytes (the exchange runs in "
                        "bf16, widened to f32 after), int8 quarters them "
                        "(per-leaf scale + "
                        "stochastic rounding, f32 master params kept); "
                        "none is bitwise identical to the uncompressed "
                        "path.  Data-parallel and GSPMD engines; the "
                        "pipeline schedules reject it")
    p.add_argument("--precision", default="f32",
                   choices=["f32", "bf16", "bf16-f32master",
                            "fp16-f32master"],
                   help="end-to-end mixed-precision policy "
                        "(parallel/precision.py): param STORAGE + compute "
                        "+ grad-reduce dtypes, distinct from --dtype "
                        "(activations only; a non-f32 policy owns the "
                        "model dtype).  bf16: pure bfloat16 — params AND "
                        "optimizer state halve.  bf16-f32master: bf16 "
                        "storage/compute with a float32 master copy "
                        "inside the optimizer state (the Micikevicius "
                        "mixed-precision recipe) — param bytes halve, "
                        "updates below bf16 resolution still accumulate. "
                        "fp16-f32master: float16 + master + dynamic loss "
                        "scaling (overflow steps are skipped and the "
                        "scale backs off; pair with --health on for the "
                        "anomaly guard).  f32 (default) compiles the "
                        "byte-identical pre-policy programs.  Pipeline "
                        "modes reject non-f32 policies")
    p.add_argument("--grad-bucket-mb", type=float, default=0.0,
                   metavar="MB",
                   help="communication/compute overlap: partition the "
                        "gradient pytree into ~MB-sized buckets in "
                        "reverse-backward order (parallel/overlap.py) so "
                        "each bucket's collective — composed with "
                        "--grad-compression, which then codes per bucket "
                        "— is schedulable behind the remaining backward "
                        "compute (XLA latency-hiding flags are enabled "
                        "on TPU; with --grad-accum K > 1 each "
                        "microbatch's reduce also overlaps the next "
                        "microbatch's backward).  ~4 recommended; 0 "
                        "(default) compiles the exact pre-overlap "
                        "programs.  The run measures and reports the "
                        "exposed-vs-hidden collective split "
                        "(grad_collective_exposed_s); pipeline modes "
                        "reject the flag")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "(jax_compilation_cache_dir): repeat runs and "
                        "bench warmups skip recompiles of unchanged "
                        "programs")
    p.add_argument("--steps-per-call", type=int, default=None,
                   help="steady-state drain: training steps rolled into one "
                        "jitted lax.scan per host dispatch (README "
                        "'steady-state performance').  Default auto: 8, "
                        "downshifting to 1 only for a steps-to-target run "
                        "(its ≤10-step eval resolution needs boundary "
                        "state every step); telemetry (--metrics-path, "
                        "--trace, --watchdog-timeout) rides the chunked "
                        "drain without downshifting")
    p.add_argument("--prefetch", type=int, default=2,
                   help="device-prefetch depth: host batches staged onto "
                        "the mesh this many steps ahead so transfer N+1 "
                        "overlaps compute N (data/device_prefetch.py)")
    p.add_argument("--result-path", default=None, help="JSONL event sink path")
    p.add_argument("--supervisor", default=None, metavar="HOST[:PORT]",
                   help="report the reference's start/done/results event "
                        "triple to an external supervisor socket (reference "
                        "server.py:121-124; port defaults to 4000).  Distinct "
                        "from -sa, which is the multi-host coordinator")
    p.add_argument("--log-every", type=int, default=50)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--checkpoint-dir", default=None,
                   help="enable TrainState checkpointing to this directory")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="steps between checkpoints (0: final only)")
    p.add_argument("--async-checkpoint", default="on", choices=["on", "off"],
                   dest="async_checkpoint",
                   help="'on' (default): checkpoint saves cost the training "
                        "thread only a device snapshot — the device→host "
                        "transfer, atomic Orbax write and retention sweep "
                        "run on a background writer thread, overlapped with "
                        "the next training chunks (at most one save in "
                        "flight; writer errors re-raise at the next "
                        "checkpoint).  'off': the previous synchronous "
                        "blocking-save path, bit-for-bit — same on-disk "
                        "format, restorable either way")
    p.add_argument("--resume", action="store_true",
                   help="restore the latest checkpoint before training")
    p.add_argument("--elastic-restore", action="store_true",
                   help="mesh-shape-independent resume (elastic/"
                        "reshard.py): restore the latest checkpoint onto "
                        "THIS run's mesh whatever mesh wrote it — device "
                        "count and axis layout may both differ within the "
                        "GSPMD engine family — continue the exact batch "
                        "sequence from the checkpoint's data state "
                        "(exactly-once resume; a pre-elastic checkpoint "
                        "restarts the stream with a resume_replay_steps "
                        "warning), and report preemption_lost_s / "
                        "resume_replay_steps in the run report (gated by "
                        "`analyze diff`)")
    p.add_argument("--max-steps-per-lease", type=int, default=0,
                   metavar="N",
                   help="graceful lease drain (elastic/lease.py): stop at "
                        "the first chunk boundary at/after N steps, write "
                        "the final checkpoint (data state included) and "
                        "exit with a structured `preempted` report "
                        "section — relaunch with --elastic-restore to "
                        "continue.  Checkpointed runs also drain on "
                        "SIGTERM (the scheduler's preemption notice) "
                        "whether or not N is set.  Requires "
                        "--checkpoint-dir")
    p.add_argument("--metrics-path", "--metrics", default=None,
                   dest="metrics_path",
                   help="per-step metrics JSONL path (async crash-durable "
                        "sink; records ride the multi-step scan drain, so "
                        "this no longer downshifts --steps-per-call)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="structured trace-span JSONL path: a monotonic-"
                        "clock timeline of compile/chunk_dispatch/"
                        "materialize/checkpoint/eval spans plus prefetch "
                        "gauges, with run/host/process ids (README "
                        "'Observability'); span names are mirrored into "
                        "XProf when --profile-dir is also set")
    p.add_argument("--timeline", action="store_true",
                   help="time-series gauge sampler + XLA program ledger "
                        "(README 'Timeline & memory observability'): queue "
                        "depth / KV blocks / replica load series sampled at "
                        "existing loop boundaries (bounded rings, "
                        "self-measured overhead), plus per-compiled-program "
                        "memory_analysis and compile wall-time in the run "
                        "report ('xla' section, peak_hbm_bytes_est / "
                        "compile_total_s).  Host-side only — off compiles "
                        "the exact pre-timeline program set.  Renders "
                        "offline via `analyze timeline` / "
                        "`analyze programs` and as Perfetto counter tracks")
    p.add_argument("--timeline-interval", type=float, default=0.05,
                   metavar="SECONDS",
                   help="minimum seconds between --timeline samples per "
                        "gauge group (default 0.05; 0 = record every "
                        "boundary crossing)")
    p.add_argument("--roofline", action="store_true",
                   help="roofline efficiency ledger (README 'Roofline & "
                        "efficiency accounting'): analytic model FLOPs/"
                        "bytes cost model + device peak table → train_mfu "
                        "on the fit result, serve_prefill_mfu / "
                        "serve_decode_mbu on the serve summary, and a "
                        "per-compiled-program intensity/bound attribution "
                        "table in the run report ('roofline' section; "
                        "renders offline via `analyze roofline`).  On an "
                        "unknown device kind utilizations report null — a "
                        "peak is never invented.  Host-side only — off "
                        "keeps the program set and every summary/report "
                        "key set byte-identical")
    p.add_argument("--profile-dir", default=None,
                   help="write an XLA profiler trace here (TensorBoard/XProf)")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "f32", "bfloat16", "bf16"],
                   help="model compute dtype; bfloat16 = mixed precision "
                        "(f32 params, bf16 activations on the MXU)")
    p.add_argument("--watchdog-timeout", type=float, default=0.0,
                   help=">0: detect a stalled step loop (no progress for this "
                        "many seconds PER STEP) and emit a 'stall' event — "
                        "the reference deadlocks silently instead.  Under "
                        "--steps-per-call k the loop beats once per chunk "
                        "and the stall budget scales to k × this value")
    p.add_argument("--watchdog-abort", action="store_true",
                   help="on stall, exit(75) after reporting so a supervisor "
                        "can relaunch with --resume (a wedged XLA runtime "
                        "cannot be recovered in-process)")
    p.add_argument("--health", default="off", choices=["off", "on"],
                   help="per-step numeric training-health stats, computed "
                        "ON DEVICE inside the jitted scan and stacked "
                        "through the trajectory like metrics (zero "
                        "downshift): global grad norm, param norm, update "
                        "ratio, non-finite leaf count, loss-spike score "
                        "vs a running EMA (observability/health.py).  "
                        "They ride --metrics-path records and feed "
                        "--on-anomaly; 'off' (default) compiles the exact "
                        "pre-health program")
    p.add_argument("--on-anomaly", default="warn", choices=["warn", "halt"],
                   dest="on_anomaly",
                   help="with --health on: response to a per-step health "
                        "anomaly (non-finite params/grads, update-ratio "
                        "ceiling, loss spike) — 'warn' records structured "
                        "anomaly trace events and a health summary, "
                        "'halt' additionally stops at the offending step. "
                        " Subsumes the loss-only nan guard (README "
                        "'Health monitoring')")
    p.add_argument("--no-nan-guard", action="store_true",
                   help="disable the fatal divergence (NaN/inf) response: "
                        "without --health, skips the legacy loss-only "
                        "check; with --health on + --on-anomaly warn, "
                        "downgrades nonfinite anomalies (which stay fatal "
                        "by default) to record-and-continue")
    p.add_argument("--max-restarts", type=int, default=0,
                   help=">0: on crash, restart from the latest checkpoint up "
                        "to N times (requires --checkpoint-dir + "
                        "--checkpoint-every)")
    return p


def select_engine(args: argparse.Namespace) -> str:
    if args.mode in ("c", "centralized"):
        return "sync" if args.centralized_strategy == "sync" else "async"
    if args.mode in ("d", "decentralized"):
        if args.decentralized_strategy in ("graph", "custom"):
            return "gossip"
        if args.decentralized_strategy in ("sync", "fsdp"):
            return args.decentralized_strategy
        return "allreduce"
    return "sync"  # tpu_pod


def _honor_platform_env() -> None:
    """Re-assert the user's JAX platform choice over preloaded plugins.

    The package __init__ already runs this at import time (see
    distributed_tensorflow_tpu._honor_platform_env — the single
    definition); main() re-asserts for belt-and-braces in embedding
    scenarios where the host process imported jax (but initialized no
    backend) before setting the env vars and importing us."""
    from distributed_tensorflow_tpu import _honor_platform_env as _honor

    _honor()


def main(argv: list[str] | None = None, *, model_fn=None,
         dataset_fn=None) -> dict:
    """CLI entry.  ``model_fn``/``dataset_fn`` are the reference's user
    plug-in contract (reference README.md:12: "edit model_fn/dataset_fn in
    initializer.py"): when provided they override --model/--dataset."""
    _honor_platform_env()
    parser = build_parser()
    args = parser.parse_args(argv)

    try:
        model_args = parse_model_args(args.model_arg)
    except argparse.ArgumentTypeError as bad:
        parser.error(str(bad))  # clean usage error + exit 2, not a traceback

    if (args.task_type is None) != (args.server_address is None):
        # the reference dispatches on task_type alone (reference
        # initializer.py:147-155); silently running single-process when one
        # half of the pair is missing would mask a misconfigured pod
        parser.error("-tt/--task_type and -sa/--server_address must be "
                     "given together for a multi-host run")

    if args.task_type is not None and args.server_address is not None:
        # multi-host pod: same SPMD program on every host, coordinated by
        # process 0 — replaces the reference's role-per-machine dispatch
        # (reference initializer.py:147-155)
        from distributed_tensorflow_tpu.parallel import mesh as meshlib

        # process 0 is the coordinator ('server' role); worker i maps to
        # process i+1, so '-tt worker -ti 0' does not collide with the server
        meshlib.multihost_initialize(
            coordinator_address=args.server_address,
            num_processes=args.num_processes,
            process_id=args.task_index + 1 if args.task_type == "worker" else 0,
        )

    config = ExperimentConfig(
        engine=select_engine(args),
        model=args.model,
        dataset=args.dataset,
        model_fn=model_fn,
        dataset_fn=dataset_fn,
        n_devices=args.number_nodes,
        batch_size=args.batch_size,
        epochs=args.epochs,
        learning_rate=args.lr,
        lr_schedule=args.lr_schedule,
        warmup_steps=args.warmup_steps,
        grad_accum=args.grad_accum,
        grad_compression=args.grad_compression,
        precision=args.precision,
        grad_bucket_mb=args.grad_bucket_mb,
        compile_cache=args.compile_cache,
        weight_decay=args.weight_decay,
        clip_norm=args.clip_norm,
        sync_every=args.sync_every,
        degree=args.degree,
        seed=args.seed,
        log_every=args.log_every,
        steps_per_call=args.steps_per_call,
        prefetch=args.prefetch,
        result_path=args.result_path,
        supervisor_address=args.supervisor,
        seq_parallel=args.seq_parallel,
        attention_impl=args.attention,
        positional=args.positional,
        kv_heads=args.kv_heads,
        remat=args.remat,
        model_args=model_args,
        tensor_parallel=args.tensor_parallel,
        pipeline_parallel=args.pipeline_parallel,
        microbatches=args.microbatches,
        pipeline_schedule=args.pipeline_schedule,
        pipeline_hidden=args.pipeline_hidden,
        expert_parallel=args.expert_parallel,
        num_experts=args.num_experts,
        aux_weight=args.aux_weight,
        router_top_k=args.router_top_k,
        router_z_weight=args.router_z_weight,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        async_checkpoint=args.async_checkpoint == "on",
        resume=args.resume,
        elastic_restore=args.elastic_restore,
        max_steps_per_lease=args.max_steps_per_lease,
        metrics_path=args.metrics_path,
        trace_path=args.trace,
        timeline=args.timeline,
        timeline_interval=args.timeline_interval,
        roofline=args.roofline,
        profile_dir=args.profile_dir,
        dtype=args.dtype,
        watchdog_timeout=args.watchdog_timeout,
        watchdog_abort=args.watchdog_abort,
        nan_guard=not args.no_nan_guard,
        health=args.health,
        on_anomaly=args.on_anomaly,
        max_restarts=args.max_restarts,
        sample_tokens=args.sample,
        sample_prompt_len=args.sample_prompt_len,
        serve_requests=args.serve,
        serve_slots=args.serve_slots,
        serve_max_new=args.serve_max_new,
        serve_prompt_len=args.serve_prompt_len,
        serve_kv_dtype=args.serve_kv_dtype,
        serve_prefill_chunk=args.serve_prefill_chunk,
        serve_prefix_cache=args.serve_prefix_cache,
        serve_prefix_block=args.serve_prefix_block,
        serve_shared_prefix=args.serve_shared_prefix,
        serve_slo_ttft=args.serve_slo_ttft,
        serve_slo_itl=args.serve_slo_itl,
        serve_queue_cap=args.serve_queue_cap,
        serve_draft_config=args.serve_draft_config,
        serve_draft_k=args.serve_draft_k,
        serve_replicas=args.serve_replicas,
        serve_fault_spec=args.serve_fault_spec,
        serve_hot_swap=args.serve_hot_swap,
        serve_watchdog_s=args.serve_watchdog,
        serve_kv_layout=args.serve_kv_layout,
        serve_paged_block=args.serve_paged_block,
        serve_paged_blocks=args.serve_paged_blocks,
        serve_disaggregate=args.serve_disaggregate,
        serve_routing=args.serve_routing,
        serve_autoscale=args.serve_autoscale,
        serve_multi_step=args.serve_multi_step,
    )
    summary = run(config)  # run() itself wraps recovery when max_restarts>0
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main()
