"""Offline analysis of the telemetry streams: the read side of PR 2's
write side.  Nothing in the repo could read the JSONL files back until now
— this module (and its CLI, ``python -m
distributed_tensorflow_tpu.observability.analyze``) turns them into
answers:

  spans TRACE.jsonl        span aggregation + stall/starvation summary
  export TRACE.jsonl -o F  Chrome-trace-event JSON — load F in Perfetto
                           (https://ui.perfetto.dev) or chrome://tracing
  health METRICS.jsonl     health timeline: first anomaly step, stat maxima
  serve TRACE.jsonl        per-request serving waterfall
                           (queue→prefill-chunks→decode) from the
                           scheduler's request/prefill_chunk spans +
                           overload shed events; --text renders bars
  diff BASE NEW            run-vs-run regression diff of two run reports
                           (or BENCH_*.json lines); exits nonzero iff a
                           metric regressed beyond --threshold
  timeline TRACE.jsonl     the --timeline gauge series (queue depth, KV
                           blocks, replica load, chunk step time) rendered
                           as text sparklines per series — per-replica
                           lanes grouped — from the trace file alone;
                           --json emits the exact summaries instead
  programs REPORT          the --timeline XLA program ledger: per-program
                           memory_analysis bytes + compile seconds (and
                           the round-19 cost_analysis flops/bytes
                           columns); with --against BASE it becomes the
                           drift gate — exit nonzero when the program set
                           grew or a program's temp bytes grew past
                           --temp-threshold (flops growth warns)
  roofline REPORT          the --roofline attribution table: per-program
                           arithmetic intensity, compute/bandwidth bound
                           and attainable %-of-peak, plus the run's
                           train_mfu / serve_decode_mbu headline — from a
                           run report or a bare manifest; --device/--dtype
                           override the peak lookup, --json for JSON

Inputs are whatever the sinks wrote: a trace JSONL (``--trace``), a metrics
JSONL (``--metrics-path``), a result JSONL (``--result-path``), the
harness's printed summary, or a ``bench.py`` line.  ``load_report`` accepts
any of them — for multi-line files the LAST parsable JSON object wins (the
summary/bench line), and a ``run_report`` found inside a summary is
flattened into the comparison.

Deliberately stdlib-only (json/math/argparse): the analyzer must run
anywhere the JSONL files land — a laptop, a CI step — without importing
jax or initializing any backend.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from pathlib import Path
from typing import Any, Iterable

# sibling pure-host modules (no jax, no backend init — same portability
# contract as this file): the timeline ring buffer/sparkline renderer and
# the program-manifest differ are the read side's data structures
from distributed_tensorflow_tpu.observability.timeline import (
    GaugeSeries, sparkline)
from distributed_tensorflow_tpu.observability.xla_stats import diff_manifests


def read_jsonl(path: str | Path) -> list[dict]:
    """Parse a JSONL stream.  The sink's crash-durability contract is
    whole-lines-only, so every non-empty line must parse; a torn line is a
    real error, not something to paper over."""
    records = []
    for i, line in enumerate(Path(path).read_text().splitlines(), 1):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}:{i}: unparsable JSONL line "
                             f"({e.msg})") from e
    return records


# ------------------------------------------------------------ span summary

def span_aggregate(records: Iterable[dict]) -> dict[str, dict[str, float]]:
    """Per-name {count, total_s, max_s, mean_s} over the span records —
    the offline twin of Tracer.span_summary (which only exists while the
    run's process is alive)."""
    agg: dict[str, list] = {}
    for rec in records:
        if rec.get("event") != "span":
            continue
        a = agg.setdefault(rec["name"], [0, 0.0, 0.0])
        a[0] += 1
        a[1] += float(rec.get("dur_s", 0.0))
        a[2] = max(a[2], float(rec.get("dur_s", 0.0)))
    return {name: {"count": c, "total_s": tot, "max_s": mx,
                   "mean_s": tot / c if c else 0.0}
            for name, (c, tot, mx) in sorted(agg.items())}


def trace_summary(records: list[dict]) -> dict[str, Any]:
    """Everything the trace stream can answer offline: the span table, a
    wall-clock estimate, counter totals, and the stall/starvation story
    (prefetch queue-depth gauges, anomaly events, stall events)."""
    spans = span_aggregate(records)
    ts = [float(r["t"]) for r in records if "t" in r]
    ends = [float(r["t"]) + float(r.get("dur_s", 0.0))
            for r in records if "t" in r]
    gauges = [r for r in records if r.get("event") == "gauge"
              and r.get("name") == "prefetch_depth"]
    counters: dict[str, int] = {}
    for r in records:
        if r.get("event") == "counter":
            counters[r["name"]] = r.get("total", 0)
    anomalies = [r for r in records if r.get("event") == "event"
                 and r.get("name") == "anomaly"]
    # serving overload: one `overload` event per shed (429'd) request —
    # surfaced here so `analyze spans` answers "did admission control
    # engage" without a separate tool
    overloads = [r for r in records if r.get("event") == "event"
                 and r.get("name") == "overload"]
    # dispatch gaps: time between consecutive chunk_dispatch span STARTS
    # minus the span's own duration — host-side stall between dispatches
    dispatch = sorted((float(r["t"]), float(r.get("dur_s", 0.0)))
                      for r in records if r.get("event") == "span"
                      and r.get("name") == "chunk_dispatch")
    gaps = [max(b[0] - (a[0] + a[1]), 0.0)
            for a, b in zip(dispatch, dispatch[1:])]
    # checkpoint time split: 'checkpoint' (sync save) and 'ckpt_snapshot'
    # (async backpressure + device snapshot) block the training thread;
    # 'ckpt_write' is the background writer's Orbax write.  NB these are
    # span WALL times — the run report's checkpoint_overlapped_s
    # additionally discounts write seconds the trainer stood blocked on
    # (they live in checkpoint_wait_s), so blocked_s + the report's
    # overlapped_s ≈ the span totals here, never more
    ckpt_blocked = sum(spans.get(n, {}).get("total_s", 0.0)
                       for n in ("checkpoint", "ckpt_snapshot"))
    ckpt_overlapped = spans.get("ckpt_write", {}).get("total_s", 0.0)
    return {
        "records": len(records),
        "spans": spans,
        "wall_s": (max(ends) - min(ts)) if ts else 0.0,
        "counters": counters,
        "stalls": {
            "prefetch_starvation": (max(int(g.get("starvation", 0))
                                        for g in gauges) if gauges else None),
            "zero_depth_gauges": sum(1 for g in gauges
                                     if not g.get("value")),
            "gauges": len(gauges),
            "max_dispatch_gap_s": max(gaps) if gaps else None,
            "checkpoint_blocked_s": ckpt_blocked,
            "checkpoint_overlapped_s": ckpt_overlapped,
            "anomaly_events": len(anomalies),
            "first_anomaly_step": (anomalies[0].get("step")
                                   if anomalies else None),
            "overload_events": len(overloads),
        },
    }


# --------------------------------------------------------- Perfetto export

def _json_safe(value: Any) -> Any:
    """Strict-JSON rendering of an arg value: Python's json module emits
    bare ``Infinity``/``NaN`` tokens that JSON.parse (Perfetto,
    chrome://tracing) rejects — and anomalous runs, the ones most worth
    looking at, carry exactly those values.  Render them as strings."""
    if isinstance(value, float) and not math.isfinite(value):
        return repr(value)  # 'inf' / '-inf' / 'nan'
    return value


def to_chrome_trace(records: list[dict]) -> dict[str, Any]:
    """Chrome-trace-event JSON (the format Perfetto and chrome://tracing
    load): every span record becomes exactly ONE complete ('X') event —
    the round-trip tests count on that bijection — events become instants
    ('i'), gauges/counters become counter tracks ('C').  Timestamps are
    the records' monotonic seconds in microseconds; pid is the JAX process
    index (so merged pod timelines separate per process), tid the OS pid."""
    events: list[dict] = []
    procs: dict[int, str] = {}
    for rec in records:
        kind = rec.get("event")
        if "t" not in rec or kind not in ("span", "event", "gauge",
                                          "counter"):
            continue
        pid = int(rec.get("process", 0))
        tid = int(rec.get("pid", 0))
        procs.setdefault(pid, f"{rec.get('host', '?')} "
                              f"(process {pid}, run {rec.get('run', '?')})")
        if kind == "event" and rec.get("name") == "timeline_series":
            # --timeline bulk series → one counter-track sample per ring
            # entry.  Per-replica series get their own pid LANE (Perfetto
            # groups counter tracks by pid), so a fleet trace shows each
            # replica's queue depth / KV blocks as parallel lanes with a
            # named header instead of one interleaved mess.
            replica = rec.get("replica")
            cpid = pid if replica is None else _TIMELINE_PID_BASE + replica
            if replica is not None:
                procs.setdefault(cpid, f"replica {replica} (timeline)")
            series = rec.get("series", "?")
            for t_mono, _wall, value in rec.get("samples", ()):
                events.append({"name": series, "cat": "timeline",
                               "ph": "C", "ts": float(t_mono) * 1e6,
                               "pid": cpid, "tid": 0,
                               "args": {series: _json_safe(value)}})
            continue
        ts = float(rec["t"]) * 1e6
        drop = {"event", "name", "t", "dur_s", "run", "host", "pid",
                "process", "schema_version"}
        if kind in ("gauge", "counter"):
            # only there is 'value' the counter-track payload; an EVENT's
            # value field (e.g. an anomaly's offending stat value) is an
            # arg the operator needs to see
            drop.add("value")
        args = {k: _json_safe(v) for k, v in rec.items() if k not in drop}
        if kind == "span":
            events.append({"name": rec["name"], "cat": "span", "ph": "X",
                           "ts": ts, "dur": float(rec.get("dur_s", 0.0)) * 1e6,
                           "pid": pid, "tid": tid, "args": args})
        elif kind == "event":
            events.append({"name": rec["name"], "cat": "event", "ph": "i",
                           "ts": ts, "s": "t", "pid": pid, "tid": tid,
                           "args": args})
        elif kind == "gauge":
            events.append({"name": rec["name"], "cat": "gauge", "ph": "C",
                           "ts": ts, "pid": pid, "tid": tid,
                           "args": {rec["name"]: rec.get("value", 0)}})
        else:  # counter
            events.append({"name": rec["name"], "cat": "counter", "ph": "C",
                           "ts": ts, "pid": pid, "tid": tid,
                           "args": {rec["name"]: rec.get("total", 0)}})
    events.sort(key=lambda e: e["ts"])
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "ts": 0,
             "args": {"name": label}} for pid, label in sorted(procs.items())]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


# per-replica timeline counter lanes: offset far above any real JAX
# process index so fleet lanes never collide with pod processes
_TIMELINE_PID_BASE = 100000


# ------------------------------------------------------ timeline (gauges)

def timeline_series(records: Iterable[dict]) -> dict[str, GaugeSeries]:
    """Rebuild the run's gauge series from the trace's bulk
    ``timeline_series`` events (Timeline.emit): {series_key: GaugeSeries},
    per-replica series under their ``name@rN`` key.  Lossless — the
    events carry the exact totals alongside the retained ring."""
    out: dict[str, GaugeSeries] = {}
    for rec in records:
        if rec.get("event") != "event" \
                or rec.get("name") != "timeline_series":
            continue
        name = rec.get("series", "?")
        replica = rec.get("replica")
        key = name if replica is None else f"{name}@r{replica}"
        g = GaugeSeries.from_dict({
            "capacity": rec.get("capacity", 512),
            "samples": rec.get("samples", []),
            "count": rec.get("count",
                             len(rec.get("samples", []))
                             + int(rec.get("dropped", 0) or 0)),
            "sum": rec.get("sum", 0.0),
            "vmin": rec.get("vmin"),
            "vmax": rec.get("vmax"),
        })
        if key in out:      # several windows in one trace (bench/sweep)
            out[key].merge(g)
        else:
            out[key] = g
    return out


def timeline_summary(records: list[dict]) -> dict[str, Any]:
    """JSON summary of a trace's timeline: per-series digests
    (GaugeSeries.summary) plus the sampler's self-measured overhead from
    the ``timeline_overhead`` event."""
    series = timeline_series(records)
    overhead = None
    for rec in records:
        if rec.get("event") == "event" \
                and rec.get("name") == "timeline_overhead":
            overhead = (overhead or 0.0) + float(rec.get("overhead_s", 0.0))
    return {
        "series": {k: s.summary() for k, s in sorted(series.items())},
        "series_n": len(series),
        "overhead_s": overhead,
    }


def render_timeline_text(records: list[dict], width: int = 60) -> str:
    """Sparkline rendering of every timeline series — one line per
    series, per-replica lanes grouped under their base name, retained
    window min→max annotated.  Stdlib glyphs only."""
    series = timeline_series(records)
    if not series:
        return "(no timeline_series events in trace — run with --timeline)"
    out = []
    namew = max(len(k) for k in series)
    for key in sorted(series):
        s = series[key]
        d = s.summary()
        drop = f" (+{d['dropped']} dropped)" if d["dropped"] else ""
        out.append(
            f"{key:>{namew}} |{sparkline(s.values(), width):<{width}}| "
            f"min={d['min']:g} max={d['max']:g} last={d['last']:g} "
            f"n={d['count']}{drop}")
    summ = timeline_summary(records)
    if summ["overhead_s"] is not None:
        out.append(f"sampler overhead: {summ['overhead_s'] * 1e3:.3f} ms")
    return "\n".join(out)


# -------------------------------------------------- XLA program manifests

def extract_manifest(report: dict[str, Any]) -> dict[str, Any]:
    """The program-ledger manifest from any artifact shape: a bare
    manifest (``analyze programs`` against another run's saved manifest),
    a run report carrying the ``xla`` section, or a summary whose nested
    run_report carries it (load_report already flattened that case)."""
    if isinstance(report.get("programs"), dict):
        return report
    xla = report.get("xla")
    if isinstance(xla, dict) and isinstance(xla.get("programs"), dict):
        return xla
    raise ValueError(
        "no XLA program manifest found (expected a 'programs' dict or an "
        "'xla' section — was the run launched with --timeline?)")


# ------------------------------------------------------- serving waterfall

def serve_waterfall(records: list[dict]) -> dict[str, Any]:
    """Per-request phase waterfall from a serving trace: the scheduler's
    ``request`` spans carry queue_wait_s/prefill_s/decode_s/ttft_s attrs
    (attached at finish), ``prefill_chunk`` spans carry the chunk-by-
    chunk fill, and ``overload`` events are the shed (429'd) requests.
    One row per request SPAN (not per rid: a bench/sweep trace holds
    several windows that all reuse rids 0..n−1 — every window's spans
    get their own rows, and each chunk attaches to the request span
    whose [start, end] interval contains it), arrival-ordered — the
    queue→prefill-chunks→decode story of every request served.

    Fleet traces (serving/fleet.py) add failover: each ``requeue`` event
    is a retry hop — the retried request's NEXT request span is its new
    segment on the surviving replica.  Same-rid rows get an ``attempt``
    number in time order, retried rows carry the hop's
    ``original_arrival_s`` (retry TTFT is charged from the ORIGINAL
    arrival — the row is keyed to it, not to the requeue time), and the
    hops ride the output as ``requeues``."""
    rows: list[dict[str, Any]] = []
    chunk_recs: list[dict[str, Any]] = []
    shed: list[dict[str, Any]] = []
    requeues: list[dict[str, Any]] = []
    for rec in records:
        kind = rec.get("event")
        rid = rec.get("rid")
        if rid is None:
            continue
        if kind == "event" and rec.get("name") == "requeue":
            requeues.append({"rid": rid, "t": rec.get("t"),
                             "from_replica": rec.get("from_replica"),
                             "to_replica": rec.get("to_replica"),
                             "attempt": rec.get("attempt"),
                             "arrival_s": rec.get("arrival_s"),
                             "emitted": rec.get("emitted"),
                             "reason": rec.get("reason")})
            continue
        if kind == "span" and rec.get("name") == "request":
            rows.append({
                "rid": rid,
                "t": rec.get("t"),
                "dur_s": rec.get("dur_s"),
                "prompt_len": rec.get("prompt_len"),
                "max_new_tokens": rec.get("max_new_tokens"),
                "queue_wait_s": rec.get("queue_wait_s"),
                "prefill_s": rec.get("prefill_s"),
                "decode_s": rec.get("decode_s"),
                "ttft_s": rec.get("ttft_s"),
                "tokens": rec.get("tokens"),
                "slo_met": rec.get("slo_met"),
                "prefill_chunks": [],
            })
        elif kind == "span" and rec.get("name") == "prefill_chunk":
            chunk_recs.append({
                "rid": rid,
                "t": rec.get("t"), "dur_s": rec.get("dur_s"),
                "tokens": rec.get("tokens"), "start": rec.get("start")})
        elif kind == "event" and rec.get("name") == "overload":
            shed.append({"rid": rid, "t": rec.get("t"),
                         "queue_depth": rec.get("queue_depth"),
                         "queue_cap": rec.get("queue_cap")})
    rows.sort(key=lambda r: (r["t"] is None, r["t"]))
    # chunk → request-span attribution by containment: the chunk's entry
    # time falls inside exactly one same-rid request span's interval
    # (windows run sequentially, so same-rid intervals are disjoint);
    # chunks of a request whose span never closed (killed window) drop
    for c in sorted(chunk_recs, key=lambda c: (c["t"] is None, c["t"])):
        if c["t"] is None:
            continue
        for row in rows:
            if (row["rid"] == c["rid"] and row["t"] is not None
                    and row["t"] <= c["t"]
                    <= row["t"] + (row["dur_s"] or 0.0)):
                row["prefill_chunks"].append(
                    {k: v for k, v in c.items() if k != "rid"})
                break
    # failover attribution: a row is a RETRY segment (attempt 2, 3, ...)
    # only when a requeue hop for its rid landed between the previous
    # same-rid row's start and this row's start — bench traces reuse
    # rids 0..n−1 across windows, so bare same-rid counting would tag
    # every later window's rows as phantom retries.  The hop's original
    # arrival keys the retried row (the retry-TTFT accounting rule).
    hops_by_rid: dict[Any, list] = {}
    for q in requeues:
        if q.get("t") is not None:
            hops_by_rid.setdefault(q["rid"], []).append(q)
    last_row: dict[Any, dict[str, Any]] = {}
    for row in rows:   # rows are already time-sorted
        prev = last_row.get(row["rid"])
        attempt, hop = 1, None
        if prev is not None and row["t"] is not None \
                and prev["t"] is not None:
            for q in hops_by_rid.get(row["rid"], ()):
                if prev["t"] <= q["t"] <= row["t"]:
                    hop = q
            if hop is not None:
                # the journal's own attempt number when the hop carries
                # it (a request can hop twice while QUEUED, leaving no
                # span between — prev+1 would undercount against the
                # requeue rows rendered alongside)
                attempt = hop.get("attempt") or (prev["attempt"] + 1)
        row["attempt"] = attempt
        if hop is not None and hop.get("arrival_s") is not None:
            row["original_arrival_s"] = hop["arrival_s"]
        last_row[row["rid"]] = row
    met = [r["slo_met"] for r in rows if r.get("slo_met") is not None]
    return {
        "requests": rows,
        "shed": shed,
        "requeues": requeues,
        "requests_n": len(rows),
        "shed_n": len(shed),
        "requeue_n": len(requeues),
        "slo_met_n": sum(bool(m) for m in met) if met else None,
    }


def render_waterfall_text(wf: dict[str, Any], width: int = 60) -> str:
    """ASCII rendering of ``serve_waterfall``: one bar per request on a
    shared wall-clock axis — '.' queue wait, '=' prefill, '#' decode —
    plus a shed line per 429'd request.  Falls back to span duration when
    a request has no phase attrs (a pre-round-13 trace)."""
    rows = wf["requests"]
    timed = [r for r in rows if r.get("t") is not None]
    if not timed:
        return "(no request spans in trace)"
    t0 = min(r["t"] for r in timed)
    # the span's t is its HOST entry (admission claim); the waterfall
    # starts each bar at claim − queue_wait so the queue phase shows
    starts = [r["t"] - (r.get("queue_wait_s") or 0.0) for r in timed]
    ends = [r["t"] + (r.get("dur_s") or 0.0) for r in timed]
    t0 = min(t0, min(starts))
    span = max(max(ends) - t0, 1e-9)
    scale = width / span
    out = []
    for r, start in zip(timed, starts):
        q = r.get("queue_wait_s") or 0.0
        p = r.get("prefill_s") or 0.0
        d = r.get("decode_s")
        d = (r.get("dur_s") or 0.0) - q - p if d is None else d
        off = int((start - t0) * scale)
        bar = (" " * off + "." * max(int(q * scale), 0)
               + "=" * max(int(p * scale), 1)
               + "#" * max(int(max(d, 0.0) * scale), 1))
        slo = ("" if r.get("slo_met") is None
               else (" SLO+" if r["slo_met"] else " SLO-"))
        # a retry hop's new span segment: tagged with its attempt number
        # and (when the requeue event carried it) the ORIGINAL arrival
        # the retried request's TTFT is charged from
        retry = ""
        if (r.get("attempt") or 1) > 1:
            orig = r.get("original_arrival_s")
            retry = (f" retry#{r['attempt']}"
                     + (f" (orig arrival {orig:.4f}s)"
                        if orig is not None else ""))
        out.append(f"{str(r['rid']):>6} |{bar:<{width + 4}}| "
                   f"q={q:.4f}s p={p:.4f}s d={max(d, 0.0):.4f}s"
                   f"{slo}{retry}")
    for rq in wf.get("requeues", ()):
        # the hop itself: where on the shared axis the request left its
        # dead replica for a survivor (same clamping as shed marks —
        # requeue events are emitted immediately, spans only at exit)
        off = int(max((rq["t"] or 0) - t0, 0.0) * scale)
        off = min(max(off, 0), width + 3)
        out.append(f"{str(rq['rid']):>6} |{' ' * off}>"
                   f"{'':<{max(width + 3 - off, 0)}}"
                   f"| requeue r{rq.get('from_replica')}→"
                   f"r{rq.get('to_replica')} after "
                   f"{rq.get('emitted')} tokens ({rq.get('reason')})")
    for s in wf["shed"]:
        # clamp into the axis: overload events are emitted immediately
        # while request spans only land at exit, so a partial trace can
        # carry sheds PAST the last closed span's end — a negative pad
        # width would crash the formatter
        off = int((max(s["t"] - t0, 0.0)) * scale) if s.get("t") else 0
        off = min(max(off, 0), width + 3)
        out.append(f"{str(s['rid']):>6} |{' ' * off}x"
                   f"{'':<{max(width + 3 - off, 0)}}"
                   f"| shed (429) at depth {s.get('queue_depth')}")
    out.append(f"legend: .=queue =prefill #=decode x=shed >=requeue; "
               f"{wf['requests_n']} served, {wf['shed_n']} shed, "
               f"{wf.get('requeue_n', 0)} requeued")
    return "\n".join(out)


# ----------------------------------------------------------- health files

def health_timeline(records: list[dict], *,
                    max_update_ratio: float = 1.0,
                    loss_spike_factor: float = 10.0) -> dict[str, Any]:
    """Summary of a metrics stream carrying the health keys (or a trace
    stream carrying ``anomaly`` events): first anomaly step, run maxima,
    and the non-finite/threshold step counts — the offline twin of the
    fit result's ``health`` section, recomputable from the file alone.

    The threshold kwargs mirror ``HealthConfig``'s defaults (this module
    stays stdlib-only, so it cannot import the jax-backed config class) —
    pass the run's actual thresholds when they were customized."""
    first = None
    nonfinite_steps = 0
    threshold_steps = 0
    maxima: dict[str, float] = {}
    steps = 0
    anomaly_steps: list[int] = []
    for rec in records:
        if rec.get("event") == "event" and rec.get("name") == "anomaly":
            step = rec.get("step")
            if step is not None and step not in anomaly_steps:
                anomaly_steps.append(step)
            continue
        if "event" in rec or "step" not in rec:
            # trace records (spans/gauges/counters) may carry a 'step'
            # attr (checkpoint/eval spans do) but are not health steps —
            # only metric records (no 'event' envelope) count
            continue
        steps += 1
        nonfinite = bool(rec.get("nonfinite_count"))
        crossed = False
        for key in ("grad_norm", "param_norm", "update_norm",
                    "update_ratio", "loss_spike", "loss"):
            v = rec.get(key)
            if v is None:
                continue
            if not math.isfinite(v):
                nonfinite = True
                continue
            if key != "loss":
                maxima[key] = max(maxima.get(key, v), v)
            if key == "update_ratio" and v > max_update_ratio:
                crossed = True
            if key == "loss_spike" and v > loss_spike_factor:
                crossed = True
        nonfinite_steps += nonfinite
        threshold_steps += (crossed and not nonfinite)
        if (nonfinite or crossed) and first is None:
            first = rec["step"]
    if anomaly_steps and (first is None or anomaly_steps[0] < first):
        first = anomaly_steps[0]
    return {
        "steps": steps,
        "first_anomaly_step": first,
        "nonfinite_steps": nonfinite_steps,
        "threshold_steps": threshold_steps,
        "anomaly_events": len(anomaly_steps),
        **{f"max_{k}": v for k, v in sorted(maxima.items())},
    }


# ------------------------------------------------------------ run-vs-run

# (key, better-direction) pairs the differ compares when present+numeric in
# BOTH reports.  Covers run reports, fit summaries AND bench.py lines —
# one table so a BENCH_*.json trajectory can be diffed against a run.
_DIFF_METRICS: tuple[tuple[str, str], ...] = (
    ("step_time_p50_s", "lower"), ("step_time_p95_s", "lower"),
    ("step_time_mean_s", "lower"), ("compile_s", "lower"),
    ("elapsed_s", "lower"), ("telemetry_overhead_frac", "lower"),
    ("grad_allreduce_bytes", "lower"),
    # per-device state footprint (--precision; run report AND bench line):
    # the storage numbers mixed precision exists to shrink — param bytes
    # halve under bf16 storage; optimizer bytes are gated too so a master
    # policy's f32 copy (a deliberate, bounded cost) cannot silently grow
    # past what the policy change justified
    ("param_bytes_per_device", "lower"),
    ("opt_state_bytes_per_device", "lower"),
    # fp16 dynamic-loss-scale skips (flattened from the loss_scale
    # section below): a step that skipped did no training — more skips at
    # equal work is a regression
    ("loss_scale_skipped_steps", "lower"),
    # exposed gradient-collective seconds (run report AND bench line —
    # the communication/compute-overlap gate, BASELINE.md: exposed time
    # is the number that must go down; hidden_s is deliberately NOT
    # compared — burying more collective time under compute is the point)
    ("grad_collective_exposed_s", "lower"),
    # training-thread seconds blocked on checkpointing (run report /
    # fit result; overlapped_s is deliberately NOT compared — moving work
    # onto the background writer is the point, not a regression)
    ("checkpoint_wait_s", "lower"),
    # elastic preemption accounting (run report of an --elastic-restore
    # run; BASELINE.md "Preemption accounting"): wall seconds between the
    # restored checkpoint's save and the resume — time nothing trained —
    # and steps whose data-stream position could not be restored (0 = an
    # exact exactly-once resume).  Both lower-is-better: a fatter
    # preemption window or a lossier resume is a regression in
    # time-to-quality even when throughput held.
    ("preemption_lost_s", "lower"),
    ("resume_replay_steps", "lower"),
    # step-time outlier count (flattened from the stragglers section
    # below): more outlier chunks at equal work = a degrading lease
    ("straggler_events", "lower"),
    ("examples_per_sec", "higher"), ("examples_per_sec_per_device", "higher"),
    ("test_accuracy", "higher"),
    # bench.py line vocabulary ("value"'s direction is resolved per line —
    # see _value_direction; today's value-bearing bench metrics are rates)
    ("step_time_p50", "lower"), ("step_time_p95", "lower"),
    ("prefetch_starvation", "lower"), ("grad_bytes_per_step_wire", "lower"),
    ("dispatch_value", "higher"), ("trainer_examples_per_sec", "higher"),
    ("mfu", "higher"),
    # health: anomaly count (flattened from the health section below)
    ("health_anomalies", "lower"),
    # serving (bench --serve line / run report `serve` section, flattened
    # below): latency percentiles gate lower-is-better — TTFT includes
    # queue wait by the BASELINE.md accounting rule, so an admission
    # regression shows up here, not just in throughput — and
    # requests/sec/chip higher.  ITL/TTFT p50s compared too: a p95-only
    # gate would let the median regress behind a stable tail.
    ("serve_requests_per_sec_per_chip", "higher"),
    ("serve_requests_per_sec", "higher"),
    ("serve_tokens_per_sec", "higher"),
    ("serve_ttft_p50_s", "lower"), ("serve_ttft_p95_s", "lower"),
    ("serve_itl_p50_s", "lower"), ("serve_itl_p95_s", "lower"),
    # chunked prefill + prefix caching (round 10): the prefill/decode
    # token split and the prefix-pool hit rate are rates — all
    # higher-is-better (NB every *_per_sec key here must be listed, or
    # the `sec_per`-substring direction bug class regresses silently;
    # the _value_direction unit tests pin each one)
    ("serve_prefill_tokens_per_sec", "higher"),
    ("serve_decode_tokens_per_sec", "higher"),
    ("serve_prefix_cache_hit_rate", "higher"),
    # SLO-aware serving observability (round 13; BASELINE.md "Goodput
    # accounting"): tail latency gates at p99 — the percentile the SLO is
    # written against — queue wait p99 bounds the admission backlog
    # (overload mode exists to keep THIS bounded), goodput-under-SLO and
    # the swept maximum are THE headline serving numbers (higher), and
    # the shed rate at a fixed offered rate must not grow (shedding more
    # at equal load is lost goodput even though shedding per se is the
    # designed overload behavior)
    ("serve_ttft_p99_s", "lower"), ("serve_itl_p99_s", "lower"),
    ("serve_queue_wait_p99_s", "lower"),
    ("serve_goodput_under_slo", "higher"),
    ("serve_max_goodput_under_slo", "higher"),
    ("serve_knee_rate_per_s", "higher"),
    ("serve_shed_rate", "lower"),
    # raw decode speed (round 14): the speculative-decode accept rate is
    # draft-token efficiency — fewer accepts at the same draft config is
    # a regression in verify-step yield (BASELINE.md: cross-run
    # comparisons must state the draft config, the rate is workload-
    # dependent) — and the stored KV bytes per serving slot are the
    # capacity-per-chip number int8/bf16 storage exists to shrink.
    # serve_tokens_per_sec (the gated speculative headline, emitted
    # tokens only) is already listed above.
    ("serve_accept_rate", "higher"),
    ("serve_kv_bytes_per_slot", "lower"),
    # fleet robustness (round 15; BASELINE.md "Failover accounting"):
    # failover recovery — replica-failure detection to the failed-over
    # request's first post-requeue delivery — is the seconds a reader's
    # stream stood still, and duplicate emissions are the exactly-once
    # claim measured (0 by construction; any growth is a journal-fence
    # regression).  Both lower-is-better.
    ("serve_failover_recovery_p95_s", "lower"),
    ("serve_duplicate_emissions", "lower"),
    # paged KV (round 16; BASELINE.md "Paged accounting"): blocks in use
    # at equal workload is the footprint the block pool exists to shrink
    # (aliased prefixes stored once), and the zero-copy hit rate is the
    # fraction of prefix-pool lookups served by pointer aliasing instead
    # of device copies — fewer zero-copy hits at the same trace means
    # admissions are paying prefill for KV the pool already holds.
    ("serve_kv_blocks_in_use", "lower"),
    ("serve_prefix_zero_copy_hit_rate", "higher"),
    # timeline + XLA ledger (round 17; BASELINE.md "Memory/compile
    # accounting"): the summed per-program HBM estimate is the
    # capacity-per-chip number every KV/precision optimization exists to
    # shrink, and total compile seconds at equal work growing means a
    # program-set or cache regression.  The telemetry's own cost is gated
    # too — sink drops are lost observability records, the trace/sampler
    # overheads are the "<1% of wall" budget measured (all lower).
    ("peak_hbm_bytes_est", "lower"),
    ("compile_total_s", "lower"),
    ("sink_dropped", "lower"),
    ("serve_sink_dropped", "lower"),
    ("serve_trace_overhead_s", "lower"),
    ("timeline_overhead_s", "lower"),
    # queue-depth area (requests·s of queueing over the window) and the
    # KV block-footprint p95 — the autoscaler's target signals; at equal
    # offered load, growth is an admission/capacity regression
    ("queue_depth_auc", "lower"),
    ("kv_blocks_in_use_p95", "lower"),
    # heterogeneous fleet (round 18; BASELINE.md "Disaggregation
    # accounting"): the affinity router's fleet-wide prefix hit rate is
    # the number the router exists to raise (fewer hits at the same
    # trace = shared-prefix traffic landing on cold pools); replica-
    # seconds is the capacity actually paid for the window — the
    # autoscaler's whole point is to shrink it at held goodput; and the
    # disagg/homogeneous ITL-p95 ratio on the same seeded trace is the
    # decode-interference number disaggregation exists to shrink (< 1 =
    # disagg wins, growth = the handoff is leaking prefill work back
    # into decode iterations).
    ("serve_fleet_prefix_hit_rate", "higher"),
    ("serve_replica_seconds", "lower"),
    ("disagg_vs_homogeneous_itl_p95", "lower"),
    # roofline utilizations (round 19; BASELINE.md "Roofline
    # accounting"): MFU/MBU are fractions of the hardware actually
    # achieved — THE comparable headline across configs (a rate can rise
    # while utilization falls on a bigger device); all higher-is-better.
    # Cross-run claims must state the peak-table revision the run report
    # carries.
    ("train_mfu", "higher"),
    ("serve_decode_mbu", "higher"),
    ("serve_prefill_mfu", "higher"),
    # multi-step decode dispatch (round 20; BASELINE.md "Dispatch
    # accounting"): host-gap seconds — wall time the device sat idle
    # while Python scheduled, synced D2H, and re-uploaded — is THE
    # number fused dispatch exists to shrink (same seeded trace, same
    # k); dispatches is its denominator, and the per-role replica-
    # seconds split attributes the autoscaled capacity bill per pool.
    ("serve_host_gap_s", "lower"),
    ("serve_dispatches", "lower"),
    ("serve_replica_seconds_prefill", "lower"),
    ("serve_replica_seconds_decode", "lower"),
)


def load_report(path: str | Path) -> dict[str, Any]:
    """One comparable dict from any artifact this repo writes: a JSON
    object, or a JSONL stream whose LAST parsable object wins (result
    sinks append the summary last; bench prints one line).  A nested
    ``run_report`` is flattened under the summary's own keys, and the
    ``health`` section's anomaly count surfaces as ``health_anomalies``."""
    text = Path(path).read_text()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
        if obj is None:
            raise ValueError(f"{path}: no parsable JSON object found")
    if not isinstance(obj, dict):
        raise ValueError(f"{path}: expected a JSON object, got "
                         f"{type(obj).__name__}")
    flat = dict(obj)
    nested = obj.get("run_report")
    if isinstance(nested, dict):
        # summary keys win where both exist (they are the same numbers)
        flat = {**nested, **{k: v for k, v in obj.items()
                             if k != "run_report"}}
    health = flat.get("health")
    if isinstance(health, dict) and "anomalies" in health:
        flat.setdefault("health_anomalies", health["anomalies"])
    # the fp16 loss-scale section's skip count surfaces flat so scaling
    # regressions diff with the same machinery as everything else
    ls = flat.get("loss_scale")
    if isinstance(ls, dict) and "skipped_steps" in ls:
        flat.setdefault("loss_scale_skipped_steps", ls["skipped_steps"])
    # the straggler section's outlier count surfaces flat (events = how
    # many chunks exceeded factor × the running median step time)
    stragglers = flat.get("stragglers")
    if isinstance(stragglers, dict) and "events" in stragglers:
        flat.setdefault("straggler_events", stragglers["events"])
    # a run report's nested `serve` section surfaces its serve_* metrics
    # at the top level so serving runs diff with the same machinery as
    # training runs (bench --serve lines already emit them flat)
    serve = flat.get("serve")
    if isinstance(serve, dict):
        for key, value in serve.items():
            if key.startswith("serve_"):
                flat.setdefault(key, value)
        # the --timeline gauge digests ride the serve section under their
        # own names (batcher/fleet summary keys, no serve_ prefix) —
        # surface the gated ones flat
        for key in ("queue_depth_auc", "kv_blocks_in_use_p95",
                    "timeline_overhead_s"):
            if isinstance(serve.get(key), (int, float)):
                flat.setdefault(key, serve[key])
        # fleet-mode telemetry self-accounting (serve_fleet subsection)
        fleet = serve.get("serve_fleet")
        if isinstance(fleet, dict) \
                and isinstance(fleet.get("sink_dropped"), (int, float)):
            flat.setdefault("sink_dropped", fleet["sink_dropped"])
    # the run report's trace-sink health: drops are lost observability
    # records — surfaced flat for the lower-is-better gate
    trace = flat.get("trace")
    if isinstance(trace, dict) \
            and isinstance(trace.get("dropped"), (int, float)):
        flat.setdefault("sink_dropped", trace["dropped"])
    return flat


def _value_direction(report: dict[str, Any]) -> str:
    """Better-direction of a bench line's headline ``value``, resolved
    from the line itself: time-valued metrics/units (ms, seconds) are
    lower-is-better, rates (the current bench vocabulary — examples/sec,
    tokens/sec) higher.  Hard-coding 'higher' would invert the verdict
    the day a time-valued bench metric gains a headline value."""
    probe = f"{report.get('metric', '')} {report.get('unit', '')}".lower()
    # rates first: "…_per_sec_per_chip" CONTAINS the substring "sec_per",
    # so the time-per test alone misread every rate-valued bench line as
    # lower-is-better (an examples/sec improvement diffed as a regression)
    if any(s in probe for s in ("per_sec", "per sec", "/sec", "/s ")):
        return "higher"
    # utilization-valued headlines (round 19: MFU/MBU fractions of the
    # hardware peak) are higher-is-better — checked before the time/byte
    # classes so e.g. a "decode_mbu" metric never trips the "byte" test
    if any(s in probe for s in ("mfu", "mbu", "utilization")):
        return "higher"
    if any(s in probe for s in ("_ms", " ms", "ms/", "_s ", "seconds_per",
                                "sec_per", "s/step", "latency",
                                # byte-valued headlines (kv_bytes_per_slot
                                # class): smaller footprint is the win
                                "byte",
                                # latency-ratio headlines (the round-18
                                # disagg line: disagg/homogeneous itl_p95,
                                # < 1 = disagg wins): ITL is a latency
                                "itl")):
        return "lower"
    return "higher"


def diff_reports(base: dict[str, Any], new: dict[str, Any],
                 threshold: float = 0.1) -> dict[str, Any]:
    """Compare every shared numeric metric of the table; a metric REGRESSES
    when it moves in its worse direction by more than ``threshold``
    (relative; a zero baseline uses absolute change).  Returns
    {regressions, improvements, unchanged, compared, threshold} — plus
    ``metric_mismatch`` (and NO comparisons) when the two inputs are bench
    lines for different metrics: a decode line diffed against an attention
    line would otherwise compare unrelated numbers silently."""
    m_a, m_b = base.get("metric"), new.get("metric")
    if m_a is not None and m_b is not None and m_a != m_b:
        return {"compared": 0, "threshold": threshold,
                "metric_mismatch": {"base": m_a, "new": m_b},
                "regressions": [], "improvements": [], "unchanged": []}
    table = _DIFF_METRICS + (("value", _value_direction(base)),)
    regressions, improvements, unchanged = [], [], []
    for key, better in table:
        a, b = base.get(key), new.get(key)
        if not isinstance(a, (int, float)) or not isinstance(b, (int, float)) \
                or isinstance(a, bool) or isinstance(b, bool):
            continue
        if not (math.isfinite(a) and math.isfinite(b)):
            continue
        delta = (b - a) / abs(a) if a else (b - a)
        worse = delta > threshold if better == "lower" \
            else delta < -threshold
        better_move = delta < -threshold if better == "lower" \
            else delta > threshold
        row = {"metric": key, "base": a, "new": b,
               "delta_frac": round(delta, 6), "better": better}
        (regressions if worse else
         improvements if better_move else unchanged).append(row)
    return {
        "compared": len(regressions) + len(improvements) + len(unchanged),
        "threshold": threshold,
        "regressions": regressions,
        "improvements": improvements,
        "unchanged": unchanged,
    }


# --------------------------------------------------- roofline attribution

def _cmd_roofline(args) -> int:
    """``analyze roofline``: render the per-program roofline table —
    arithmetic intensity, compute/bandwidth bound, attainable %-of-peak —
    plus the run's headline utilizations, offline from a run report or a
    bare manifest (stdlib only; the roofline module imports no jax).
    Device kind/dtype come from the report's own roofline section (or
    environment), overridable; an unknown kind degrades honestly —
    intensity still renders, bound/%-of-peak stay None."""
    from distributed_tensorflow_tpu.observability.roofline import (
        PEAK_TABLE_REVISION, device_peaks, program_attribution,
        ridge_point)

    flat = load_report(args.report)
    rf = flat.get("roofline")
    rf = rf if isinstance(rf, dict) else {}
    dev = rf.get("device") or {}
    kind = (args.device or dev.get("device_kind")
            or (flat.get("environment") or {}).get("device_kind"))
    dtype = args.dtype or dev.get("dtype") or "bf16"
    peaks = device_peaks(kind)
    try:
        manifest = extract_manifest(flat)
    except ValueError:
        manifest = {"programs": {}}
    rows = program_attribution(manifest.get("programs", {}),
                               peaks=peaks, dtype=dtype)
    headline = {k: flat.get(k) for k in ("train_mfu", "serve_decode_mbu",
                                         "serve_prefill_mfu")
                if isinstance(flat.get(k), (int, float))}
    out = {
        "device_kind": kind,
        "known_device": peaks is not None,
        "peak_table_revision": (dev.get("peak_table_revision")
                                or PEAK_TABLE_REVISION),
        "dtype": dtype,
        "ridge_flops_per_byte": ridge_point(peaks, dtype),
        **headline,
        "programs": rows,
    }
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    known = "known" if peaks is not None else "UNKNOWN — no peaks"
    ridge = out["ridge_flops_per_byte"]
    print(f"device: {kind or '?'} ({known})  dtype={dtype}  "
          f"peak-table rev {out['peak_table_revision']}"
          + (f"  ridge={ridge:.1f} flops/byte" if ridge else ""))
    if headline:
        print("  ".join(f"{k}={v:.4f}" for k, v in headline.items()))
    if not rows:
        print("no programs with cost-analysis data (run with --roofline "
              "on a backend that reports cost_analysis)")
        return 0
    namew = max(len(r["program"]) for r in rows)

    def _fmt(v, spec, none="-"):
        return format(v, spec) if isinstance(v, (int, float)) else none

    print(f"{'program':<{namew}}  {'flops':>10}  {'bytes':>10}  "
          f"{'flops/B':>8}  {'bound':>9}  {'%peak':>6}")
    for r in rows:
        frac = r["attainable_frac_of_peak"]
        print(f"{r['program']:<{namew}}  "
              f"{_fmt(r['flops'], '10.3g'):>10}  "
              f"{_fmt(r['bytes_accessed'], '10.3g'):>10}  "
              f"{_fmt(r['arithmetic_intensity'], '8.2f'):>8}  "
              f"{r['bound'] or '-':>9}  "
              + (f"{100 * frac:>5.1f}%" if isinstance(frac, (int, float))
                 else f"{'-':>6}"))
    return 0


# ------------------------------------------------------------------- CLI

def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributed_tensorflow_tpu.observability.analyze",
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("spans", help="span aggregation + stall summary")
    sp.add_argument("trace", help="trace JSONL (--trace output)")

    ex = sub.add_parser("export", help="Chrome-trace JSON for Perfetto")
    ex.add_argument("trace", help="trace JSONL (--trace output)")
    ex.add_argument("-o", "--output", default=None,
                    help="output path (default: <trace>.chrome.json)")

    he = sub.add_parser("health", help="health timeline summary")
    he.add_argument("metrics", help="metrics or trace JSONL")
    he.add_argument("--max-update-ratio", type=float, default=1.0,
                    help="update-ratio anomaly ceiling (HealthConfig "
                         "default; pass the run's value if customized)")
    he.add_argument("--spike-factor", type=float, default=10.0,
                    help="loss-spike anomaly factor (HealthConfig default)")

    sv = sub.add_parser("serve", help="per-request serving waterfall "
                                      "(queue→prefill-chunks→decode)")
    sv.add_argument("trace", help="serving trace JSONL (--trace output "
                                  "of a --serve run or bench --serve)")
    sv.add_argument("--text", action="store_true",
                    help="render ASCII bars instead of JSON")
    sv.add_argument("--width", type=int, default=60,
                    help="--text: bar width in characters")

    df = sub.add_parser("diff", help="run-vs-run regression diff "
                                     "(exit 1 iff a metric regressed)")
    df.add_argument("base", help="baseline report/summary/bench JSON(L)")
    df.add_argument("new", help="candidate report/summary/bench JSON(L)")
    df.add_argument("--threshold", type=float, default=0.1,
                    help="relative regression threshold (default 0.1)")

    tl = sub.add_parser("timeline", help="--timeline gauge series as "
                                         "text sparklines (per-replica "
                                         "lanes) from the trace alone")
    tl.add_argument("trace", help="trace JSONL of a --timeline run")
    tl.add_argument("--json", action="store_true",
                    help="emit the per-series summaries as JSON instead")
    tl.add_argument("--width", type=int, default=60,
                    help="sparkline width in characters")

    pg = sub.add_parser("programs",
                        help="--timeline XLA program ledger: memory/"
                             "compile manifest; --against BASE = drift "
                             "gate (exit 1 on added programs or temp-"
                             "bytes growth)")
    pg.add_argument("report", help="run report / summary JSON(L) with an "
                                   "'xla' section, or a bare manifest")
    pg.add_argument("--against", default=None, metavar="BASE",
                    help="baseline report/manifest to diff against")
    pg.add_argument("--temp-threshold", type=float, default=0.10,
                    help="relative temp-bytes growth that fails the gate "
                         "(default 0.10)")

    rl = sub.add_parser("roofline",
                        help="--roofline attribution: per-program "
                             "arithmetic intensity, compute/bandwidth "
                             "bound and attainable %-of-peak from a run "
                             "report (or a bare program manifest)")
    rl.add_argument("report", help="run report / summary JSON(L) with an "
                                   "'xla' section, or a bare manifest")
    rl.add_argument("--device", default=None, metavar="KIND",
                    help="device kind override (default: the report's "
                         "roofline/environment section; unknown kinds "
                         "render intensity only — bound and %-of-peak "
                         "honestly stay None)")
    rl.add_argument("--dtype", default=None,
                    help="peak dtype key (bf16|f32|int8; default: the "
                         "report's roofline dtype, else bf16)")
    rl.add_argument("--json", action="store_true",
                    help="emit the table as JSON instead of text")

    args = p.parse_args(argv)
    if args.cmd == "spans":
        print(json.dumps(trace_summary(read_jsonl(args.trace)), indent=2))
        return 0
    if args.cmd == "export":
        out = args.output or str(args.trace) + ".chrome.json"
        trace = to_chrome_trace(read_jsonl(args.trace))
        Path(out).write_text(json.dumps(trace))
        n = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
        print(f"wrote {out}: {len(trace['traceEvents'])} events "
              f"({n} spans) — load it at https://ui.perfetto.dev",
              file=sys.stderr)
        return 0
    if args.cmd == "serve":
        wf = serve_waterfall(read_jsonl(args.trace))
        if args.text:
            print(render_waterfall_text(wf, width=args.width))
        else:
            print(json.dumps(wf, indent=2))
        return 0
    if args.cmd == "health":
        print(json.dumps(health_timeline(
            read_jsonl(args.metrics),
            max_update_ratio=args.max_update_ratio,
            loss_spike_factor=args.spike_factor), indent=2))
        return 0
    if args.cmd == "timeline":
        records = read_jsonl(args.trace)
        if args.json:
            print(json.dumps(timeline_summary(records), indent=2))
        else:
            print(render_timeline_text(records, width=args.width))
        return 0
    if args.cmd == "programs":
        current = extract_manifest(load_report(args.report))
        if args.against is None:
            print(json.dumps(current, indent=2))
            return 0
        base = extract_manifest(load_report(args.against))
        findings = diff_manifests(current, base,
                                  temp_threshold=args.temp_threshold)
        failed = [f for f in findings if f.get("severity") == "fail"]
        print(json.dumps({"findings": findings,
                          "failed": len(failed),
                          "temp_threshold": args.temp_threshold,
                          "program_count": {
                              "base": len(base.get("programs", {})),
                              "new": len(current.get("programs", {}))}},
                         indent=2))
        # the drift gate: growth in the program set or in a program's
        # temp bytes past threshold fails CI; removals are informational
        return 1 if failed else 0
    if args.cmd == "roofline":
        return _cmd_roofline(args)
    # diff: 0 = no regression, 1 = regression past threshold, 2 = nothing
    # was compared (mismatched bench metrics, or inputs sharing no known
    # metric keys — e.g. an operator diffing two trace files).  A 0 on an
    # empty comparison would read as "no regression" for a typo.
    result = diff_reports(load_report(args.base), load_report(args.new),
                          threshold=args.threshold)
    print(json.dumps(result, indent=2))
    if result.get("metric_mismatch") or result["compared"] == 0:
        return 2
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
