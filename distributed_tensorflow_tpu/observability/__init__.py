"""Observability: on-device metric trajectories, trace spans, run reports.

The reference's only observability is ``print()`` plus one wall-clock
window (SURVEY.md §5; logging is actively disabled in dist_keras.py:67-68).
This package is the opposite pole — telemetry that observes the shipped
fast path instead of disabling it:

  sink     — AsyncJsonlSink: background writer thread over a bounded
             queue (drop counter on overflow), line-buffered JSONL so a
             killed run leaves only whole lines.  The host cost of a
             record is one queue put.
  trace    — structured span/event timeline (monotonic clock, run/host/
             process ids) shared with XProf via
             ``jax.profiler.TraceAnnotation``, plus cheap in-memory span
             aggregates for the run report even when no file sink is
             configured.
  report   — the end-of-run structured summary: steady-state step-time
             percentiles split from compile, chunk shapes actually used,
             watchdog heartbeat/stall counts, prefetch starvation totals,
             sink drops, the health section, and the measured telemetry
             overhead itself.
  health   — per-step numeric-health stats computed ON DEVICE inside the
             jitted many-step scan (grad/param/update norms, update
             ratio, non-finite leaf count, loss-spike vs a running EMA)
             via optimizer-level capture transforms, plus the host-side
             anomaly policy behind ``--on-anomaly warn|halt``.
  metrics  — LogHistogram / MetricsRegistry: streaming log-bucketed
             histograms (fixed geometric buckets, O(1) record, mergeable
             across windows/replicas) — serving latency p50/p95/p99
             computed online without storing every sample.
  slo      — SLOMonitor: goodput-under-SLO accounting (requests/sec
             meeting BOTH the TTFT and ITL targets; shed requests are
             offered load, never goodput).
  timeline — GaugeSeries / Timeline: bounded-ring time-series gauges
             (O(1) record, exact merge, per-series interval throttle,
             self-measured overhead) sampled at existing iteration
             boundaries — the autoscaler's sensor substrate, rendered by
             ``analyze timeline`` and the Perfetto counter tracks.
  xla_stats— ProgramLedger: per-compiled-program XLA memory_analysis +
             compile wall-time (``ledger.jit`` observes a call site's
             compiles; flag off = literal ``jax.jit``), with a manifest
             the ``analyze programs`` drift gate diffs; round 19 adds
             cost_analysis flops/bytes columns for roofline attribution.
  roofline — GPTCostModel / DevicePeaks / Roofline: analytic model
             FLOPs and must-read bytes from config alone, the device
             peak table (unknown kind → None, never an invented peak),
             and the MFU/MBU wiring object ``--roofline`` threads
             through trainer, batcher, fleet and run report.  Stdlib-
             only — ``analyze roofline`` renders offline.
  analyze  — the offline read side: span aggregation, stall summaries,
             Chrome-trace-event export (Perfetto-loadable), health
             timelines, and the run-vs-run regression diff.  Stdlib-only,
             usable as ``python -m
             distributed_tensorflow_tpu.observability.analyze``.

Why this lives OUTSIDE the step loop's downshift logic: per-step metric
records ride the ``lax.scan`` carry of ``Engine.build_many_step`` and are
materialized once per chunk (one host sync per k steps), so enabling
``--metrics-path`` or the watchdog no longer forces ``Trainer.fit`` down
to ``steps_per_call=1`` (see Trainer.resolve_steps_per_call).
"""

from distributed_tensorflow_tpu.observability.metrics import (
    LogHistogram, MetricsRegistry, exact_percentile)
from distributed_tensorflow_tpu.observability.report import (
    build_run_report, runtime_environment, serve_section)
from distributed_tensorflow_tpu.observability.sink import (
    SCHEMA_VERSION, AsyncJsonlSink)
from distributed_tensorflow_tpu.observability.roofline import (
    PEAK_TABLE_REVISION, DevicePeaks, GPTCostModel, Roofline, device_peaks,
    program_attribution)
from distributed_tensorflow_tpu.observability.slo import SLOMonitor
from distributed_tensorflow_tpu.observability.timeline import (
    GaugeSeries, Timeline, sparkline)
from distributed_tensorflow_tpu.observability.trace import (
    NULL_TRACER, Tracer)
from distributed_tensorflow_tpu.observability.xla_stats import (
    ProgramLedger, diff_manifests)

__all__ = [
    "AsyncJsonlSink",
    "DevicePeaks",
    "GPTCostModel",
    "GaugeSeries",
    "HealthConfig",
    "LogHistogram",
    "MetricsRegistry",
    "NULL_TRACER",
    "PEAK_TABLE_REVISION",
    "ProgramLedger",
    "Roofline",
    "SCHEMA_VERSION",
    "SLOMonitor",
    "Timeline",
    "Tracer",
    "build_run_report",
    "device_peaks",
    "diff_manifests",
    "program_attribution",
    "runtime_environment",
    "serve_section",
    "sparkline",
    "exact_percentile",
]


def __getattr__(name: str):
    # lazy: health pulls in jax/optax, which the stdlib-only analyze CLI
    # (and anything else reading JSONL offline) must not pay for
    if name == "HealthConfig":
        from distributed_tensorflow_tpu.observability.health import (
            HealthConfig)

        return HealthConfig
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
