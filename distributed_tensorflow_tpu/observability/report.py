"""End-of-run report: one structured summary of how the run behaved.

This is the layer every later scaling PR reads its numbers from — a single
dict (emitted as the harness's ``run_report`` event and carried in the
summary) that answers the operational questions a throughput number alone
cannot:

* steady-state step time p50/p95 SPLIT from compile (the first chunk
  smears its XLA compile over its k entries; percentiles over the rest);
* which chunk shapes the drain actually dispatched (``chunk_sizes`` —
  auto-resolution, tail chunks and ``max_steps`` truncation all show up
  here);
* watchdog heartbeat/stall counts, prefetch starvation totals, metric
  sink drops — the "did telemetry or input starve the device" trio;
* the measured cost of the telemetry itself (``telemetry_overhead_s`` /
  ``_frac``): the "metrics+tracing within 5% of telemetry-off" budget is
  reported by the run, not assumed.
"""

from __future__ import annotations

import os
from typing import Any

from distributed_tensorflow_tpu.observability.sink import SCHEMA_VERSION


def runtime_environment() -> dict[str, Any]:
    """The execution-environment facts that make perf numbers attributable
    across containers (the r03–r05 lesson: a bench trajectory without
    them cannot be compared): jax version, device kind, and the effective
    XLA flag carriers (``XLA_FLAGS`` / ``LIBTPU_INIT_ARGS`` — the overlap
    flags ``utils/harness.enable_overlap_flags`` sets ride the latter).
    The jax fields degrade to None rather than force a backend where none
    was initialized by the caller's run."""
    env: dict[str, Any] = {
        "jax_version": None,
        "device_kind": None,
        "xla_flags": os.environ.get("XLA_FLAGS"),
        "libtpu_init_args": os.environ.get("LIBTPU_INIT_ARGS"),
    }
    try:
        import jax

        env["jax_version"] = jax.__version__
        # device_kind only when a backend ALREADY exists: jax.local_devices()
        # would otherwise initialize one as a side effect, locking in
        # whatever LIBTPU_INIT_ARGS/XLA_FLAGS are set NOW and silently
        # ignoring flags the caller (e.g. enable_overlap_flags) meant to
        # apply before its own init — the exact misattribution this
        # section exists to prevent
        from jax._src import xla_bridge

        if getattr(xla_bridge, "_backends", None):
            env["device_kind"] = jax.local_devices()[0].device_kind
    except Exception:
        pass
    return env


def serve_section(summary: dict[str, Any] | None,
                  n_devices: int = 1, tracer=None) -> dict[str, Any] | None:
    """Normalize a ContinuousBatcher summary into the run-report/bench
    ``serve`` section: the per-request result objects are dropped (the
    section must stay JSON), and the per-chip rates — requests/sec (the
    round-7 headline) and goodput-under-SLO (the round-13 one, mirroring
    examples_per_sec_per_device) — are derived here so every surface
    divides by the same device count.  ``tracer`` (when enabled) adds the
    serve window's telemetry self-accounting — sink drop counter + span
    bookkeeping overhead, previously train-report-only — gated
    lower-is-better by `analyze diff`."""
    if summary is None:
        return None
    sec = {k: v for k, v in summary.items() if k != "results"}
    for key in ("serve_requests_per_sec", "serve_goodput_under_slo"):
        v = sec.get(key)
        sec[f"{key}_per_chip"] = (
            v / n_devices if isinstance(v, (int, float)) and n_devices
            else None)
    if tracer is not None and getattr(tracer, "enabled", False):
        tstats = tracer.stats()
        sec["serve_sink_dropped"] = tstats.get("dropped")
        sec["serve_sink_written"] = tstats.get("written")
        sec["serve_trace_overhead_s"] = tstats.get("overhead_s", 0.0)
    return sec


def build_run_report(fit_result: dict[str, Any], *,
                     watchdog=None, metrics_logger=None, tracer=None,
                     serve: dict[str, Any] | None = None,
                     timeline=None, ledger=None, roofline=None,
                     ) -> dict[str, Any]:
    """Assemble the run report from the Trainer's fit result and the live
    telemetry objects.  Every argument except ``fit_result`` is optional —
    absent subsystems report as None, so readers can distinguish
    "disabled" from "zero".  ``serve`` is a post-training serving window's
    section (``serve_section``) — serving gets the same trajectory and
    regression gating training has (`analyze diff` flattens the nested
    serve_* keys)."""
    st = fit_result.get("step_time") or {}
    elapsed = float(fit_result.get("elapsed") or 0.0)

    report: dict[str, Any] = {
        "schema_version": SCHEMA_VERSION,
        "steps": fit_result.get("steps"),
        # the measured value, even when it is 0.0 (an instantly-ending run
        # is a real observation); None only when fit never reported one —
        # `elapsed or None` used to collapse the two
        "elapsed_s": (float(fit_result["elapsed"])
                      if fit_result.get("elapsed") is not None else None),
        # resolved drain shape + the chunk lengths actually dispatched,
        # and WHY auto mode downshifted when it did (None: no clamp)
        "steps_per_call": fit_result.get("steps_per_call"),
        "steps_per_call_clamp": fit_result.get("steps_per_call_clamp"),
        "chunk_sizes": fit_result.get("chunk_sizes"),
        "prefetch_depth": fit_result.get("prefetch_depth"),
        # gradient-collective payload: wire bytes under --grad-compression
        # vs the raw (uncompressed) figure (None: stateless engine)
        "grad_allreduce_bytes": fit_result.get("grad_allreduce_bytes"),
        "grad_allreduce_bytes_raw": fit_result.get(
            "grad_allreduce_bytes_raw"),
        "grad_compression": fit_result.get("grad_compression"),
        # mixed-precision policy (--precision; parallel/precision.py) +
        # the per-device state footprint it moves: param bytes halve
        # under bf16 storage, optimizer bytes grow by a master policy's
        # f32 copy — both gated lower-is-better by `analyze diff`.
        # loss_scale is the fp16 skip-accounting section (None: policy
        # without dynamic scaling).
        "precision": fit_result.get("precision"),
        "param_bytes_per_device": fit_result.get("param_bytes_per_device"),
        "opt_state_bytes_per_device": fit_result.get(
            "opt_state_bytes_per_device"),
        "loss_scale": fit_result.get("loss_scale"),
        # communication/compute overlap (--grad-bucket-mb;
        # parallel/overlap.py): the bucket size in effect, and the
        # exposed-vs-hidden collective split the one-time probe measured
        # (exposed_s is the gated number — BASELINE.md; None = overlap
        # off or probe unsupported, distinguishable from a measured 0.0)
        "grad_bucket_mb": fit_result.get("grad_bucket_mb"),
        "grad_collective_exposed_s": (
            fit_result.get("collective_overlap") or {}).get("exposed_s"),
        "grad_collective_hidden_s": (
            fit_result.get("collective_overlap") or {}).get("hidden_s"),
        "collective_overlap": fit_result.get("collective_overlap"),
        # steady-state percentiles (compile excluded — see StepTimer)
        "compile_s": st.get("compile_s", st.get("first_step_s")),
        "step_time_p50_s": st.get("steady_p50_s"),
        "step_time_p95_s": st.get("steady_p95_s"),
        "step_time_mean_s": st.get("steady_mean_s"),
        # checkpoint cost split (BASELINE.md accounting rule): wait_s is
        # training-thread blocked time — the only part charged against
        # throughput — overlapped_s ran on the background writer behind
        # training.  None when the run had no checkpoint manager.
        "checkpoint_wait_s": fit_result.get("checkpoint_wait_s"),
        "checkpoint_overlapped_s": fit_result.get("checkpoint_overlapped_s"),
        "checkpoint_async": fit_result.get("checkpoint_async"),
        # elastic preemption tolerance (distributed_tensorflow_tpu/
        # elastic/): the graceful-drain outcome (the lease's should_stop
        # reason, None on a normal finish), the resume-side accounting of
        # an --elastic-restore run — preemption_lost_s (save → resume
        # wall-clock gap, the MLPerf time-to-quality cost of the
        # preemption) and resume_replay_steps (steps whose data position
        # could not be restored; 0 = exact resume), both gated
        # lower-is-better by `analyze diff` — plus the step the restore
        # came from, the lease arming record and the straggler summary.
        # None throughout when the run was not elastic — "not an elastic
        # run" stays distinguishable from a measured 0.
        "preempted": fit_result.get("preempted"),
        "preemption_lost_s": fit_result.get("preemption_lost_s"),
        "resume_replay_steps": fit_result.get("resume_replay_steps"),
        "restored_step": fit_result.get("restored_step"),
        "lease": fit_result.get("lease"),
        "stragglers": fit_result.get("stragglers"),
    }

    report["watchdog"] = None if watchdog is None else {
        "beats": watchdog.beats,
        "stall_episodes": watchdog.stall_episodes,
        "timeout_s": watchdog.timeout,
    }

    starvation = fit_result.get("prefetch_starvation")
    report["prefetch"] = None if starvation is None else {
        "depth": fit_result.get("prefetch_depth"),
        "starvation": starvation,
        "fill_wait_s": fit_result.get("prefetch_fill_wait_s"),
    }

    report["metrics_sink"] = None if metrics_logger is None else \
        metrics_logger.stats()

    # numeric-health summary (Trainer fit with the engine's health layer
    # on): anomaly record + run maxima of the per-step stats.  None when
    # health was off — "disabled" stays distinguishable from "healthy".
    report["health"] = fit_result.get("health")

    # serving window (--serve): requests/sec/chip + TTFT/ITL percentiles
    # of the post-training continuous-batching run.  None when serving was
    # off — the section, not its absence, is what `analyze diff` gates.
    report["serve"] = serve

    overhead = 0.0
    if tracer is not None and tracer.enabled:
        report["spans"] = tracer.span_summary()
        tstats = tracer.stats()
        # an ENABLED tracer always reports a dict — written/dropped are
        # ints for a file-backed sink (0 = enabled but idle), None for an
        # aggregate-only tracer (no file).  The old `... or None` collapsed
        # enabled-but-idle into the same None as disabled.
        report["trace"] = {"written": tstats.get("written"),
                           "dropped": tstats.get("dropped")}
        overhead += tracer.overhead_s
    else:
        report["spans"] = None
        report["trace"] = None
    if metrics_logger is not None:
        overhead += getattr(metrics_logger, "overhead_s", 0.0)

    # --timeline sections (None when sampling/ledger are off — "disabled"
    # stays distinguishable from "measured zero"):
    # * `timeline`: per-series digests + the sampler's own measured cost
    #   (the < 1% budget is reported, not assumed);
    # * `xla`: the per-compiled-program memory/compile manifest, with the
    #   two headline keys — peak_hbm_bytes_est (per-program XLA peak
    #   estimates SUMMED per run) and compile_total_s (the `compile`
    #   span total + ledger-observed compiles) — hoisted to the top
    #   level for `analyze diff`'s lower-is-better gates.
    compile_span_s = 0.0
    if tracer is not None and tracer.enabled:
        compile_span_s = (tracer.span_summary().get("compile") or
                          {}).get("total_s", 0.0)
    if timeline is not None:
        report["timeline"] = {
            "interval_s": timeline.interval_s,
            "overhead_s": round(timeline.overhead_s, 6),
            "overhead_frac": (round(timeline.overhead_s / elapsed, 6)
                              if elapsed > 0 else None),
            "series": timeline.summary(),
        }
        overhead += timeline.overhead_s
    else:
        report["timeline"] = None
    if ledger is not None:
        manifest = ledger.manifest()
        report["xla"] = manifest
        report["peak_hbm_bytes_est"] = manifest["peak_hbm_bytes_est"]
        report["compile_total_s"] = round(
            compile_span_s + manifest["compile_total_s"], 6)
    else:
        report["xla"] = None
        report["peak_hbm_bytes_est"] = None
        report["compile_total_s"] = (round(compile_span_s, 6)
                                     if compile_span_s else None)

    # --roofline section: ONLY present when a Roofline was attached —
    # with the flag off the report key set stays byte-identical to
    # round 18 (parity pin; note the contrast with the always-present
    # None sections above, which predate the parity discipline).
    # The train half echoes the Trainer's flag-gated result keys, the
    # serve half points at the serve section's own roofline block, and
    # `programs` is the per-compiled-program attribution table —
    # intensity, compute/bandwidth bound, attainable fraction of peak —
    # from the ledger manifest's cost_analysis columns.
    if roofline is not None:
        from distributed_tensorflow_tpu.observability.roofline import (
            flops_crosscheck, program_attribution)

        rf_train = {
            "model_flops_per_step": fit_result.get(
                "train_model_flops_per_step"),
            "achieved_flops_per_sec": fit_result.get(
                "train_achieved_flops_per_sec"),
            "mfu": fit_result.get("train_mfu"),
        }
        programs = None
        if ledger is not None:
            manifest = report["xla"] or {}
            programs = program_attribution(
                manifest.get("programs", {}),
                peaks=roofline.peaks, dtype=roofline.dtype)
            # analytic-vs-XLA cross-check on the train step: the ratio of
            # XLA's counted flops to the analytic model flops (None when
            # either side is missing; ~3x is remat's signature)
            xla_train = next(
                (rec.get("flops")
                 for name, rec in manifest.get("programs", {}).items()
                 if "train" in name and rec.get("flops")), None)
            rf_train["xla_flops_crosscheck"] = flops_crosscheck(
                rf_train["model_flops_per_step"], xla_train)
        report["roofline"] = {
            "device": roofline.describe(),
            "train": rf_train,
            "serve": (serve or {}).get("roofline"),
            "programs": programs,
        }
        # hoisted for `analyze diff`'s higher-is-better gate (the serve
        # keys flatten from the serve section's serve_* prefix already)
        report["train_mfu"] = fit_result.get("train_mfu")

    # execution environment (jax version, device kind, effective XLA
    # flags): bench/report trajectories stay attributable across
    # containers — the r03–r05 measurement-blackout lesson
    report["environment"] = runtime_environment()

    # the telemetry's own measured cost, against the run's wall clock —
    # this is the number the 5%-overhead acceptance bound reads
    report["telemetry_overhead_s"] = round(overhead, 6)
    report["telemetry_overhead_frac"] = (
        round(overhead / elapsed, 6) if elapsed > 0 else None)
    return report
