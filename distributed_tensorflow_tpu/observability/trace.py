"""Structured trace spans: a monotonic-clock JSONL event timeline.

A span is one named region of host-side work — ``compile``,
``chunk_dispatch``, ``materialize``, ``checkpoint``, ``eval`` are the
Trainer's vocabulary — recorded as one JSONL event at span exit:

    {"schema_version": 1, "event": "span", "name": "chunk_dispatch",
     "t": <monotonic start>, "dur_s": 0.0021, "run": "r-1a2b3c",
     "host": "tpu-vm-0", "pid": 12345, "process": 0, ...attrs}

plus ``event``/``gauge``/``counter`` instants with the same envelope.
Timestamps are ``time.monotonic()`` — orderable within a run, immune to
wall-clock steps; each record also carries run/host/process ids so pod
timelines from many processes can be merged and disentangled.

Two design points keep this zero-downshift:

* Emission is an ``AsyncJsonlSink.write`` (one queue put) — and when no
  ``path`` is configured the tracer still aggregates per-name
  count/total/max in memory (two ``perf_counter`` calls and a dict update
  per span), which is what the end-of-run report reads.  The Trainer's
  spans are per *chunk*, not per step, so even the file-backed cost is
  amortized k×.
* Spans enter a ``jax.profiler.TraceAnnotation`` with the same name, so
  when an XProf window (``--profile-dir``, utils/metrics.profile) is
  open, the span timeline and the XLA profile share names — one
  vocabulary across both tools.

``NULL_TRACER`` is the do-nothing default: callers instrument
unconditionally and pay nothing when observability is off.

The tracer also tracks its own cost (``overhead_s``): time spent inside
span bookkeeping and event emission, surfaced by the run report so the
"telemetry within 5% of telemetry-off" budget is measured, not assumed.
"""

from __future__ import annotations

import contextlib
import os
import socket
import threading
import time
import uuid
from pathlib import Path
from typing import Any, Iterator

from distributed_tensorflow_tpu.observability.sink import AsyncJsonlSink


class _NullTracer:
    """Inert tracer: the default for uninstrumented runs.  Every method is
    a no-op; ``span`` yields immediately."""

    enabled = False
    overhead_s = 0.0

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[None]:
        yield

    def event(self, name: str, **fields: Any) -> None:
        pass

    def gauge(self, name: str, value: float, **fields: Any) -> None:
        pass

    def counter(self, name: str, inc: int = 1, **fields: Any) -> None:
        pass

    def span_summary(self) -> dict:
        return {}

    def stats(self) -> dict:
        return {}

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


NULL_TRACER = _NullTracer()


def _profiler_annotation(name: str):
    """``jax.profiler.TraceAnnotation`` for the span name, or a null
    context when jax (or the profiler) is unavailable — the tracer must
    not force a jax import on pure-host users."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - jax always present in this repo
        return contextlib.nullcontext()


class Tracer:
    """Span/event recorder (see module docstring).

    ``path=None`` → aggregate-only: spans update the in-memory per-name
    summary (for the run report) but no file is written.  ``annotate``
    mirrors span names into XProf via ``TraceAnnotation``.
    """

    enabled = True

    def __init__(self, path: str | Path | None = None,
                 run_id: str | None = None, process_index: int = 0,
                 annotate: bool = True, sink: AsyncJsonlSink | None = None):
        self.run_id = run_id or f"r-{uuid.uuid4().hex[:8]}"
        self.host = socket.gethostname()
        self.pid = os.getpid()
        self.process_index = process_index
        self.overhead_s = 0.0
        self._annotate = annotate
        self._sink = sink if sink is not None else (
            AsyncJsonlSink(path) if path else None)
        # per-name aggregates: name -> [count, total_s, max_s].  The
        # read-modify-write updates are lock-guarded: the serving fleet
        # (serving/fleet.py) shares ONE tracer across N replica worker
        # threads, and concurrent span exits would otherwise lose counts
        # (the JSONL sink is queue-based and was already thread-safe)
        self._agg_lock = threading.Lock()
        self._spans: dict[str, list] = {}
        self._counters: dict[str, int] = {}
        if self._sink is not None:
            self.event("trace_start", wall_time=time.time())

    # ------------------------------------------------------------ emission
    def _emit(self, record: dict[str, Any]) -> None:
        if self._sink is not None:
            self._sink.write({
                **record,
                "run": self.run_id, "host": self.host, "pid": self.pid,
                "process": self.process_index,
            })

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[dict]:
        """Time a named region; one JSONL event at exit, plus the in-memory
        aggregate the run report reads.  Yields the span's attr dict —
        keys added to it BEFORE exit ride the emitted record, which is how
        the serving scheduler attaches per-request phase attribution
        (queue_wait_s/prefill_s/decode_s) computed only at finish."""
        t_mono = time.monotonic()
        t0 = time.perf_counter()
        ctx = _profiler_annotation(name) if self._annotate \
            else contextlib.nullcontext()
        with ctx:
            try:
                yield attrs
            finally:
                dur = time.perf_counter() - t0
                t_book = time.perf_counter()
                with self._agg_lock:
                    agg = self._spans.setdefault(name, [0, 0.0, 0.0])
                    agg[0] += 1
                    agg[1] += dur
                    agg[2] = max(agg[2], dur)
                self._emit({"event": "span", "name": name, "t": t_mono,
                            "dur_s": dur, **attrs})
                self.overhead_s += time.perf_counter() - t_book

    def event(self, name: str, **fields: Any) -> None:
        t0 = time.perf_counter()
        self._emit({"event": "event", "name": name, "t": time.monotonic(),
                    **fields})
        self.overhead_s += time.perf_counter() - t0

    def gauge(self, name: str, value: float, **fields: Any) -> None:
        t0 = time.perf_counter()
        self._emit({"event": "gauge", "name": name, "t": time.monotonic(),
                    "value": value, **fields})
        self.overhead_s += time.perf_counter() - t0

    def counter(self, name: str, inc: int = 1, **fields: Any) -> None:
        t0 = time.perf_counter()
        with self._agg_lock:
            self._counters[name] = total = \
                self._counters.get(name, 0) + inc
        self._emit({"event": "counter", "name": name, "t": time.monotonic(),
                    "inc": inc, "total": total, **fields})
        self.overhead_s += time.perf_counter() - t0

    # ------------------------------------------------------------- summary
    def span_summary(self) -> dict[str, dict[str, float]]:
        """Per-name {count, total_s, max_s} — the run report's span table."""
        return {name: {"count": c, "total_s": tot, "max_s": mx}
                for name, (c, tot, mx) in sorted(self._spans.items())}

    def stats(self) -> dict[str, Any]:
        out: dict[str, Any] = {"overhead_s": self.overhead_s,
                               "counters": dict(self._counters)}
        if self._sink is not None:
            out.update(self._sink.stats())
        return out

    def flush(self) -> None:
        """Drain the queued records to disk without closing the sink —
        the Trainer's failure-path cleanup calls this so no buffered span
        outlives a raising fit."""
        if self._sink is not None:
            self._sink.flush()

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()

    def __enter__(self) -> "Tracer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
