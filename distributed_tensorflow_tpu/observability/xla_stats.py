"""Per-compiled-program XLA memory/compile ledger.

Hardware has been blind since BENCH_r02, yet XLA reports HBM footprint
and compile cost for free on every backend: ``compiled.memory_analysis()``
carries argument/output/temp/generated-code bytes per executable (the
tests already read it on CPU), and compile wall-time is one
``perf_counter`` pair around ``lower().compile()``.  :class:`ProgramLedger`
captures both without changing what runs:

* ``ledger.jit(fn, name=...)`` replaces a ``jax.jit(fn)`` call site.
  With no ledger (flag off) the call site uses ``jax.jit`` literally, so
  the compiled-program set is byte-identical — the PR 11 parity
  discipline.  With a ledger, the wrapper AOT-compiles on first call per
  abstract argument signature (``jax.jit(fn).lower(*args).compile()``),
  times the compile, records the executable's memory analysis, then
  dispatches the cached executable — same program, one extra host-side
  bookkeeping pass at compile time, zero per-call device syncs.
* ``capture(name, lowered_or_compiled)`` records programs compiled
  elsewhere (the bench harness already AOT-lowers the train step for
  ``cost_analysis`` — the same executable yields its memory analysis at
  no extra compile).

``peak_bytes_est`` per program is ``argument + output + temp − alias``
bytes — XLA's own live-footprint decomposition; ``manifest()`` sums it
per run (every program's buffers are resident in a serving process) and
totals compile seconds.  ``analyze programs --against BASELINE`` diffs
two manifests: a new program or temp-bytes growth past a threshold exits
nonzero — the reusable form of today's hand-written program-set pins.

If AOT lowering fails for a call site (exotic shardings, backend quirks),
the wrapper falls back to plain ``jax.jit`` dispatch and records the
program name with ``compile_s`` only — observability must never take the
serving path down.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

# jax is imported lazily inside the jit path: the manifest/diff half of
# this module is what the stdlib-only `analyze programs` CLI imports,
# and it must not pay (or require) a jax import

_MEM_FIELDS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
)


def cost_fields(compiled) -> dict[str, Any]:
    """Extract XLA ``cost_analysis`` flops/bytes from a compiled
    executable — the same executable whose memory_analysis the ledger
    already reads, at zero extra compiles.  None-tolerant: CPU backends
    may report nothing, and rounds 19's roofline attribution treats a
    None column as "no data", never as zero work."""
    out: dict[str, Any] = {"flops": None, "bytes_accessed": None}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if ca is not None:
            flops = float(ca.get("flops", 0.0) or 0.0)
            nbytes = float(ca.get("bytes accessed", 0.0) or 0.0)
            out["flops"] = flops if flops > 0 else None
            out["bytes_accessed"] = nbytes if nbytes > 0 else None
    except Exception:
        pass
    return out


def memory_fields(compiled) -> dict[str, int]:
    """Extract the memory-analysis byte fields from a compiled executable,
    zeros when the backend reports nothing (memory_analysis may be None
    or partial off-TPU)."""
    out = {dst: 0 for _, dst in _MEM_FIELDS}
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        for src, dst in _MEM_FIELDS:
            try:
                out[dst] = int(getattr(mem, src, 0) or 0)
            except Exception:
                pass
    # XLA's live-footprint decomposition: arguments + outputs + temps
    # minus donated/aliased bytes counted twice
    out["peak_bytes_est"] = max(
        out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
        - out["alias_bytes"], 0)
    return out


def _abstract_signature(args: tuple) -> tuple:
    """Hashable (treedef, per-leaf shape/dtype) key — one compile per
    distinct abstract signature, mirroring jax.jit's own cache key."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    return treedef, tuple(
        (tuple(getattr(x, "shape", ())), str(getattr(x, "dtype", type(x))),
         bool(getattr(x, "weak_type", False)))
        for x in leaves)


class _ObservedJit:
    """Callable standing in for one ``jax.jit(fn)``: AOT-compiles per
    abstract signature with timing + memory capture, dispatches the
    cached executable thereafter."""

    def __init__(self, ledger: "ProgramLedger", fn: Callable, name: str,
                 **jit_kwargs: Any):
        import jax

        self._ledger = ledger
        self._jitted = jax.jit(fn, **jit_kwargs)
        self._name = name
        self._compiled: dict[tuple, Callable] = {}

    def __call__(self, *args):
        sig = _abstract_signature(args)
        compiled = self._compiled.get(sig)
        if compiled is None:
            t0 = time.perf_counter()
            try:
                compiled = self._jitted.lower(*args).compile()
            except Exception:
                # fall back to the plain jitted callable: its first call
                # still compiles (timed below), but no memory analysis
                compiled = self._jitted
                self._compiled[sig] = compiled
                out = compiled(*args)
                self._ledger._record(self._name, None,
                                     time.perf_counter() - t0)
                return out
            self._compiled[sig] = compiled
            self._ledger.capture(self._name, compiled,
                                 compile_s=time.perf_counter() - t0)
        return compiled(*args)


class ProgramLedger:
    """Named per-program memory/compile records (module docstring).
    Thread-safe: the serving fleet's replica workers compile through one
    shared ledger."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> {compiles, compile_s, <memory fields>}
        self._programs: dict[str, dict[str, Any]] = {}

    # ------------------------------------------------------------- capture
    def jit(self, fn: Callable, name: str, **jit_kwargs: Any) -> _ObservedJit:
        """Observed replacement for ``jax.jit(fn, **jit_kwargs)``.  Call
        sites select it with ``jax.jit if ledger is None else ledger.jit``
        so the flag-off path is the literal builtin."""
        return _ObservedJit(self, fn, name, **jit_kwargs)

    def capture(self, name: str, compiled, compile_s: float = 0.0) -> None:
        """Record a compiled executable's memory analysis under ``name``
        (programs compiled elsewhere — bench's AOT train step — enter
        here at zero extra compile cost).  Cost-analysis flops/bytes ride
        the same executable (round 19's roofline columns)."""
        self._record(name, {**memory_fields(compiled),
                            **cost_fields(compiled)}, compile_s)

    def _record(self, name: str, mem: dict[str, int] | None,
                compile_s: float) -> None:
        with self._lock:
            rec = self._programs.get(name)
            if rec is None:
                rec = self._programs[name] = {
                    "compiles": 0, "compile_s": 0.0,
                    **{dst: 0 for _, dst in _MEM_FIELDS},
                    "peak_bytes_est": 0,
                    # cost_analysis columns (round 19): None until a
                    # backend reports them — None is "no data", never 0
                    "flops": None, "bytes_accessed": None}
            rec["compiles"] += 1
            rec["compile_s"] += float(compile_s)
            if mem is not None:
                # identical recompiles (fleet replicas) report identical
                # bytes — keep the max so a heterogeneous same-name
                # program surfaces its worst case
                for k, v in mem.items():
                    if v is None:
                        continue
                    if rec.get(k) is None:
                        rec[k] = v if k in ("flops", "bytes_accessed") \
                            else int(v)
                    else:
                        rec[k] = max(rec[k], v if k in
                                     ("flops", "bytes_accessed") else int(v))

    # ------------------------------------------------------------- reading
    def programs(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {name: dict(rec)
                    for name, rec in sorted(self._programs.items())}

    def compile_total_s(self) -> float:
        with self._lock:
            return sum(rec["compile_s"] for rec in self._programs.values())

    def peak_hbm_bytes_est(self) -> int:
        """Per-run peak estimate: per-program peaks SUMMED — every
        program's buffers stay resident in a long-lived serving process
        (BASELINE.md "Memory/compile accounting" states the semantics
        and its bias vs measured HBM)."""
        with self._lock:
            return sum(rec["peak_bytes_est"]
                       for rec in self._programs.values())

    def manifest(self) -> dict[str, Any]:
        """JSON-ready ledger: the ``analyze programs`` input."""
        return {
            "schema_version": 1,
            "programs": self.programs(),
            "program_count": len(self._programs),
            "peak_hbm_bytes_est": self.peak_hbm_bytes_est(),
            "compile_total_s": self.compile_total_s(),
        }


def diff_manifests(current: dict[str, Any], baseline: dict[str, Any],
                   temp_threshold: float = 0.10,
                   flops_threshold: float = 0.10) -> list[dict[str, Any]]:
    """Program-set drift between two manifests (stdlib-only — analyze
    imports this logic's twin; kept here so library users gate in-process).

    Returns a list of findings; empty means no drift.  A finding is a
    program ADDED vs baseline, or one whose ``temp_bytes`` grew more than
    ``temp_threshold`` (relative; absolute growth when baseline is 0).
    FLOPs growth past ``flops_threshold`` WARNS (``severity: warn``) the
    way temp-bytes growth fails — more model work per call is worth a
    look but legitimate config changes move it, so it never exits the
    gate nonzero on its own; None columns (CPU backends) compare as "no
    data" and are skipped.  Removed programs are reported as
    informational (``severity: info``) — shrinking the program set never
    fails the gate."""
    cur = current.get("programs", {})
    base = baseline.get("programs", {})
    findings: list[dict[str, Any]] = []
    for name in sorted(cur):
        if name not in base:
            findings.append({
                "severity": "fail", "kind": "program_added", "name": name,
                "detail": f"program {name!r} not in baseline"})
            continue
        t_cur = int(cur[name].get("temp_bytes", 0))
        t_base = int(base[name].get("temp_bytes", 0))
        if t_base <= 0:
            grew = t_cur > 0
            rel = None
        else:
            rel = (t_cur - t_base) / t_base
            grew = rel > temp_threshold
        if grew:
            findings.append({
                "severity": "fail", "kind": "temp_bytes_grew", "name": name,
                "baseline": t_base, "current": t_cur, "relative": rel,
                "threshold": temp_threshold,
                "detail": (f"temp bytes {t_base} -> {t_cur} "
                           f"(threshold {temp_threshold:.0%})")})
        f_cur = cur[name].get("flops")
        f_base = base[name].get("flops")
        if f_cur is not None and f_base is not None and f_base > 0:
            f_rel = (float(f_cur) - float(f_base)) / float(f_base)
            if f_rel > flops_threshold:
                findings.append({
                    "severity": "warn", "kind": "flops_grew", "name": name,
                    "baseline": f_base, "current": f_cur,
                    "relative": f_rel, "threshold": flops_threshold,
                    "detail": (f"flops {f_base:.3g} -> {f_cur:.3g} "
                               f"(threshold {flops_threshold:.0%})")})
    for name in sorted(set(base) - set(cur)):
        findings.append({
            "severity": "info", "kind": "program_removed", "name": name,
            "detail": f"program {name!r} gone vs baseline"})
    return findings
