"""Numeric training-health stats, computed ON DEVICE inside the step.

PR 2 built the telemetry transport (sink/trace/report); this module puts a
numeric-health signal on it.  The old divergence check was host-side and
loss-only (``utils/failure.check_finite`` at log cadence) — it misses
exploding grad norms, silent weight blow-ups and per-leaf non-finites until
the loss is already garbage.  Here every step carries, stacked through the
``lax.scan`` trajectory exactly like loss/accuracy:

  grad_norm        global L2 of the raw gradient (pre-optimizer)
  param_norm       global L2 of the parameters entering the update
  update_norm      global L2 of the applied update (post-optimizer Δp)
  update_ratio     ‖Δp‖ / ‖p‖ — the classic step-sanity number
  nonfinite_count  leaves whose post-update params contain NaN/inf
  loss_spike       loss / bias-corrected running EMA of the loss

The capture rides the OPTIMIZER, not the engines: ``wrap_optimizer`` chains
two pass-through ``optax`` transforms around the engine's ``tx`` — one
before it (sees the raw gradients) and one after (sees the final updates
and the parameters) — whose *states* hold the scalars.  Every engine funnels
its cross-device-reduced gradients through ``self.tx.update``, so one hook
covers sync/async/gossip/fsdp/tp/ep/sp/pipeline without touching their step
programs.  ``Engine.enable_health`` installs the wrap and the base
``step``/``build_many_step`` hooks read the scalars back out of the NEW
``opt_state`` inside the jit (``from_opt_state``) and merge them into the
step metrics.  With health OFF nothing is wrapped and nothing is read — the
compiled program is byte-for-byte the pre-health one (the same discipline
as ``--grad-compression none``).

Engines whose state stacks per-device copies (async local SGD, gossip)
carry the capture scalars with that leading axis; ``from_opt_state``
reduces them — worst device for the norms/ratio, sum for the non-finite
count — so the reported stat is the one an operator wants paged about.

``detect_anomalies`` is the host-side policy half: given one step's
materialized floats and thresholds, it names every offending stat.  The
Trainer runs it per step at chunk flush (``--on-anomaly warn|halt``),
subsuming the loss-only nan_guard.

``HealthConfig.inject_nan_at`` is a TEST hook: it scales the gradients of
one optimizer step by ``inject_scale`` (default inf) inside the capture
transform, so the detection path is testable end to end on any engine.
Python-level gated — ``None`` leaves the program untouched.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

# the per-step stats the health layer adds to the metrics trajectory
HEALTH_KEYS = ("grad_norm", "param_norm", "update_norm", "update_ratio",
               "nonfinite_count", "loss_spike")

_EPS = 1e-12


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Thresholds of the anomaly policy + the EMA shape of the spike score.

    Defaults are deliberately loose — they flag pathology (a step that
    rewrites the whole model, a 10× loss jump, any non-finite), not noisy
    training.  ``max_grad_norm`` is None (disabled) because a sane ceiling
    is model-scale-dependent; non-finite grad norms are always flagged.
    """

    ema_decay: float = 0.9           # loss EMA decay (bias-corrected)
    loss_spike_factor: float = 10.0  # anomaly: loss > factor × EMA
    max_update_ratio: float = 1.0    # anomaly: ‖Δp‖/‖p‖ above this
    max_grad_norm: float | None = None  # anomaly ceiling (None: disabled)
    # TEST hook: scale the gradients of this 1-based optimizer step by
    # inject_scale (inf → the seeded-NaN acceptance scenario).  None (the
    # default) compiles to the unmodified program.
    inject_nan_at: int | None = None
    inject_scale: float = float("inf")


class GradCaptureState(NamedTuple):
    """Pre-optimizer capture: raw-gradient norm + optimizer-step count."""

    count: jax.Array      # optimizer updates applied so far (1-based)
    grad_norm: jax.Array


class UpdateCaptureState(NamedTuple):
    """Post-optimizer capture: parameter/update norms and non-finites."""

    param_norm: jax.Array
    update_norm: jax.Array
    update_ratio: jax.Array
    nonfinite_count: jax.Array


def global_norm(tree: Any) -> jax.Array:
    """Global L2 norm over every leaf, accumulated in f32 (bf16 leaves
    would overflow their own square sums)."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(jnp.asarray(l, jnp.float32)))
                        for l in leaves))


def nonfinite_leaf_count(tree: Any) -> jax.Array:
    """Number of floating LEAVES containing any NaN/inf (integer leaves
    cannot be non-finite and are skipped)."""
    leaves = [l for l in jax.tree.leaves(tree)
              if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)]
    if not leaves:
        return jnp.zeros((), jnp.int32)
    return sum(jnp.any(~jnp.isfinite(l)).astype(jnp.int32) for l in leaves)


def _grad_capture(config: HealthConfig) -> optax.GradientTransformation:
    """Pass-through transform BEFORE the optimizer: records the global
    gradient norm (and applies the test-only NaN injection)."""

    def init(params):
        del params
        return GradCaptureState(count=jnp.zeros((), jnp.int32),
                                grad_norm=jnp.zeros((), jnp.float32))

    def update(updates, state, params=None):
        del params
        count = state.count + 1
        if config.inject_nan_at is not None:  # python gate: test hook only
            scale = jnp.where(count == config.inject_nan_at,
                              jnp.float32(config.inject_scale),
                              jnp.float32(1.0))
            updates = jax.tree.map(lambda g: g * scale.astype(g.dtype),
                                   updates)
        return updates, GradCaptureState(count=count,
                                         grad_norm=global_norm(updates))

    return optax.GradientTransformation(init, update)


def _update_capture() -> optax.GradientTransformation:
    """Pass-through transform AFTER the optimizer: records ‖p‖, ‖Δp‖,
    their ratio, and the non-finite leaf count of the post-update params
    (``apply_updates`` is ``p + Δp``, recomputed here leaf-wise so the
    count reflects what the next step will train on)."""

    def init(params):
        del params
        # distinct arrays per field: donated states must not alias one
        # zero buffer across leaves (double-donation is a runtime error)
        return UpdateCaptureState(param_norm=jnp.zeros((), jnp.float32),
                                  update_norm=jnp.zeros((), jnp.float32),
                                  update_ratio=jnp.zeros((), jnp.float32),
                                  nonfinite_count=jnp.zeros((), jnp.int32))

    def update(updates, state, params=None):
        del state
        if params is None:
            raise ValueError(
                "health capture needs tx.update(grads, opt_state, params) — "
                "every engine in this repo passes params; a custom caller "
                "must too")
        pn = global_norm(params)
        un = global_norm(updates)
        new_params = jax.tree.map(lambda p, u: p + u.astype(p.dtype)
                                  if jnp.issubdtype(jnp.asarray(p).dtype,
                                                    jnp.floating) else p,
                                  params, updates)
        return updates, UpdateCaptureState(
            param_norm=pn, update_norm=un,
            update_ratio=un / jnp.maximum(pn, _EPS),
            nonfinite_count=nonfinite_leaf_count(new_params))

    return optax.GradientTransformation(init, update)


def wrap_optimizer(tx: optax.GradientTransformation,
                   config: HealthConfig) -> optax.GradientTransformation:
    """``chain(grad_capture, tx, update_capture)`` — the whole install."""
    return optax.chain(_grad_capture(config), tx, _update_capture())


def _find_capture(opt_state: Any, typ: type) -> list:
    found: list = []

    def visit(x):
        if isinstance(x, typ):
            found.append(x)
        return x

    jax.tree.map(visit, opt_state, is_leaf=lambda x: isinstance(x, typ))
    return found


def from_opt_state(opt_state: Any) -> dict[str, jax.Array]:
    """Read the captured health scalars back out of a (possibly nested,
    possibly per-device-stacked) optimizer state.  Norms/ratio reduce with
    ``max`` (worst device copy is the one to page about), the non-finite
    count with ``sum``."""
    grads = _find_capture(opt_state, GradCaptureState)
    upds = _find_capture(opt_state, UpdateCaptureState)
    if not grads or not upds:
        raise ValueError(
            "no health capture state in opt_state — call "
            "Engine.enable_health() BEFORE init_state()/the first step, so "
            "the optimizer tree gains its capture slots")
    g, u = grads[0], upds[0]
    return {
        "grad_norm": jnp.max(g.grad_norm).astype(jnp.float32),
        "param_norm": jnp.max(u.param_norm).astype(jnp.float32),
        "update_norm": jnp.max(u.update_norm).astype(jnp.float32),
        "update_ratio": jnp.max(u.update_ratio).astype(jnp.float32),
        "nonfinite_count": jnp.sum(u.nonfinite_count).astype(jnp.int32),
    }


# --------------------------------------------------------------- loss EMA

def ema_init() -> tuple[jax.Array, jax.Array]:
    """(ema_value, step_count) carry — threaded through the scan so the
    spike score is computed on device, k-invariantly."""
    return (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32))


def ema_spike(loss: jax.Array, ema: tuple[jax.Array, jax.Array],
              config: HealthConfig):
    """(spike_score, new_ema): loss over the bias-corrected running EMA of
    the loss (Adam-style correction, so early steps are not judged against
    the zero init).  The first step scores 1.0 by definition."""
    val, t = ema
    loss32 = jnp.asarray(loss, jnp.float32)
    decay = jnp.float32(config.ema_decay)
    corrected = val / jnp.maximum(1.0 - decay ** t.astype(jnp.float32),
                                  _EPS)
    spike = jnp.where(t > 0, loss32 / jnp.maximum(corrected, _EPS),
                      jnp.float32(1.0))
    new = (decay * val + (1.0 - decay) * loss32, t + 1)
    return spike, new


# --------------------------------------------------------- anomaly policy

def detect_anomalies(floats: dict[str, float],
                     config: HealthConfig) -> list[dict[str, Any]]:
    """Host-side policy over ONE step's materialized metrics: returns one
    record per offending stat — ``{"stat", "value", "limit", "reason",
    "kind"}`` — empty when the step is healthy.  ``kind`` separates
    ``'nonfinite'`` (divergence: NaN/inf anywhere — the class the legacy
    nan_guard made fatal) from ``'threshold'`` (a finite value past its
    ceiling); the threshold checks only fire on finite values (a NaN
    comparison would silently pass them)."""
    out: list[dict[str, Any]] = []

    def flag(stat: str, value, limit, reason: str, kind: str) -> None:
        out.append({"stat": stat, "value": value, "limit": limit,
                    "reason": reason, "kind": kind})

    nf = floats.get("nonfinite_count")
    if nf is not None and nf > 0:
        flag("nonfinite_count", nf, 0,
             "non-finite values in the updated parameters", "nonfinite")
    for stat in ("loss", "grad_norm", "update_ratio", "loss_spike"):
        v = floats.get(stat)
        if v is not None and not math.isfinite(v):
            flag(stat, v, None, "non-finite", "nonfinite")
    gn = floats.get("grad_norm")
    if (config.max_grad_norm is not None and gn is not None
            and math.isfinite(gn) and gn > config.max_grad_norm):
        flag("grad_norm", gn, config.max_grad_norm,
             "gradient norm above ceiling", "threshold")
    ur = floats.get("update_ratio")
    if ur is not None and math.isfinite(ur) and ur > config.max_update_ratio:
        flag("update_ratio", ur, config.max_update_ratio,
             "update rewrote too much of the model in one step", "threshold")
    ls = floats.get("loss_spike")
    if ls is not None and math.isfinite(ls) and ls > config.loss_spike_factor:
        flag("loss_spike", ls, config.loss_spike_factor,
             "loss spiked vs its running EMA", "threshold")
    return out
