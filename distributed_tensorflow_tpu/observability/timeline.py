"""Time-series telemetry: bounded gauge ring buffers + a throttled sampler.

ROADMAP item 2 (queue-driven autoscaling) needs the signals the serving
stack already computes — queue depth, active slots, KV blocks in use,
per-replica load — as *time series*, not end-of-window scalars.
:class:`GaugeSeries` is the storage: a **bounded ring buffer** of
``(t_mono, wall, value)`` samples —

* ``record`` is O(1): one list write at a rotating index, no allocation
  after warm-up and no growth proportional to run length;
* exact totals ride alongside (count/sum/min/max over EVERY sample ever
  recorded, like ``LogHistogram``), so the retained window never lies
  about the extremes;
* two series **merge by time order** — ``merge`` produces exactly what
  one series recording both sample streams would hold (the
  merge≡record-all law the tests pin), so per-replica series fold into
  fleet series without resampling;
* ``to_dict``/``from_dict`` round-trip the full state.

:class:`Timeline` is the named-series front callers sample into at
existing chunk/iteration boundaries (``tl.sample_many({...})``), with a
**per-series minimum interval** (the ``--timeline-interval`` cadence) so
a tight decode loop costs one ``monotonic()`` call per skipped sample,
and a self-measured ``overhead_s`` so the "< 1% of run wall time" budget
is measured, not assumed.  Flag-off is ``timeline=None`` at every call
site — no wrapper, no branch cost beyond one ``is not None``.

``emit(tracer)`` writes each series as ONE ``timeline_series`` JSONL
event (bulk samples, not a record per sample), which is how
``analyze timeline`` and the Perfetto counter-track export work from the
trace file alone.  Deliberately stdlib-only (math/time) — the offline
``analyze`` CLI and pure-host tests import this without jax.
"""

from __future__ import annotations

import math
import time
from typing import Any, Callable, Iterable, Mapping

from distributed_tensorflow_tpu.observability.metrics import exact_percentile


class GaugeSeries:
    """Bounded ring buffer of ``(t_mono, wall, value)`` gauge samples
    (module docstring).  ``capacity`` bounds retained samples; exact
    count/sum/min/max cover every sample ever recorded."""

    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self._buf: list[tuple[float, float, float] | None] = \
            [None] * self.capacity
        self._head = 0          # next write index
        self._n = 0             # retained samples (<= capacity)
        self.count = 0          # every sample ever recorded
        self.sum = 0.0
        self.vmin: float | None = None
        self.vmax: float | None = None

    # ------------------------------------------------------------- record
    def record(self, value: float, t_mono: float | None = None,
               wall: float | None = None) -> None:
        """O(1): one ring write + four scalar updates.  ``t_mono``/``wall``
        default to now — passing them lets a sampler batch one clock read
        across many series."""
        t = time.monotonic() if t_mono is None else float(t_mono)
        w = time.time() if wall is None else float(wall)
        v = float(value)
        self._buf[self._head] = (t, w, v)
        self._head = (self._head + 1) % self.capacity
        self._n = min(self._n + 1, self.capacity)
        self.count += 1
        self.sum += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)

    @property
    def dropped(self) -> int:
        """Samples overwritten by the ring bound (count − retained)."""
        return self.count - self._n

    def samples(self) -> list[tuple[float, float, float]]:
        """Retained samples in recording order (oldest first)."""
        if self._n < self.capacity:
            return [s for s in self._buf[:self._n]]
        return [s for s in (self._buf[self._head:] + self._buf[:self._head])]

    def values(self) -> list[float]:
        return [s[2] for s in self.samples()]

    # ------------------------------------------------------------- merge
    def merge(self, other: "GaugeSeries") -> "GaugeSeries":
        """Fold ``other`` into this series: retained samples interleave by
        monotonic time and the most recent ``capacity`` survive — EXACTLY
        what one series recording both streams in time order would hold
        (the merge≡record-all test pins this).  Exact totals add."""
        merged = sorted(self.samples() + other.samples(), key=lambda s: s[0])
        keep = merged[-self.capacity:]
        self._buf = keep + [None] * (self.capacity - len(keep))
        self._head = len(keep) % self.capacity
        self._n = len(keep)
        self.count += other.count
        self.sum += other.sum
        for v in (other.vmin, other.vmax):
            if v is not None:
                self.vmin = v if self.vmin is None else min(self.vmin, v)
                self.vmax = v if self.vmax is None else max(self.vmax, v)
        return self

    # ----------------------------------------------------------- analysis
    def auc(self) -> float | None:
        """Trapezoidal value·seconds over the retained window — the
        ``queue_depth_auc`` integral (requests·s of queueing the
        autoscaler minimizes).  None until two samples exist."""
        s = self.samples()
        if len(s) < 2:
            return None
        return sum((s[i + 1][0] - s[i][0]) * (s[i][2] + s[i + 1][2]) / 2.0
                   for i in range(len(s) - 1))

    def summary(self) -> dict[str, Any]:
        """JSON-ready digest: exact totals + retained-window stats."""
        vals = self.values()
        s = self.samples()
        return {
            "count": self.count,
            "retained": self._n,
            "dropped": self.dropped,
            "mean": (self.sum / self.count) if self.count else None,
            "min": self.vmin,
            "max": self.vmax,
            "last": vals[-1] if vals else None,
            "p50": exact_percentile(vals, 0.50),
            "p95": exact_percentile(vals, 0.95),
            "auc": self.auc(),
            "duration_s": (s[-1][0] - s[0][0]) if len(s) > 1 else 0.0,
        }

    # ----------------------------------------------------------- serialize
    def to_dict(self) -> dict[str, Any]:
        return {
            "capacity": self.capacity,
            "samples": [list(s) for s in self.samples()],
            "count": self.count,
            "sum": self.sum,
            "vmin": self.vmin,
            "vmax": self.vmax,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "GaugeSeries":
        g = cls(capacity=int(d["capacity"]))
        for t, w, v in d.get("samples", []):
            g._buf[g._head] = (float(t), float(w), float(v))
            g._head = (g._head + 1) % g.capacity
            g._n = min(g._n + 1, g.capacity)
        g.count = int(d.get("count", g._n))
        g.sum = float(d.get("sum", 0.0))
        g.vmin = d.get("vmin")
        g.vmax = d.get("vmax")
        return g


def _series_key(name: str, replica: int | None) -> str:
    return name if replica is None else f"{name}@r{replica}"


def split_series_key(key: str) -> tuple[str, int | None]:
    """Inverse of the ``name@rN`` per-replica key convention (the analyze
    CLI groups per-replica lanes with this)."""
    if "@r" in key:
        name, _, rid = key.rpartition("@r")
        if rid.isdigit():
            return name, int(rid)
    return key, None


class Timeline:
    """Named gauge series + the throttled sampling front (module
    docstring).  One Timeline instance spans a run; providers from many
    components (scheduler, fleet, kv, trainer) sample into it, with
    per-replica series keyed ``name@rN``."""

    def __init__(self, interval_s: float = 0.05, capacity: int = 512,
                 clock: Callable[[], float] | None = None):
        if interval_s < 0:
            raise ValueError(
                f"interval_s must be >= 0, got {interval_s}")
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self.overhead_s = 0.0   # self-measured sampler bookkeeping cost
        self._mono = clock if clock is not None else time.monotonic
        self._series: dict[str, GaugeSeries] = {}
        self._last_t: dict[str, float] = {}   # per throttle group

    # ------------------------------------------------------------ sampling
    def series(self, name: str, replica: int | None = None) -> GaugeSeries:
        key = _series_key(name, replica)
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = GaugeSeries(capacity=self.capacity)
        return s

    def sample(self, name: str, value: float,
               replica: int | None = None) -> bool:
        """Throttled single-gauge sample; returns whether it recorded."""
        return self.sample_many({name: value}, replica=replica,
                                group=_series_key(name, replica))

    def sample_many(self, values: Mapping[str, float],
                    replica: int | None = None,
                    group: str = "") -> bool:
        """Record a batch of gauges sharing ONE clock read and ONE
        throttle decision (``group`` names the throttle bucket — each
        call site is its own bucket by default).  The skip path is the
        hot path: one ``monotonic()`` call and a dict lookup."""
        t = self._mono()
        gkey = group or (f"@r{replica}" if replica is not None else "")
        last = self._last_t.get(gkey)
        if last is not None and (t - last) < self.interval_s:
            return False
        t0 = time.perf_counter()
        self._last_t[gkey] = t
        wall = time.time()
        for name, value in values.items():
            if value is None:
                continue
            self.series(name, replica).record(value, t_mono=t, wall=wall)
        self.overhead_s += time.perf_counter() - t0
        return True

    # ------------------------------------------------------------ analysis
    def names(self) -> list[str]:
        return sorted(self._series)

    def summary(self) -> dict[str, dict[str, Any]]:
        return {k: s.summary() for k, s in sorted(self._series.items())}

    def stat(self, name: str, field: str,
             replica: int | None = None) -> Any:
        """One summary field of one series, None when the series does not
        exist — the run-report/bench key accessor."""
        s = self._series.get(_series_key(name, replica))
        return s.summary().get(field) if s is not None else None

    def merge(self, other: "Timeline") -> "Timeline":
        for key, s in other._series.items():
            mine = self._series.get(key)
            if mine is None:
                self._series[key] = GaugeSeries.from_dict(s.to_dict())
            else:
                mine.merge(s)
        self.overhead_s += other.overhead_s
        return self

    # ------------------------------------------------------------ emission
    def emit(self, tracer) -> None:
        """Write every series as one bulk ``timeline_series`` trace event
        (+ one ``timeline_overhead`` event), so ``analyze timeline`` and
        the Perfetto counter-track export work from the trace file alone.
        Emission happens ONCE at window end — the sampling hot path never
        touches the sink."""
        for key, s in sorted(self._series.items()):
            name, replica = split_series_key(key)
            # the exact totals ride along so the offline reconstruction
            # (analyze timeline → GaugeSeries.from_dict) is lossless even
            # when the ring dropped samples
            tracer.event("timeline_series", series=name, replica=replica,
                         capacity=s.capacity, dropped=s.dropped,
                         count=s.count, sum=s.sum, vmin=s.vmin,
                         vmax=s.vmax,
                         samples=[list(x) for x in s.samples()])
        tracer.event("timeline_overhead", overhead_s=self.overhead_s,
                     series=len(self._series))

    # ----------------------------------------------------------- serialize
    def to_dict(self) -> dict[str, Any]:
        return {
            "interval_s": self.interval_s,
            "capacity": self.capacity,
            "overhead_s": self.overhead_s,
            "series": {k: s.to_dict()
                       for k, s in sorted(self._series.items())},
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Timeline":
        tl = cls(interval_s=float(d.get("interval_s", 0.05)),
                 capacity=int(d.get("capacity", 512)))
        tl.overhead_s = float(d.get("overhead_s", 0.0))
        tl._series = {k: GaugeSeries.from_dict(sd)
                      for k, sd in d.get("series", {}).items()}
        return tl


# ---------------------------------------------------------------- rendering
_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: Iterable[float], width: int = 60) -> str:
    """Stdlib text sparkline: values bucketed to ``width`` columns, each
    column the mean of its bucket, scaled into 8 glyph levels.  The
    ``analyze timeline`` renderer — no plotting dependency."""
    vals = [float(v) for v in values]
    if not vals:
        return ""
    if len(vals) > width:
        # bucket-mean downsample so spikes within a bucket still move it
        out = []
        for i in range(width):
            lo = i * len(vals) // width
            hi = max((i + 1) * len(vals) // width, lo + 1)
            out.append(sum(vals[lo:hi]) / (hi - lo))
        vals = out
    vmin, vmax = min(vals), max(vals)
    span = vmax - vmin
    if span <= 0 or not math.isfinite(span):
        return _SPARK[0] * len(vals)
    return "".join(
        _SPARK[min(int((v - vmin) / span * (len(_SPARK) - 1) + 0.5),
                   len(_SPARK) - 1)]
        for v in vals)
