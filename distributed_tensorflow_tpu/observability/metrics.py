"""Streaming log-bucketed histograms: online percentiles without samples.

The serving engine needs p50/p95/**p99** of TTFT/ITL/queue-wait computed
*online* — "millions of users" means millions of latency observations, and
storing every sample to sort at the end is exactly the accounting that
stops scaling first.  :class:`LogHistogram` is the standard fix (HDR-
histogram / Prometheus-style): a **fixed geometric bucket ladder** —
bucket ``i`` covers ``(min · g^i, min · g^(i+1)]`` — so

* ``record`` is O(1): one ``log``, one dict increment, no allocation
  proportional to the data;
* any quantile is exact to within ONE bucket's relative width
  (``growth − 1``, 5% by default) — the error bound is a *configuration
  constant*, not a property of the data;
* two histograms with the same ladder **merge by adding counts** —
  windows merge into runs, and per-replica histograms will merge into
  fleet totals (ROADMAP item 2) without resampling.

The bucket EDGES are a pure function of ``(min_value, growth,
max_value)``, so merge compatibility is checkable and serialization
(``to_dict``/``from_dict``) carries only the sparse nonzero counts.
Global min/max are tracked exactly and quantiles clamp into ``[min, max]``
— a point-mass distribution reports its exact value, and the extreme
quantiles of small samples cannot overshoot the data.

:class:`MetricsRegistry` is the named-histogram front the scheduler
records into (``registry.record("ttft", 0.042)``); its ``snapshot()`` is
the JSON-ready summary table and ``merge`` composes registries window by
window.  Deliberately stdlib-only (math) — the offline ``analyze`` CLI
and pure-host tests import this without jax.
"""

from __future__ import annotations

import math
from typing import Any, Iterable


def exact_percentile(vals: Iterable[float], q: float) -> float | None:
    """Linear-interpolated percentile over stored samples — the stdlib
    reference path every histogram quantile is tested against, and the
    one summary surfaces keep using for per-window stored samples."""
    vals = list(vals)
    if not vals:
        return None
    s = sorted(vals)
    if len(s) == 1:
        return s[0]
    pos = (len(s) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(s) - 1)
    return s[lo] + (s[hi] - s[lo]) * (pos - lo)


class LogHistogram:
    """Fixed-geometric-bucket streaming histogram (module docstring).

    ``min_value``/``max_value`` bound the resolved range: values at or
    below ``min_value`` count in an underflow bucket, values above
    ``max_value`` in an overflow bucket — both still exact in ``count``/
    ``sum``/``min``/``max``, and quantiles landing there report the
    tracked exact extremes, never a fabricated in-range value."""

    def __init__(self, min_value: float = 1e-6, growth: float = 1.05,
                 max_value: float = 3600.0):
        if not (min_value > 0 and max_value > min_value):
            raise ValueError(
                f"need 0 < min_value < max_value, got "
                f"({min_value}, {max_value})")
        if growth <= 1.0:
            raise ValueError(f"growth must be > 1, got {growth}")
        self.min_value = float(min_value)
        self.growth = float(growth)
        self.max_value = float(max_value)
        self._log_g = math.log(self.growth)
        # fixed ladder: bucket count derives from the config alone, so two
        # same-config histograms are index-aligned by construction
        self.n_buckets = int(math.ceil(
            math.log(self.max_value / self.min_value) / self._log_g))
        self.counts: dict[int, int] = {}   # sparse: bucket index -> count
        self.underflow = 0
        self.overflow = 0
        self.count = 0
        self.sum = 0.0
        self.vmin: float | None = None
        self.vmax: float | None = None

    # ------------------------------------------------------------- record
    def record(self, value: float) -> None:
        """O(1): one log + one dict increment."""
        v = float(value)
        self.count += 1
        self.sum += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)
        if v <= self.min_value:
            self.underflow += 1
        elif v > self.max_value:
            self.overflow += 1
        else:
            i = int(math.log(v / self.min_value) / self._log_g)
            # float rounding can land exactly-on-edge values one bucket
            # high/low; clamp into the ladder and nudge down when v sits
            # at or below the bucket's lower edge
            i = min(max(i, 0), self.n_buckets - 1)
            if v <= self.min_value * self.growth ** i:
                i = max(i - 1, 0)
            self.counts[i] = self.counts.get(i, 0) + 1

    # ---------------------------------------------------------- quantiles
    @property
    def relative_width(self) -> float:
        """One bucket's relative width — THE quantile error bound."""
        return self.growth - 1.0

    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile: the upper edge of the bucket holding the
        ``ceil(q·count)``-th observation, clamped into the exact observed
        [min, max].  Within ``relative_width`` of the true sample
        quantile by construction."""
        if not self.count:
            return None
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        rank = max(1, math.ceil(q * self.count))
        seen = self.underflow
        if rank <= seen:
            return self.vmin  # everything down here is <= min_value
        for i in sorted(self.counts):
            seen += self.counts[i]
            if rank <= seen:
                edge = self.min_value * self.growth ** (i + 1)
                return min(max(edge, self.vmin), self.vmax)
        return self.vmax  # overflow bucket: the tracked exact maximum

    def summary(self) -> dict[str, Any]:
        """JSON-ready digest — the snapshot row the serve section carries."""
        return {
            "count": self.count,
            "sum": self.sum,
            "mean": (self.sum / self.count) if self.count else None,
            "min": self.vmin,
            "max": self.vmax,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
            "relative_width": self.relative_width,
        }

    # ------------------------------------------------------------- merge
    def _config(self) -> tuple[float, float, float]:
        return (self.min_value, self.growth, self.max_value)

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Add ``other``'s counts into this histogram.  Ladders must be
        identical — merged quantiles are then EXACTLY what record-all
        would have produced (the merge-equivalence test pins this)."""
        if self._config() != other._config():
            raise ValueError(
                f"cannot merge histograms with different bucket ladders: "
                f"{self._config()} vs {other._config()}")
        for i, c in other.counts.items():
            self.counts[i] = self.counts.get(i, 0) + c
        self.underflow += other.underflow
        self.overflow += other.overflow
        self.count += other.count
        self.sum += other.sum
        for v in (other.vmin, other.vmax):
            if v is not None:
                self.vmin = v if self.vmin is None else min(self.vmin, v)
                self.vmax = v if self.vmax is None else max(self.vmax, v)
        return self

    # ----------------------------------------------------------- serialize
    def to_dict(self) -> dict[str, Any]:
        return {
            "min_value": self.min_value,
            "growth": self.growth,
            "max_value": self.max_value,
            "counts": {str(i): c for i, c in sorted(self.counts.items())},
            "underflow": self.underflow,
            "overflow": self.overflow,
            "count": self.count,
            "sum": self.sum,
            "vmin": self.vmin,
            "vmax": self.vmax,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "LogHistogram":
        h = cls(min_value=d["min_value"], growth=d["growth"],
                max_value=d["max_value"])
        h.counts = {int(i): int(c) for i, c in d.get("counts", {}).items()}
        h.underflow = int(d.get("underflow", 0))
        h.overflow = int(d.get("overflow", 0))
        h.count = int(d.get("count", 0))
        h.sum = float(d.get("sum", 0.0))
        h.vmin = d.get("vmin")
        h.vmax = d.get("vmax")
        return h


class MetricsRegistry:
    """Named LogHistograms sharing one default ladder.

    The scheduler records phase observations by name (``ttft``, ``itl``,
    ``queue_wait``, ``prefill``, ``queue_depth``); ``snapshot()`` is the
    summary table and ``merge`` folds one registry into another — the
    per-window → per-run → per-fleet aggregation path."""

    def __init__(self, min_value: float = 1e-6, growth: float = 1.05,
                 max_value: float = 3600.0):
        self._default = (min_value, growth, max_value)
        self._hists: dict[str, LogHistogram] = {}

    def histogram(self, name: str, **kwargs: float) -> LogHistogram:
        """Get-or-create; per-histogram ladder overrides apply only at
        creation (a later conflicting override is ignored — the ladder is
        fixed for the histogram's lifetime by design)."""
        h = self._hists.get(name)
        if h is None:
            mn, g, mx = self._default
            h = LogHistogram(min_value=kwargs.get("min_value", mn),
                             growth=kwargs.get("growth", g),
                             max_value=kwargs.get("max_value", mx))
            self._hists[name] = h
        return h

    def record(self, name: str, value: float) -> None:
        self.histogram(name).record(value)

    def names(self) -> list[str]:
        return sorted(self._hists)

    def snapshot(self) -> dict[str, dict[str, Any]]:
        return {name: h.summary() for name, h in sorted(self._hists.items())}

    def merge(self, other: "MetricsRegistry") -> "MetricsRegistry":
        for name, h in other._hists.items():
            if name in self._hists:
                self._hists[name].merge(h)
            else:
                self._hists[name] = LogHistogram.from_dict(h.to_dict())
        return self

    def to_dict(self) -> dict[str, Any]:
        return {name: h.to_dict() for name, h in sorted(self._hists.items())}

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "MetricsRegistry":
        reg = cls()
        for name, hd in d.items():
            reg._hists[name] = LogHistogram.from_dict(hd)
        return reg
