"""Async JSONL sink: bounded queue + background writer thread.

Telemetry's cardinal rule here is *zero downshift*: emitting a record must
never put the host on the device's critical path.  A synchronous
``open(...).write`` per record (the old MetricsLogger) costs a syscall and
— on a network filesystem — an unbounded stall inside the step loop.  This
sink moves the I/O to a daemon thread behind a bounded queue:

* ``write(record)`` is one ``Queue.put_nowait`` — O(µs), never blocks.
* When the queue is full the record is DROPPED and counted
  (``dropped``), never buffered unboundedly and never back-pressured
  into the training loop.  The drop counter is reported in the run
  report, so a lossy capture is visible, not silent.
* The file is opened line-buffered and every record is written as ONE
  ``write`` call of a complete ``json.dumps(...) + "\\n"`` line, flushed
  per line — a SIGKILLed run leaves only whole JSON lines behind
  (crash-durability is tested in tests/test_observability.py).
* ``close()`` drains what was queued, flushes, and closes the file.

Every record carries ``schema_version`` so downstream readers can evolve.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from pathlib import Path
from typing import Any

# bump when a record's field semantics change (readers key on this)
SCHEMA_VERSION = 1

_CLOSE = object()  # queue sentinel: drain-and-exit


class AsyncJsonlSink:
    """Bounded-queue background JSONL writer (see module docstring).

    ``maxsize`` bounds the host memory a stalled filesystem can consume;
    at the default 8192 records (~100 B each) that is under a megabyte.
    ``start=False`` keeps the writer thread unstarted (tests exercise the
    overflow path deterministically this way); ``close()`` then drains
    synchronously.
    """

    def __init__(self, path: str | Path, maxsize: int = 8192,
                 start: bool = True):
        self.path = Path(path)
        self.dropped = 0
        self.written = 0
        self.enqueued = 0
        self._q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._f = open(self.path, "a", buffering=1)  # line-buffered
        self._lock = threading.Lock()  # close() vs writer-thread teardown
        self._closed = False
        self._thread: threading.Thread | None = None
        if start:
            self._thread = threading.Thread(
                target=self._drain_forever,
                name=f"jsonl-sink:{self.path.name}", daemon=True)
            self._thread.start()

    # ------------------------------------------------------------ producer
    def write(self, record: dict[str, Any]) -> bool:
        """Enqueue one record; returns False (and counts a drop) when the
        queue is full or the sink is closed.  ``schema_version`` is stamped
        here so every durable line carries it regardless of caller."""
        if self._closed:
            self.dropped += 1
            return False
        rec = {"schema_version": SCHEMA_VERSION, **record}
        try:
            self._q.put_nowait(rec)
            self.enqueued += 1
            return True
        except queue.Full:
            self.dropped += 1
            return False

    # ------------------------------------------------------------ consumer
    def _write_line(self, rec: dict) -> None:
        # ONE write call per complete line: with line buffering the flush
        # happens at the newline, so a kill between records never leaves a
        # partial line (the crash-durability contract)
        self._f.write(json.dumps(rec) + "\n")
        self.written += 1

    def _drain_forever(self) -> None:
        while True:
            rec = self._q.get()
            if rec is _CLOSE:
                return
            with self._lock:
                if self._f.closed:
                    return
                self._write_line(rec)

    def _drain_unstarted(self) -> None:
        """No writer thread (``start=False``): drain synchronously."""
        while True:
            try:
                rec = self._q.get_nowait()
            except queue.Empty:
                return
            if rec is not _CLOSE:
                self._write_line(rec)

    def flush(self, timeout: float = 5.0) -> None:
        """Best-effort wait until everything ACCEPTED so far is on disk —
        the wait target is the written count, not queue emptiness (the
        writer dequeues a record before it hits the file, so an empty
        queue does not mean the last record landed)."""
        if self._thread is None:
            self._drain_unstarted()
        target = self.enqueued
        deadline = time.monotonic() + timeout
        while self.written < target and time.monotonic() < deadline:
            time.sleep(0.005)
        if self._lock.acquire(timeout=timeout):
            try:
                if not self._f.closed:
                    self._f.flush()
            finally:
                self._lock.release()

    def close(self, timeout: float = 5.0) -> None:
        """Drain queued records, flush, close the file.  Idempotent, and
        BOUNDED even when the writer thread is wedged mid-write on a hung
        filesystem (lock acquires time out rather than block — the
        harness's watchdog-abort path calls this on its way to
        ``os._exit`` and must never hang on the stall it is escaping)."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            try:
                self._q.put(_CLOSE, timeout=timeout)
            except queue.Full:  # pragma: no cover - writer wedged
                pass
            self._thread.join(timeout=timeout)
        else:
            self._drain_unstarted()
        if self._lock.acquire(timeout=timeout):
            try:
                if not self._f.closed:
                    self._f.flush()
                    self._f.close()
            finally:
                self._lock.release()

    def stats(self) -> dict[str, int]:
        return {"written": self.written, "dropped": self.dropped}

    def __enter__(self) -> "AsyncJsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover - GC-timing safety net
        try:
            self.close(timeout=0.5)
        except Exception:
            pass
