"""SLO-aware goodput accounting for the serving engine.

The headline serving number is NOT median latency at one arrival rate —
MLPerf's measurement discipline (Mattson et al., arXiv:1910.01500) and
Sarathi-Serve's goodput framing (Agrawal et al., arXiv:2403.02310) both
define it as **requests/sec that meet the latency target**: a request
counts only when its TTFT *and* its inter-token latency are inside the
SLO, and a shed (429'd) request never counts, however fast the rejection
was.  :class:`SLOMonitor` is that definition as an online accumulator —
one ``observe`` per completed request, one ``shed`` per rejected one, a
``summary`` per window — so ``bench.py --serve --sweep`` can walk the
arrival-rate ladder and report ``serve_max_goodput_under_slo`` as the
number a capacity plan can actually be written against.

Per-request ITL is judged at a percentile of that request's own gaps
(p99 by default): a stream that stalls once near the end failed its
reader even if the mean gap was fine.  BASELINE.md "Goodput accounting"
carries the comparison rules (state the SLO with the number; shed ≠
goodput; p99 claims need the sample count).

Stdlib-only, like the rest of the offline-readable observability layer.
"""

from __future__ import annotations

from typing import Any, Iterable

from distributed_tensorflow_tpu.observability.metrics import exact_percentile


class SLOMonitor:
    """Online goodput-under-SLO accumulator (module docstring).

    ``ttft_s``/``itl_s`` are the latency targets in clock units;
    ``quantile`` is the per-request ITL percentile judged against
    ``itl_s`` (0.99 = the p99-ITL convention).  One monitor measures one
    window; ``reset()`` rearms it for the next."""

    def __init__(self, ttft_s: float, itl_s: float,
                 quantile: float = 0.99):
        if ttft_s <= 0 or itl_s <= 0:
            raise ValueError(
                f"SLO targets must be positive, got ttft_s={ttft_s}, "
                f"itl_s={itl_s}")
        if not 0.0 < quantile <= 1.0:
            raise ValueError(f"quantile must be in (0, 1], got {quantile}")
        self.ttft_s = float(ttft_s)
        self.itl_s = float(itl_s)
        self.quantile = float(quantile)
        self.reset()

    def reset(self) -> None:
        self.requests = 0
        self.good_requests = 0
        self.shed_requests = 0
        self.ttft_misses = 0
        self.itl_misses = 0

    # ------------------------------------------------------------ observe
    def observe(self, ttft_s: float, itl_gaps: Iterable[float]) -> bool:
        """Account one COMPLETED request; returns whether it met the SLO
        (TTFT within target AND the request's own ITL ``quantile`` within
        target — a single-token request has no gaps and passes ITL
        trivially)."""
        self.requests += 1
        itl_stat = exact_percentile(itl_gaps, self.quantile)
        ttft_ok = ttft_s <= self.ttft_s
        itl_ok = itl_stat is None or itl_stat <= self.itl_s
        if not ttft_ok:
            self.ttft_misses += 1
        if not itl_ok:
            self.itl_misses += 1
        good = ttft_ok and itl_ok
        self.good_requests += good
        return good

    def shed(self, n: int = 1) -> None:
        """Account ``n`` shed (429'd) requests: offered load that is by
        definition NOT goodput."""
        self.shed_requests += int(n)

    # ------------------------------------------------------------ summary
    def summary(self, elapsed_s: float | None = None) -> dict[str, Any]:
        """The window's SLO section.  ``goodput_requests_per_sec`` needs
        the window's elapsed time; with zero completed requests the
        attainment is None (no claim, not a perfect score) and goodput is
        0.0 when time passed, None when it did not."""
        attainment = (self.good_requests / self.requests
                      if self.requests else None)
        goodput = None
        if elapsed_s is not None and elapsed_s > 0:
            goodput = self.good_requests / elapsed_s
        return {
            "slo_ttft_s": self.ttft_s,
            "slo_itl_s": self.itl_s,
            "quantile": self.quantile,
            "requests": self.requests,
            "good_requests": self.good_requests,
            "shed_requests": self.shed_requests,
            "ttft_misses": self.ttft_misses,
            "itl_misses": self.itl_misses,
            "slo_attainment": attainment,
            "goodput_requests_per_sec": goodput,
        }
