"""Roofline efficiency ledger: analytic FLOPs/bytes cost model + device
peak table + MFU/MBU attribution (ISSUE 19).

Every number the stack reported before this module was a latency, a rate,
or a byte count.  This module turns rates into *utilizations* — the
fraction of the hardware a phase achieves — and classifies each phase and
each compiled program compute- vs bandwidth-bound on the classic roofline
model (arithmetic intensity vs the ridge point peak_flops/peak_BW).

Three parts:

  GPTCostModel   — analytic FLOPs/bytes for the GPT family, from config
                   alone (no jax import): per-token train FLOPs (fwd+bwd),
                   per-token decode FLOPs at a given context, prefill
                   FLOPs per chunk (chunk sums are exact — they telescope
                   to the monolithic figure), and the bytes a decode step
                   MUST read (params once per batched step + per-slot
                   context KV under monolithic/paged/int8 layouts).
  DevicePeaks    — peak matmul flops/s per dtype + HBM bytes/s, keyed on
                   ``device_kind`` substrings.  An unknown kind returns
                   None and every downstream MFU/MBU honestly reports
                   None — a peak is never invented (BASELINE.md rule).
  Roofline       — the wiring object the trainer/batcher/report carry
                   when ``--roofline`` is on: peaks + device count +
                   optional cost model, with ``mfu()``/``mbu()`` and the
                   per-program attribution helpers.

Accounting rules (the BASELINE.md "Roofline accounting" contract):

* MFU uses *model* FLOPs — matmul FLOPs the math requires (2·MACs,
  backward = 2× forward).  Rematerialization, elementwise ops, optimizer
  FLOPs and XLA's bookkeeping are never credited; XLA's own
  ``cost_analysis`` count rides alongside as a cross-check only.
* MBU counts bytes the model *must* read — the weights once per batched
  decode step and the written KV context per slot (block-granular under
  the paged layout, payload+scales under int8) — never the bytes XLA
  happened to move (a monolithic decode program scans the full
  ``max_len`` table; those idle bytes are the *inefficiency* MBU exists
  to expose, not part of the denominator's credit).
* Any published MFU/MBU states ``PEAK_TABLE_REVISION`` — peak figures
  are revisable, and a revision bump re-bases every claim.

Stdlib-only: the analyze CLI renders roofline tables offline from a run
report or manifest without importing jax.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

# Revision of the peak figures below.  Bump when any entry changes and
# state the revision with every published MFU/MBU claim (BASELINE.md).
PEAK_TABLE_REVISION = 1

# Public per-chip figures: (device_kind substring, peak bf16 matmul
# flops/s, HBM bytes/s).  First match wins, so specific v5/v6 entries
# precede the bare "v5" fallback (some libtpu builds report v5p as just
# "TPU v5").  f32 is listed at half the bf16 rate and int8 at double —
# the MXU convention, part of what REVISION pins.
_DEVICE_PEAKS = (
    ("v6 lite", 918e12, 1640e9),
    ("v6e", 918e12, 1640e9),
    ("v5 lite", 197e12, 819e9),
    ("v5e", 197e12, 819e9),
    ("v5p", 459e12, 2765e9),
    ("v5", 459e12, 2765e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
)

_KV_SCALE_BYTES = 4  # int8 KV: one f32 max-abs scale per (position, kv_head)


@dataclass(frozen=True)
class DevicePeaks:
    """Peak figures for one device kind, at PEAK_TABLE_REVISION."""

    device_kind: str
    flops_per_s: dict          # dtype key ("bf16"/"f32"/"int8") -> flops/s
    hbm_bytes_per_s: float
    revision: int = PEAK_TABLE_REVISION


def device_peaks(device_kind: str | None) -> DevicePeaks | None:
    """Peak table lookup.  Unknown/None kinds return None — downstream
    MFU/MBU then report None rather than a number against a fabricated
    peak (the honesty rule CI pins on CPU)."""
    if not device_kind:
        return None
    kind = str(device_kind).lower()
    for sub, bf16, hbm in _DEVICE_PEAKS:
        if sub in kind:
            return DevicePeaks(str(device_kind),
                               {"bf16": bf16, "f32": bf16 / 2,
                                "int8": 2 * bf16}, hbm)
    return None


def _dtype_key(dtype) -> str:
    s = str(dtype)
    if "bfloat16" in s or "float16" in s:
        return "bf16"
    if "int8" in s:
        return "int8"
    return "f32"


def _kv_itemsize(kv_dtype) -> int:
    s = str(kv_dtype)
    if "int8" in s:
        return 1
    if "bfloat16" in s or "float16" in s:
        return 2
    return 4


@dataclass
class GPTCostModel:
    """Analytic FLOPs/bytes for one GPT config (models/gpt.py fields).

    FLOPs are matmul FLOPs only (2·MACs): embeddings are gathers, LN and
    softmax are elementwise — excluded, the standard MFU accounting the
    CNN bench already uses.  MoE counts the ACTIVE path (top-1 through
    one ffn-wide expert — identical FLOPs to dense by construction,
    models/moe.py) plus the router projection.
    """

    vocab: int
    hidden: int
    layers: int
    heads: int
    ffn: int
    max_len: int
    kv_heads: int | None = None
    causal: bool = True
    learned_pos: bool = True
    tie_embeddings: bool = True
    moe_experts: int = 0
    kv_dtype: str = "f32"          # KV cache storage: "f32"|"bf16"|"int8"
    kv_layout: str = "monolithic"  # "monolithic"|"paged"
    paged_block: int = 16
    # measured stored-param bytes (sum of actual leaf nbytes) when the
    # caller has real params in hand; the analytic 4-byte-f32 weight
    # count is the fallback
    param_bytes_override: int | None = None

    @classmethod
    def from_model(cls, model, **overrides) -> "GPTCostModel | None":
        """Duck-typed construction from a GPT-family flax module (any
        object with the models/gpt.py config fields).  Returns None for
        models the analytic family doesn't cover (no ``causal_lm``
        marker) — callers then report MFU as None, never a wrong one."""
        if not getattr(model, "causal_lm", False):
            return None
        kw = dict(
            vocab=int(model.vocab_size),
            hidden=int(model.hidden),
            layers=int(model.layers),
            heads=int(model.heads),
            ffn=int(model.ffn),
            max_len=int(model.max_len),
            kv_heads=getattr(model, "kv_heads", None),
            learned_pos=getattr(model, "positional", "learned") == "learned",
            tie_embeddings=bool(getattr(model, "tie_embeddings", True)),
            moe_experts=int(getattr(model, "moe_experts", 0) or 0),
        )
        kw.update(overrides)
        return cls(**kw)

    # -- shapes -----------------------------------------------------------

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads

    @property
    def n_kv_heads(self) -> int:
        return int(self.kv_heads) if self.kv_heads else self.heads

    @property
    def kv_hidden(self) -> int:
        return self.n_kv_heads * self.head_dim

    def param_count(self, active_only: bool = True) -> int:
        """Matmul weight count (biases/LN excluded — sub-percent).  With
        ``active_only`` (the decode must-read figure) MoE counts one
        expert's MLP; otherwise all experts are counted (storage)."""
        n = self.vocab * self.hidden
        if self.learned_pos:
            n += self.max_len * self.hidden
        attn = self.hidden * (self.hidden + 2 * self.kv_hidden) \
            + self.hidden * self.hidden
        mlp = 2 * self.hidden * self.ffn
        e = self.moe_experts
        per_layer = attn + (mlp if (active_only or not e) else e * mlp)
        if e:
            per_layer += self.hidden * e  # router projection
        n += self.layers * per_layer
        if not self.tie_embeddings:
            n += self.hidden * self.vocab
        return n

    def param_bytes(self) -> int:
        """Stored-param bytes a decode step must stream (active path).
        Measured leaf bytes when the caller provided them (flax keeps
        param_dtype=float32 under bf16 compute today); 4-byte weights
        otherwise."""
        if self.param_bytes_override is not None:
            return int(self.param_bytes_override)
        return 4 * self.param_count(active_only=True)

    # -- FLOPs ------------------------------------------------------------

    @property
    def _proj_flops_per_token(self) -> float:
        """Per-token projection/MLP matmul FLOPs, all layers: QKV
        (GQA-aware) + attention output + MLP up/down (or the active MoE
        expert + router)."""
        per_layer = (2.0 * self.hidden * (self.hidden + 2 * self.kv_hidden)
                     + 2.0 * self.hidden * self.hidden
                     + 4.0 * self.hidden * self.ffn)
        if self.moe_experts:
            per_layer += 2.0 * self.hidden * self.moe_experts  # router
        return self.layers * per_layer

    @property
    def lm_head_flops(self) -> float:
        """Logit projection for ONE position (2·h·V)."""
        return 2.0 * self.hidden * self.vocab

    def fwd_flops_per_token(self, seq_len: int) -> float:
        """Forward matmul FLOPs per token of a full-sequence (training)
        pass at ``seq_len``: projections + the QK^T/PV einsums (4·S·h,
        halved causal — the average position attends S/2 keys) + the
        per-position LM head."""
        attn = 4.0 * seq_len * self.hidden * (0.5 if self.causal else 1.0)
        return (self._proj_flops_per_token + self.layers * attn
                + self.lm_head_flops)

    def train_flops_per_token(self, seq_len: int) -> float:
        """Fwd+bwd per token: backward costs ~2× forward (grads wrt both
        activations and weights) — the standard ×3 MFU accounting.
        Rematerialization is NEVER credited (BASELINE.md): remat recompute
        is overhead MFU must expose, not model work."""
        return 3.0 * self.fwd_flops_per_token(seq_len)

    def train_step_flops(self, batch: int, seq_len: int,
                         grad_accum: int = 1) -> float:
        """Model FLOPs of one optimizer step over ``batch`` sequences.
        Independent of ``grad_accum`` — K microbatches of batch/K sum to
        the same token count; the argument exists so the invariant is
        explicit (and pinned in tests)."""
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        return batch * seq_len * self.train_flops_per_token(seq_len)

    def decode_flops_per_token(self, context: int) -> float:
        """One new token with a KV cache holding ``context`` attended
        keys: projections + 4·L·h attention (no causal halving — the
        single query row attends everything) + the LM head."""
        return (self._proj_flops_per_token
                + self.layers * 4.0 * context * self.hidden
                + self.lm_head_flops)

    def verify_flops(self, context: int, width: int) -> float:
        """Speculative verify of ``width`` positions (k_eff+1 — the k
        drafted tokens plus the bonus position) in ONE batched step:
        position j attends context+j keys.  Weights are read once — the
        bytes side does not scale with width (see decode_step_bytes)."""
        return sum(self.decode_flops_per_token(context + j)
                   for j in range(int(width)))

    def prefill_chunk_flops(self, n: int, start: int = 0) -> float:
        """A prefill chunk of ``n`` prompt tokens beginning at absolute
        position ``start``: token at position p attends p+1 keys, so the
        attention term telescopes — chunk sums equal the monolithic
        figure exactly.  The LM head is EXCLUDED (serving computes logits
        for the last prompt position only — add ``lm_head_flops`` once
        per completed prefill)."""
        n = int(n)
        if n <= 0:
            return 0.0
        attn = 4.0 * self.hidden * (n * start + n * (n + 1) / 2.0)
        return n * self._proj_flops_per_token + self.layers * attn

    # -- bytes ------------------------------------------------------------

    @property
    def _kv_bytes_per_position(self) -> int:
        """KV bytes WRITTEN per cached position, all layers: K and V
        vectors (kv_hidden each) at the storage dtype, plus the f32
        max-abs scale per (position, kv_head) vector under int8."""
        per_layer = 2 * self.kv_hidden * _kv_itemsize(self.kv_dtype)
        if _kv_itemsize(self.kv_dtype) == 1:
            per_layer += 2 * self.n_kv_heads * _KV_SCALE_BYTES
        return self.layers * per_layer

    def kv_read_bytes(self, length: int) -> int:
        """Bytes a decode step MUST read for one slot with ``length``
        cached positions.  Paged layout reads block-granular —
        ceil(L/block)·block positions; monolithic credits exactly L even
        though the compiled program scans the whole max_len table (those
        idle bytes are the inefficiency MBU exposes)."""
        length = int(length)
        if length <= 0:
            return 0
        if self.kv_layout == "paged":
            length = math.ceil(length / self.paged_block) * self.paged_block
        return length * self._kv_bytes_per_position

    def decode_step_bytes(self, contexts) -> int:
        """Must-read bytes of ONE batched decode (or speculative verify)
        step over live slots with the given context lengths: the active
        weights once — every slot shares the stream — plus each slot's
        written KV context."""
        return self.param_bytes() + sum(
            self.kv_read_bytes(c) for c in contexts)


# ---------------------------------------------------------------------------
# attribution helpers (stdlib — analyze renders these offline)
# ---------------------------------------------------------------------------

def arithmetic_intensity(flops, bytes_accessed) -> float | None:
    """FLOPs per byte, None when either side is unknown/zero (CPU
    backends may report neither)."""
    if not flops or not bytes_accessed:
        return None
    return float(flops) / float(bytes_accessed)


def ridge_point(peaks: DevicePeaks | None, dtype: str = "bf16"):
    """Intensity (flops/byte) above which the device is compute-bound."""
    if peaks is None:
        return None
    peak = peaks.flops_per_s.get(dtype)
    if not peak or not peaks.hbm_bytes_per_s:
        return None
    return peak / peaks.hbm_bytes_per_s


def classify_bound(intensity, peaks: DevicePeaks | None,
                   dtype: str = "bf16") -> str | None:
    """'compute' or 'bandwidth', None when the intensity or the device
    peaks are unknown."""
    ridge = ridge_point(peaks, dtype)
    if intensity is None or ridge is None:
        return None
    return "compute" if intensity >= ridge else "bandwidth"


def attainable_fraction(intensity, peaks: DevicePeaks | None,
                        dtype: str = "bf16") -> float | None:
    """Roofline ceiling as a fraction of peak FLOPs: min(1, I·BW/peak).
    The best ANY schedule of this program could achieve — rendered by
    ``analyze roofline`` as %-of-peak."""
    ridge = ridge_point(peaks, dtype)
    if intensity is None or ridge is None:
        return None
    return min(1.0, intensity / ridge)


def program_attribution(programs: dict, peaks: DevicePeaks | None = None,
                        dtype: str = "bf16") -> list:
    """Per-program roofline rows from a ProgramLedger manifest's
    ``programs`` table (flops/bytes_accessed columns, ISSUE 19
    satellite): name, flops, bytes, intensity, bound, attainable
    %-of-peak.  None-tolerant throughout — a CPU manifest with no cost
    analysis yields rows of Nones, not a crash."""
    rows = []
    for name in sorted(programs):
        rec = programs[name] or {}
        flops = rec.get("flops")
        nbytes = rec.get("bytes_accessed")
        intensity = arithmetic_intensity(flops, nbytes)
        rows.append({
            "program": name,
            "flops": flops,
            "bytes_accessed": nbytes,
            "arithmetic_intensity": (round(intensity, 3)
                                     if intensity is not None else None),
            "bound": classify_bound(intensity, peaks, dtype),
            "attainable_frac_of_peak": (
                round(attainable_fraction(intensity, peaks, dtype), 4)
                if attainable_fraction(intensity, peaks, dtype) is not None
                else None),
        })
    return rows


def flops_crosscheck(analytic, xla) -> float | None:
    """XLA-reported / analytic FLOPs ratio (the sanity cross-check:
    XLA's count includes elementwise/optimizer work the model count
    excludes, so healthy ratios sit modestly above 1)."""
    if not analytic or not xla:
        return None
    return float(xla) / float(analytic)


# ---------------------------------------------------------------------------
# the wiring object
# ---------------------------------------------------------------------------

class Roofline:
    """What ``--roofline`` threads through the trainer, the batcher, the
    fleet and the run report: device peaks (None = honest unknown), the
    device count MFU/MBU normalize over, the compute dtype, and the
    analytic cost model (None for model families the analytic accounting
    doesn't cover — utilizations are then None, never invented)."""

    def __init__(self, peaks: DevicePeaks | None, n_devices: int = 1,
                 cost: GPTCostModel | None = None, dtype: str = "bf16"):
        self.peaks = peaks
        self.n_devices = max(int(n_devices), 1)
        self.cost = cost
        self.dtype = dtype
        self.revision = PEAK_TABLE_REVISION

    @classmethod
    def for_device(cls, device_kind, n_devices: int = 1,
                   cost: GPTCostModel | None = None,
                   dtype: str = "bf16") -> "Roofline":
        return cls(device_peaks(device_kind), n_devices, cost, dtype)

    @classmethod
    def for_model(cls, model, device_kind, n_devices: int = 1,
                  **cost_overrides) -> "Roofline":
        """Training-side construction: cost model from the flax module's
        config (None for non-GPT models), compute dtype from its dtype."""
        cost = GPTCostModel.from_model(model, **cost_overrides)
        return cls(device_peaks(device_kind), n_devices, cost,
                   _dtype_key(getattr(model, "dtype", "float32")))

    @classmethod
    def for_kv(cls, kv, device_kind, n_devices: int = 1) -> "Roofline":
        """Serving-side construction from a slot KV table: the decode
        model's config plus the table's ACTUAL storage dtype/layout, and
        measured stored-param bytes when the table exposes them."""
        model = getattr(kv, "dm", None)
        cost = GPTCostModel.from_model(
            model,
            kv_dtype=str(getattr(kv, "kv_dtype", None)
                         or getattr(model, "dtype", "float32")),
            kv_layout=str(getattr(kv, "kv_layout", "monolithic")
                          or "monolithic"),
            paged_block=int(getattr(kv, "paged_block", None)
                            or getattr(model, "paged_block", 16) or 16),
        ) if model is not None else None
        if cost is not None:
            measured = getattr(kv, "param_leaf_bytes", None)
            if callable(measured):
                try:
                    cost.param_bytes_override = int(measured())
                except Exception:  # noqa: BLE001 — analytic fallback
                    pass
        dtype = _dtype_key(getattr(model, "dtype", "float32"))
        return cls(device_peaks(device_kind), n_devices, cost, dtype)

    # -- utilizations -----------------------------------------------------

    def flops_peak(self) -> float | None:
        if self.peaks is None:
            return None
        return self.peaks.flops_per_s.get(self.dtype)

    def mfu(self, achieved_flops_per_s) -> float | None:
        """achieved model flops/s over the FLEET's peak (n_devices × per-
        chip peak).  None when the device or the achieved side is
        unknown."""
        peak = self.flops_peak()
        if achieved_flops_per_s is None or not peak:
            return None
        return float(achieved_flops_per_s) / (self.n_devices * peak)

    def mbu(self, achieved_bytes_per_s) -> float | None:
        if (achieved_bytes_per_s is None or self.peaks is None
                or not self.peaks.hbm_bytes_per_s):
            return None
        return (float(achieved_bytes_per_s)
                / (self.n_devices * self.peaks.hbm_bytes_per_s))

    def describe(self) -> dict:
        """The device half of every roofline report section."""
        return {
            "device_kind": (self.peaks.device_kind if self.peaks
                            else None),
            "known_device": self.peaks is not None,
            "peak_table_revision": self.revision,
            "n_devices": self.n_devices,
            "dtype": self.dtype,
            "peak_flops_per_sec": self.flops_peak(),
            "peak_hbm_bytes_per_sec": (self.peaks.hbm_bytes_per_s
                                       if self.peaks else None),
            "ridge_flops_per_byte": ridge_point(self.peaks, self.dtype),
        }
