"""Paged decode attention as a Pallas TPU kernel (vLLM's PagedAttention).

The serving KV table in ``serving/kv_cache.py`` historically stored one
contiguous ``(slots, max_len)`` row per slot, and the decode step ran a
full-width gather + softmax over it.  The paged layout (Kwon et al.,
arXiv:2309.06180) breaks that row into fixed-size physical blocks in one
shared pool ``(num_blocks, block, kv_heads, head_dim)`` and gives each slot
an int32 *block table*; a prefix-cache hit then aliases pool blocks by
pointer instead of copying KV bytes.  This kernel is the read side of that
design: a decode/verify attention kernel that follows the block table
**inside** the kernel, so the gathered ``(slots, max_len)`` K/V copy never
materializes in HBM.

Grid ``(slots, kv_heads, max_blocks)`` — the block axis iterates innermost
and sequentially, which is what lets the online-softmax accumulators
(m/l/acc) persist in VMEM scratch across a slot's blocks (the same pattern
as ``_fwd_kernel`` in flash_attention.py).  The block table and per-slot
positions ride in as *scalar-prefetch* operands
(``pltpu.PrefetchScalarGridSpec``): each K/V BlockSpec's index_map reads
``bt[s, j]`` to window the pool block-indirectly, the Pallas analogue of
vLLM's physical-block lookup.

Queries are ``(slots, l_q, heads, head_dim)`` — ``l_q == 1`` is the decode
step and ``l_q == k+1`` the speculative ``verify_block`` variant; each
query row is masked to keys at or before its own position
(``t <= pos + row % l_q``).  Grouped-query attention folds the query-head
group into the row axis, so the kernel always sees one kv head per grid
step.  int8 KV composes in-kernel: the quantized pool blocks are
dequantized from their per-vector scale blocks right after the windowed
load — the materialized f32 table the unfused path pays for never exists.

On non-TPU backends the kernel runs in Pallas interpret mode (the
flash_attention precedent), so CPU CI exercises the real kernel, not a
shadow implementation.  ``paged_attention_reference`` is the pure-jnp twin:
the gather + dense-softmax oracle used for parity tests and as the
fallback when operands carry varying axes under ``jax.shard_map`` on CPU
(interpret mode cannot lower pallas_call under vma checking).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific bits are unavailable in some CPU-only wheels
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30  # matches parallel.ring_attention.NEG_INF
_TINY = 1e-30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _join_vma(*xs) -> frozenset:
    """Union of the operands' varying-axes sets (shard_map check_vma).
    jax wheels before ``jax.typeof`` have no vma concept — empty set."""
    typeof = getattr(jax, "typeof", None)
    vma = frozenset()
    if typeof is None:
        return vma
    for x in xs:
        if x is not None:
            vma |= typeof(x).vma
    return vma


def _kernel(bt_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
            nb, blk, l_q, sm_scale, quantized):
    """One (slot, kv_head, block) grid step of the online softmax.

    ``q_ref`` block is (1, 1, GL, D) — GL = group × l_q query rows for this
    kv head; ``k_ref``/``v_ref`` blocks are (1, blk, 1, D) pool blocks
    windowed through ``bt_ref[s, j]``.  When ``quantized``, ``rest`` leads
    with the (1, blk, 1) per-vector scale blocks.
    """
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    s, j = pl.program_id(0), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    p0 = pos_ref[s]  # first query's position for this slot

    def compute():
        qb = q_ref[0, 0].astype(jnp.float32)          # (GL, D)
        kb = k_ref[0][:, 0].astype(jnp.float32)       # (blk, D)
        vb = v_ref[0][:, 0].astype(jnp.float32)
        if quantized:  # in-kernel dequant from the per-vector scales
            kb = kb * ks_ref[0][:, 0][:, None]
            vb = vb * vs_ref[0][:, 0][:, None]
        sc = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        sc = sc * sm_scale
        # key position t vs each query row's own position (row % l_q walks
        # the verify block; the group axis repeats the same position)
        t = j * blk + jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
        qoff = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 0) % l_q
        sc = jnp.where(t <= p0 + qoff, sc, NEG_INF)

        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, sc.max(axis=-1, keepdims=True))
        p = jnp.exp(sc - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_prev * corr + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)

    # skip blocks entirely past the last query's position (dead keys)
    pl.when(j * blk <= p0 + l_q - 1)(compute)

    @pl.when(j == nb - 1)
    def _():
        o_ref[0, 0] = (acc_scr[:]
                       / jnp.maximum(l_scr[:], _TINY)).astype(o_ref.dtype)


def _fold_gqa(q, kv_heads):
    """(S, L, H, D) → (S, KVH, G·L, D): group rides the query-row axis."""
    s, l, h, d = q.shape
    g = h // kv_heads
    return (q.reshape(s, l, kv_heads, g, d)
            .transpose(0, 2, 3, 1, 4).reshape(s, kv_heads, g * l, d))


def _unfold_gqa(out, l_q, heads):
    s, kvh, gl, d = out.shape
    g = gl // l_q
    return (out.reshape(s, kvh, g, l_q, d)
            .transpose(0, 3, 1, 2, 4).reshape(s, l_q, heads, d))


def paged_attention_reference(q, k_pool, v_pool, block_tables, positions, *,
                              k_scale=None, v_scale=None, scale=None):
    """Pure-jnp oracle: gather the pool through the block table, dequant,
    widen kv heads, dense masked softmax.  Same signature as the kernel
    entry; the parity tests pin the kernel against this."""
    s, l_q, h, d = q.shape
    n, blk, kvh, _ = k_pool.shape
    mb = block_tables.shape[1]
    keys = jnp.take(k_pool, block_tables, axis=0).reshape(s, mb * blk, kvh, d)
    vals = jnp.take(v_pool, block_tables, axis=0).reshape(s, mb * blk, kvh, d)
    if k_scale is not None:
        ks = jnp.take(k_scale, block_tables, axis=0).reshape(s, mb * blk, kvh)
        vs = jnp.take(v_scale, block_tables, axis=0).reshape(s, mb * blk, kvh)
        keys = keys.astype(jnp.float32) * ks[..., None]
        vals = vals.astype(jnp.float32) * vs[..., None]
    if kvh != h:
        keys = jnp.repeat(keys, h // kvh, axis=2)
        vals = jnp.repeat(vals, h // kvh, axis=2)
    from distributed_tensorflow_tpu.parallel.ring_attention import (
        dense_attention)
    t = jnp.arange(mb * blk, dtype=jnp.int32)
    valid = (t[None, None, :]
             <= positions[:, None, None]
             + jnp.arange(l_q, dtype=jnp.int32)[None, :, None])
    out = dense_attention(q.astype(jnp.float32), keys.astype(jnp.float32),
                          vals.astype(jnp.float32), causal=False,
                          scale=scale, kv_mask=valid)
    return out.astype(q.dtype)


def paged_attention(q, k_pool, v_pool, block_tables, positions, *,
                    k_scale=None, v_scale=None, scale=None,
                    interpret=None):
    """Fused paged decode attention.

    Args:
      q: (slots, l_q, heads, head_dim) queries — model layout; ``l_q`` is 1
        for the decode step, ``k+1`` for speculative verify.
      k_pool, v_pool: (num_blocks, block, kv_heads, head_dim) physical
        block pools (f32/bf16, or int8 with scales).
      block_tables: (slots, max_blocks) int32 — pool block id per logical
        block.  Unmapped entries must hold a valid index (0 is fine): the
        length mask kills their scores, but the windowed load still reads.
      positions: (slots,) int32 — position of each slot's FIRST query row
        (its current length); query row r attends keys ``t <= pos + r``.
      k_scale, v_scale: (num_blocks, block, kv_heads) f32 per-vector
        scales, required iff the pools are int8 (in-kernel dequant).
      scale: softmax scale; defaults to ``head_dim ** -0.5``.
      interpret: Pallas interpret mode; defaults to True off-TPU.

    Returns (slots, l_q, heads, head_dim) in ``q.dtype``.
    """
    s, l_q, h, d = q.shape
    n, blk, kvh, _ = k_pool.shape
    mb = block_tables.shape[1]
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be given together")
    if h % kvh:
        raise ValueError(f"heads={h} not divisible by kv_heads={kvh}")
    if interpret is None:
        interpret = _interpret_default()
    if interpret and _join_vma(q, k_pool, v_pool, k_scale, v_scale):
        # shard_map-on-CPU: interpret mode cannot lower under vma
        # checking — fall back to the jnp twin (flash_attention precedent)
        return paged_attention_reference(
            q, k_pool, v_pool, block_tables, positions,
            k_scale=k_scale, v_scale=v_scale, scale=scale)
    sm_scale = scale if scale is not None else d ** -0.5
    gl = (h // kvh) * l_q
    qf = _fold_gqa(q, kvh)
    bt = block_tables.astype(jnp.int32)
    pos = positions.astype(jnp.int32)

    kernel = functools.partial(_kernel, nb=mb, blk=blk, l_q=l_q,
                               sm_scale=sm_scale, quantized=quantized)
    in_specs = [
        pl.BlockSpec((1, 1, gl, d), lambda s, h, j, bt, pos: (s, h, 0, 0)),
        pl.BlockSpec((1, blk, 1, d),
                     lambda s, h, j, bt, pos: (bt[s, j], 0, h, 0)),
        pl.BlockSpec((1, blk, 1, d),
                     lambda s, h, j, bt, pos: (bt[s, j], 0, h, 0)),
    ]
    operands = [qf, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, blk, 1),
                         lambda s, h, j, bt, pos: (bt[s, j], 0, h)),
            pl.BlockSpec((1, blk, 1),
                         lambda s, h, j, bt, pos: (bt[s, j], 0, h)),
        ]
        operands += [k_scale, v_scale]
    if pltpu is None:  # pragma: no cover - CPU wheels without pallas.tpu
        raise NotImplementedError(
            "paged_attention needs jax.experimental.pallas.tpu "
            "(PrefetchScalarGridSpec) — unavailable in this wheel")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, kvh, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, gl, d),
                               lambda s, h, j, bt, pos: (s, h, 0, 0)),
        scratch_shapes=[
            _VMEM((gl, 1), jnp.float32),
            _VMEM((gl, 1), jnp.float32),
            _VMEM((gl, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, kvh, gl, d), q.dtype),
        interpret=interpret,
    )(bt, pos, *operands)
    return _unfold_gqa(out, l_q, h)
