"""Pallas TPU kernels for the hot ops.

The reference has no native/accelerated code at all (SURVEY.md §2: 100%
Python, the fast path is whatever tf.keras does) — this package is the
TPU-native answer: hand-written Pallas kernels where XLA's automatic fusion
leaves throughput on the table, starting with flash attention (the O(L)
-memory attention that BERT + sequence parallelism ride on).
"""

from distributed_tensorflow_tpu.ops.flash_attention import flash_attention  # noqa: F401
