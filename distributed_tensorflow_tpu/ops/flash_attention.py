"""Flash attention as Pallas TPU kernels (forward + backward).

The reference has no attention anywhere (SURVEY.md §2.2 — its only model is
an MLP on 28×28, reference initializer.py:14-19).  This kernel is pure
TPU-native capability: softmax(QKᵀ)V computed blockwise so the (L, L) score
matrix never exists in HBM — scores live tile-by-tile in VMEM, the running
(max, sum, acc) merge keeps the math exact, and the MXU sees only dense
(block_q × d) @ (d × block_k) matmuls.

Three kernels:

* ``_fwd_kernel``   — grid (B·H, Lq/bq, Lk/bk): online-softmax accumulation
  into VMEM scratch, output + logsumexp written on the last k-step.
* ``_dkv_kernel``   — grid (B·H, Lk/bk, Lq/bq): recomputes p from the saved
  logsumexp, accumulates dK/dV for one k-block across all q-blocks.
* ``_dq_kernel``    — grid (B·H, Lq/bq, Lk/bk): accumulates dQ.

The TPU grid iterates its last dimension innermost/sequentially, which is
what lets the scratch accumulators persist across that dimension (the
standard Pallas flash pattern).  Under causal masking, fully-masked blocks
are skipped with `pl.when` — ~2× fewer FLOPs at long L.

Public entry: :func:`flash_attention` on (B, L, H, D) model-layout tensors,
with optional key-validity mask and causal masking, differentiable via
`jax.custom_vjp`.  On non-TPU backends the kernels run in Pallas interpret
mode, so the same code path is unit-testable on the CPU fake mesh
(SURVEY.md §4's test-strategy requirement).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific bits are unavailable in some CPU-only wheels
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30  # matches parallel.ring_attention.NEG_INF: keeps exp()
                 # NaN-free when an entire row is masked
_TINY = 1e-30
_VMEM_BYTES = 128 * 2**20  # v4/v5e/v5p VMEM ≈ 128 MiB; the budget below
                           # validates block sizes BEFORE launching Mosaic


def _check_vmem_budget(bq: int, bk: int, d: int) -> None:
    """Fail fast (and clearly) when the requested blocks cannot fit VMEM.

    Per grid step the fwd kernel holds the (bq, bk) f32 score/prob tile,
    q/k/v blocks (bq·d + 2·bk·d) plus the f32 accumulators (~bq·d), with
    Pallas double-buffering the HBM-windowed operands.  An oversized
    choice otherwise surfaces as an opaque Mosaic allocation error deep in
    compilation.  The check is deliberately a conservative estimate (×2
    for double buffering, f32 everywhere) against a ~128 MiB budget —
    kernels near the line may still fail in Mosaic, but the common
    mistake (block_q/block_k sized like sequence lengths) is caught here."""
    tile = bq * bk * 4                       # score/prob tile, f32
    operands = 2 * (bq * d + 2 * bk * d) * 4  # q + k/v, double-buffered
    acc = 2 * bq * d * 4 + 2 * bq * 4        # out accumulator + m/l rows
    need = tile + operands + acc
    if need > _VMEM_BYTES:
        raise ValueError(
            f"flash attention blocks block_q={bq}, block_k={bk} with "
            f"head_dim={d} need ≈{need / 2**20:.0f} MiB of VMEM "
            f"(> {_VMEM_BYTES / 2**20:.0f} MiB): the (block_q × block_k) "
            f"f32 score tile must fit alongside the q/k/v blocks — use "
            f"smaller blocks (defaults 512/1024)")


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _join_vma(*xs) -> frozenset:
    """Union of the operands' varying-axes sets — pallas_call outputs must
    declare their vma explicitly when running inside `jax.shard_map`
    (check_vma); outside shard_map this is the empty set."""
    vma = frozenset()
    for x in xs:
        vma |= jax.typeof(x).vma
    return vma


def _block_spec(shape, index_map):
    if _VMEM is None:
        return pl.BlockSpec(shape, index_map)
    return pl.BlockSpec(shape, index_map, memory_space=_VMEM)


def _causal_skip(i, j, bq, bk):
    """True when k-block j is entirely in the future of q-block i."""
    return j * bk > i * bq + bq - 1


def _unless_skipped(causal, i, j, bq, bk, body):
    """Run ``body`` now, or under `pl.when` if causal skipping applies."""
    if causal:
        pl.when(jnp.logical_not(_causal_skip(i, j, bq, bk)))(body)
    else:
        body()


def _tile_mask(s, i, j, bq, bk, causal, mask_blk):
    """Apply causal + key-validity masking to a (bq, bk) score tile."""
    if causal:
        qpos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    return jnp.where(mask_blk > 0.0, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, out_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, bq, bk, nk):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _tile_mask(s, i, j, bq, bk, causal, mask_ref[0])

        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_prev * corr + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p, v, preferred_element_type=jnp.float32)

    _unless_skipped(causal, i, j, bq, bk, compute)

    @pl.when(j == nk - 1)
    def _():
        l_safe = jnp.maximum(l_scr[:], _TINY)
        out_ref[0] = (acc_scr[:] / l_safe).astype(out_ref.dtype)
        lse_ref[0, 0] = (m_scr[:] + jnp.log(l_safe))[:, 0]


def _fwd(q, k, v, mask, scale, causal, bq, bk, interpret):
    """q (BH, Lq, D); k/v (BH, Lk, D); mask (BH, 1, Lk) → out, lse (BH, 1, Lq)."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    nq, nk = lq // bq, lk // bk

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nk=nk)
    # row-vector operands (mask, lse) carry a middle singleton dim so their
    # blocks are (1, 1, bL) — last two dims then satisfy the TPU tiling rule
    # (second-to-last == full array dim 1, last divisible by 128)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            _block_spec((1, bq, d), lambda b, i, j: (b, i, 0)),
            _block_spec((1, bk, d), lambda b, i, j: (b, j, 0)),
            _block_spec((1, bk, d), lambda b, i, j: (b, j, 0)),
            _block_spec((1, 1, bk), lambda b, i, j: (b, 0, j)),
        ],
        out_specs=[
            _block_spec((1, bq, d), lambda b, i, j: (b, i, 0)),
            _block_spec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype,
                                 vma=_join_vma(q, k, v, mask)),
            jax.ShapeDtypeStruct((bh, 1, lq), jnp.float32,
                                 vma=_join_vma(q, k, v, mask)),
        ],
        scratch_shapes=[
            _VMEM((bq, 1), jnp.float32) if _VMEM else None,
            _VMEM((bq, 1), jnp.float32) if _VMEM else None,
            _VMEM((bq, d), jnp.float32) if _VMEM else None,
        ],
        interpret=interpret,
    )(q, k, v, mask)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, bq, bk, nq):
    j, i = pl.program_id(1), pl.program_id(2)  # k-block outer, q-block inner

    @pl.when(i == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _tile_mask(s, i, j, bq, bk, causal, mask_ref[0])
        p = jnp.exp(s - lse_ref[0, 0][:, None])                   # (bq, bk)
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                    # pᵀ·dO
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                    # dsᵀ·q

    _unless_skipped(causal, i, j, bq, bk, compute)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, *, scale, causal, bq, bk, nk):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _tile_mask(s, i, j, bq, bk, causal, mask_ref[0])
        p = jnp.exp(s - lse_ref[0, 0][:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        dq_scr[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    _unless_skipped(causal, i, j, bq, bk, compute)

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd(q, k, v, mask, lse, delta, do, scale, causal, bq, bk, interpret):
    """delta = Σ_d do·out over the FULL attention output — callers computing
    blockwise/ring gradients pass the global delta (the flash backward math
    needs global lse + delta even for one k-block's contribution)."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    nq, nk = lq // bq, lk // bk

    qspec = _block_spec((1, bq, d), lambda b, x, y: (b, x, 0))
    kspec_q_outer = _block_spec((1, bk, d), lambda b, i, j: (b, j, 0))
    rowspec = _block_spec((1, 1, bq), lambda b, x, y: (b, 0, x))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk),
        grid=(bh, nq, nk),
        in_specs=[qspec, kspec_q_outer, kspec_q_outer,
                  _block_spec((1, 1, bk), lambda b, i, j: (b, 0, j)),
                  qspec, rowspec, rowspec],
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct(
            q.shape, q.dtype, vma=_join_vma(q, k, v, mask, do, lse, delta))],
        scratch_shapes=[_VMEM((bq, d), jnp.float32) if _VMEM else None],
        interpret=interpret,
    )(q, k, v, mask, do, lse, delta)[0]

    # k-block is the second grid dim here (accumulator persists over q-blocks)
    qspec_k_outer = _block_spec((1, bq, d), lambda b, j, i: (b, i, 0))
    kspec = _block_spec((1, bk, d), lambda b, j, i: (b, j, 0))
    rowspec_k_outer = _block_spec((1, 1, bq), lambda b, j, i: (b, 0, i))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq),
        grid=(bh, nk, nq),
        in_specs=[qspec_k_outer, kspec, kspec,
                  _block_spec((1, 1, bk), lambda b, j, i: (b, 0, j)),
                  qspec_k_outer, rowspec_k_outer, rowspec_k_outer],
        out_specs=[kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct(
                       k.shape, k.dtype,
                       vma=_join_vma(q, k, v, mask, do, lse, delta)),
                   jax.ShapeDtypeStruct(
                       v.shape, v.dtype,
                       vma=_join_vma(q, k, v, mask, do, lse, delta))],
        scratch_shapes=[_VMEM((bk, d), jnp.float32) if _VMEM else None,
                        _VMEM((bk, d), jnp.float32) if _VMEM else None],
        interpret=interpret,
    )(q, k, v, mask, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# differentiable core on (BH, L, D) arrays
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_core(q, k, v, mask, scale, causal, bq, bk, interpret):
    out, _ = _fwd(q, k, v, mask, scale, causal, bq, bk, interpret)
    return out


def _flash_core_fwd(q, k, v, mask, scale, causal, bq, bk, interpret):
    out, lse = _fwd(q, k, v, mask, scale, causal, bq, bk, interpret)
    return out, (q, k, v, mask, out, lse)


def _flash_core_bwd(scale, causal, bq, bk, interpret, res, do):
    q, k, v, mask, out, lse = res
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True).transpose(0, 2, 1)     # (BH, 1, Lq)
    dq, dk, dv = _bwd(q, k, v, mask, lse, delta, do,
                      scale, causal, bq, bk, interpret)
    return dq, dk, dv, jnp.zeros_like(mask)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def flash_attention(q, k, v, *, causal: bool = False,
                    scale: float | None = None, kv_mask=None,
                    block_q: int = 512, block_k: int = 1024,
                    interpret: bool | None = None):
    """Memory-efficient exact attention on model-layout tensors.

    Args:
      q: (B, Lq, H, D);  k, v: (B, Lk, H, D)  — same layout as
        `parallel.ring_attention.dense_attention` so the two are drop-in
        interchangeable inside models.
      causal: mask future positions (by absolute position, so Lq == Lk
        is expected when True).
      kv_mask: optional (B, Lk) key-validity mask (>0 == valid).
      block_q / block_k: VMEM tile sizes; clamped to the (padded) sequence
        lengths.  At the defaults (512, 1024), `bench.py --attention`
        measured fwd+bwd vs XLA dense attention on TPU v5e (B=4 H=8 D=128
        f32 causal): 3.1× faster at L=1024, 4.1× at L=4096 — recorded in
        BASELINE.md §attention.  The (bq × bk) f32 score tile must fit VMEM
        alongside the q/k/v blocks (2 MB at default).
      interpret: force Pallas interpret mode; default = auto (True off-TPU).

    Returns (B, Lq, H, D).  Rows with no valid key return 0 (same guard as
    ring_attention).
    """
    if interpret is None:
        interpret = _interpret_default()
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = scale if scale is not None else d ** -0.5

    mask = kv_mask if kv_mask is not None else jnp.ones((b, lk), jnp.float32)
    mask = mask.astype(jnp.float32)

    if interpret and _join_vma(q, k, v, mask):
        # inside shard_map on a non-TPU backend: Pallas's HLO interpreter
        # cannot currently lower under vma checking, so run the pure-jnp
        # kernel twin (identical math incl. NEG_INF/_TINY guards, and
        # differentiable by plain AD).  The real kernel covers TPU and
        # standalone-interpret tests; test_flash_block_primitives_match_
        # kernel ties the two together.
        out, _ = _fwd_block_ref(q, k, v, mask, scale, causal)
        return out

    bq = min(block_q, lq)
    bk = min(block_k, lk)
    if not interpret:  # the interpreter has no VMEM to budget
        _check_vmem_budget(bq, bk, d)
    pad_q = (-lq) % bq
    pad_k = (-lk) % bk
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        mask = jnp.pad(mask, ((0, 0), (0, pad_k)))  # padded keys invalid (0)
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))

    # (B, L, H, D) → (B·H, L, D); mask broadcasts per head
    def to_bh(x):
        return jnp.moveaxis(x, 2, 1).reshape(b * h, x.shape[1], d)

    # (B·H, 1, Lk): row b·H+h ← batch b; middle singleton for TPU tiling
    mask_bh = jnp.repeat(mask, h, axis=0)[:, None, :]
    out = _flash_core(to_bh(q), to_bh(k), to_bh(v), mask_bh,
                      scale, causal, bq, bk, interpret)
    out = jnp.moveaxis(out.reshape(b, h, lq + pad_q, d), 1, 2)
    if pad_q:
        out = out[:, :lq]
    return out


# ---------------------------------------------------------------------------
# blockwise primitives for ring attention (parallel/ring_attention.py)
# ---------------------------------------------------------------------------
#
# The ring schedule needs the kernel's RAW outputs — per-block (out, lse) on
# the forward, per-block (dq, dk, dv) given the GLOBAL lse/delta on the
# backward — because the cross-block softmax merge and the cross-device
# gradient accumulation happen at the ring layer, under its own custom_vjp.
# These wrappers only adapt layouts ((B, L, H, D) model layout ↔ the
# kernels' (B·H, L, D)) and handle block padding; they are NOT
# differentiable entry points themselves.

def _pad_seq(x, multiple):
    pad = (-x.shape[1]) % multiple
    if pad:
        cfg = [(0, 0)] * x.ndim
        cfg[1] = (0, pad)
        x = jnp.pad(x, cfg)
    return x, pad


def _to_bh(x):
    b, l, h, d = x.shape
    return jnp.moveaxis(x, 2, 1).reshape(b * h, l, d)


def _from_bh(x, b, h):
    bh, l, d = x.shape
    return jnp.moveaxis(x.reshape(b, h, l, d), 1, 2)


def _block_scores_masked(q, k, kv_mask, scale, causal):
    """f32 masked scores for one (q-block, k-block) pair, (B, H, Lq, Lk)."""
    s = jnp.einsum("blhd,bmhd->bhlm", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        qpos = jnp.arange(s.shape[-2])[:, None]
        kpos = jnp.arange(s.shape[-1])[None, :]
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    return jnp.where(kv_mask[:, None, None, :] > 0, s, NEG_INF)


def _fwd_block_ref(q, k, v, kv_mask, scale, causal):
    """Pure-jnp twin of the forward kernel for one block pair — the
    interpret-mode path: Pallas's HLO interpreter cannot currently lower
    inside `jax.shard_map`'s vma checking, so CPU-mesh tests of the ring
    schedule run this (bit-matching math incl. the NEG_INF/_TINY guards);
    the real kernels cover the same math on TPU and standalone-interpret
    tests (tests/test_flash_attention.py)."""
    s = _block_scores_masked(q, k, kv_mask, scale, causal)
    m = s.max(axis=-1)                                     # (B, H, Lq)
    p = jnp.exp(s - m[..., None])
    l = jnp.maximum(p.sum(axis=-1), _TINY)
    out = jnp.einsum("bhlm,bmhd->blhd", p, v.astype(jnp.float32))
    out = out / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype), m + jnp.log(l)


def _bwd_block_ref(q, k, v, kv_mask, do, lse, delta, scale, causal):
    """Pure-jnp twin of the backward kernels for one block pair (see
    _fwd_block_ref); p is recovered from the GLOBAL lse."""
    s = _block_scores_masked(q, k, kv_mask, scale, causal)
    p = jnp.exp(s - lse[..., None])                        # (B, H, Lq, Lk)
    do32 = do.astype(jnp.float32)
    dv = jnp.einsum("bhlm,blhd->bmhd", p, do32)
    dp = jnp.einsum("blhd,bmhd->bhlm", do32, v.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhlm,bmhd->blhd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhlm,blhd->bmhd", ds, q.astype(jnp.float32))
    return dq, dk, dv


def flash_fwd_block(q, k, v, kv_mask, *, scale, causal=False,
                    block_q: int = 512, block_k: int = 1024,
                    interpret: bool | None = None):
    """One flash forward over a (q-block, k-block) pair.

    q: (B, Lq, H, D); k/v: (B, Lk, H, D); kv_mask: (B, Lk) (>0 valid).
    Returns (out (B, Lq, H, D) in q.dtype, lse (B, H, Lq) f32).  ``causal``
    means the pair sits on the ring's diagonal (identical global offsets);
    off-diagonal causal blocks are entirely-past (causal=False) or
    entirely-future (skipped by the caller)."""
    if interpret is None:
        interpret = _interpret_default()
    if interpret:
        return _fwd_block_ref(q, k, v, kv_mask, scale, causal)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    bq, bk = min(block_q, lq), min(block_k, lk)
    _check_vmem_budget(bq, bk, d)
    q, pad_q = _pad_seq(q, bq)
    k, _ = _pad_seq(k, bk)
    v, pad_k = _pad_seq(v, bk)
    mask = kv_mask.astype(jnp.float32)
    if pad_k:
        mask = jnp.pad(mask, ((0, 0), (0, pad_k)))
    mask_bh = jnp.repeat(mask, h, axis=0)[:, None, :]
    out, lse = _fwd(_to_bh(q), _to_bh(k), _to_bh(v), mask_bh,
                    scale, causal, bq, bk, interpret)
    out = _from_bh(out, b, h)[:, :lq]
    lse = lse.reshape(b, h, lq + pad_q)[:, :, :lq]
    return out, lse


def flash_bwd_block(q, k, v, kv_mask, do, lse, delta, *, scale, causal=False,
                    block_q: int = 512, block_k: int = 1024,
                    interpret: bool | None = None):
    """Per-block gradients given the GLOBAL softmax statistics.

    lse/delta: (B, H, Lq) — log-sum-exp of the FULL row and Σ_d do·out of
    the FULL output (flash's backward recovers this block's probabilities
    as exp(s − lse)).  Returns (dq, dk, dv) in f32, each the contribution
    of this (q-block, k-block) pair alone."""
    if interpret is None:
        interpret = _interpret_default()
    if interpret:
        return _bwd_block_ref(q, k, v, kv_mask, do, lse, delta, scale,
                              causal)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    bq, bk = min(block_q, lq), min(block_k, lk)
    _check_vmem_budget(bq, bk, d)
    q, pad_q = _pad_seq(q, bq)
    do, _ = _pad_seq(do, bq)
    k, _ = _pad_seq(k, bk)
    v, pad_k = _pad_seq(v, bk)
    mask = kv_mask.astype(jnp.float32)
    if pad_k:
        mask = jnp.pad(mask, ((0, 0), (0, pad_k)))
    if pad_q:
        # padded q rows: lse NEG_INF ⇒ p = exp(s − (−∞)) would blow up;
        # use +large lse instead so p underflows to 0 and contributes nothing
        pad_rows = ((0, 0), (0, 0), (0, pad_q))
        lse = jnp.pad(lse, pad_rows, constant_values=-NEG_INF)
        delta = jnp.pad(delta, pad_rows)
    mask_bh = jnp.repeat(mask, h, axis=0)[:, None, :]
    lse_bh = lse.reshape(b * h, 1, lq + pad_q)
    delta_bh = delta.astype(jnp.float32).reshape(b * h, 1, lq + pad_q)
    dq, dk, dv = _bwd(
        _to_bh(q).astype(jnp.float32), _to_bh(k).astype(jnp.float32),
        _to_bh(v).astype(jnp.float32), mask_bh, lse_bh, delta_bh,
        _to_bh(do).astype(jnp.float32), scale, causal, bq, bk, interpret)
    dq = _from_bh(dq, b, h)[:, :lq]
    dk = _from_bh(dk, b, h)[:, :lk]
    dv = _from_bh(dv, b, h)[:, :lk]
    return dq, dk, dv
