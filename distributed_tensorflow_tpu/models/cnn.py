"""Small convnet for MNIST-class workloads.

The BASELINE.json headline config is "MNIST CNN"; the reference itself ships
only the MLP (reference initializer.py:14-19) and hints at uncommitted
CIFAR-10 experiments (reference .gitignore:1-4).  Conv layers map directly
onto the MXU; keep channel counts multiples of 8 for good tiling.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CNN(nn.Module):
    num_classes: int = 10
    features: tuple[int, ...] = (32, 64)
    dense: int = 128
    dropout_rate: float = 0.25
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if x.ndim == 3:  # (B, H, W) → add channel dim
            x = x[..., None]
        for feat in self.features:
            x = nn.Conv(feat, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.dense, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
