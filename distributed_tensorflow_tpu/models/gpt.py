"""GPT-style decoder-only causal language model.

The reference has no language models at all (SURVEY.md §2.2: its only model
is an MLP on 28×28, reference initializer.py:14-19) — this is TPU-native new
capability completing the model-family story: the framework's long-context
machinery (Pallas flash attention, ring/Ulysses sequence parallelism) exists
for exactly this workload, and a decoder LM is the model that exercises the
causal paths end-to-end (BERT only ever runs them non-causally).

Architecture: pre-LN transformer decoder (the trainable-at-depth variant),
learned positional embeddings, weight-tied LM head (`nn.Embed.attend`) —
tying keeps the biggest matrix single-copy in HBM and is standard for GPT-2
class models.  Logits are (B, L, V) for next-token prediction; the engines'
loss/eval broadcast over label dims (engines/base.py `cross_entropy`,
`token_weights`), so the same SyncEngine/FSDP/TP machinery that trains
classifiers trains this LM with zero engine-side special cases.

Attention is pluggable exactly like BERT (models/bert.py) but always causal:
  'dense'      — full causal attention; any mesh.
  'flash'      — Pallas flash kernel (ops/flash_attention.py), causal=True:
                 the kernel skips entirely-future blocks (~2× FLOPs saved)
                 and never materializes (L, L) scores in HBM.
  'ring'       — causal ring attention over the 'seq' mesh axis (inside
                 shard_map; engines/seq_parallel.py).
  'ring_flash' — ring schedule with flash local math: entirely-future
                 blocks never even launch a kernel.
  'ulysses'    — all-to-all head-parallel, causal.

``partition_model=True`` adds the same Megatron GSPMD annotations as BERT
(models/bert.py:28-34): QKV column-parallel, attention out + FFN-down
row-parallel, FFN-up column-parallel, token embedding vocab-sharded.  With
the tied head, `attend`'s contraction against the vocab-sharded embedding
makes the logits vocab-sharded too — XLA keeps the (B, L, V) tensor
distributed through the softmax-cross-entropy, never gathering V onto one
device (the Megatron vocab-parallel-loss layout, for free from GSPMD).
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax.numpy as jnp

from distributed_tensorflow_tpu.parallel import collectives as coll
from distributed_tensorflow_tpu.parallel import compression
from distributed_tensorflow_tpu.parallel import mesh as meshlib
from distributed_tensorflow_tpu.parallel.ring_attention import (
    dense_attention, ring_attention, ring_flash_attention,
    ulysses_attention, ulysses_flash_attention)


def _part(init, spec, enabled: bool):
    """Megatron annotation, applied only when TP-partitioned (mirrors
    models/bert.py:48-52: unannotated modules keep plain initializers so
    non-GSPMD engines see ordinary unboxed params)."""
    return nn.with_partitioning(init, spec) if enabled else init


def apply_rope(x, pos, base: float = 10000.0):
    """Rotary position embedding over the head dim (half-split layout).

    ``x``: (B, L, H, D) with D even; ``pos``: (B, L) or (1, L) absolute
    positions.  Rotation is a per-position preprocessing of q/k, so it
    composes unchanged with every attention impl — dense, the Pallas flash
    kernel, and the ring/Ulysses schedules (whose blocks receive globally
    offset positions) — and with the KV cache (the cached k is stored
    already rotated at its own position)."""
    d2 = x.shape[-1] // 2
    inv = base ** (-jnp.arange(d2, dtype=jnp.float32) / d2)
    ang = pos.astype(jnp.float32)[..., None] * inv        # (B, L, D/2)
    cos = jnp.cos(ang)[:, :, None, :]                     # (B, L, 1, D/2)
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :d2].astype(jnp.float32), x[..., d2:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


class CausalSelfAttention(nn.Module):
    """Multi-head causal self-attention with pluggable block math."""

    hidden: int = 128
    heads: int = 4
    attention_impl: str = "dense"
    seq_axis: str = "seq"
    partition_model: bool = False
    decode: bool = False       # KV-cache mode: one token in, attend against
                               # everything cached (see ``generate``)
    max_len: int = 512         # cache capacity in decode mode
    rope: bool = False         # rotate q/k by position (RoPE) — requires
                               # the caller to pass ``pos``
    kv_heads: int | None = None  # GQA: K/V head count < query heads
                               # (None = heads, standard MHA; 1 = MQA).
                               # Shrinks the decode cache by heads/kv_heads.
    dtype: jnp.dtype = jnp.float32
    decode_slots: bool = False   # serving mode: the batch dim is a SLOT
                               # table (serving/kv_cache.py) — the caller
                               # passes per-slot write positions, cache
                               # writes are per-row scatters, and validity
                               # is length-driven, so one compiled decode
                               # step advances slots of any age
    kv_quant: bool = False     # int8 KV storage (decode_slots only): K/V
                               # cached as int8 with one f32 max-abs scale
                               # per written vector (slot × position ×
                               # head; parallel/compression.py channel
                               # quantizer), dequantized on the attention
                               # read — the stored table is what shrinks
    paged_blocks: int = 0      # >0: paged KV layout (decode_slots only).
                               # The cache becomes ONE physical pool of
                               # this many (paged_block, kvh, head_dim)
                               # blocks shared by every slot; the caller
                               # passes per-slot int32 block tables and
                               # owns allocation/aliasing/CoW
                               # (serving/kv_cache.py PagedSlotKVCache)
    paged_block: int = 16      # tokens per physical block (must divide
                               # max_len)
    paged_fused: bool = False  # read the pool through the fused Pallas
                               # kernel (ops/paged_attention.py) instead
                               # of gather + dense — the gather path is
                               # bitwise the monolithic math (prefill /
                               # oracle); the fused path is the decode
                               # hot op (tolerance parity)

    @nn.compact
    def __call__(self, x, pos=None, block_tables=None):
        head_dim = self.hidden // self.heads
        tp = self.partition_model
        if self.rope and pos is None:
            raise ValueError("rope=True needs the caller to pass positions")
        kvh = self.kv_heads if self.kv_heads is not None else self.heads
        if kvh < 1 or self.heads % kvh:
            raise ValueError(
                f"kv_heads must be a positive divisor of heads "
                f"{self.heads}, got {kvh}")

        # column-parallel QKV (packed output dim sharded over 'model');
        # plain Dense for the same partial-manual-shard_map reason as BERT
        # (models/bert.py:73-76).  Under GQA the K/V projections emit
        # kv_heads — the parameter and (cached) activation saving — and the
        # heads broadcast back to query count right before the attention
        # math (post-cache, so the cache stays small).
        def proj(name, n_heads):
            h = nn.Dense(
                n_heads * head_dim, dtype=self.dtype, name=name,
                kernel_init=_part(nn.initializers.lecun_normal(),
                                  (None, meshlib.MODEL_AXIS), tp),
                bias_init=_part(nn.initializers.zeros_init(),
                                (meshlib.MODEL_AXIS,), tp))(x)
            return h.reshape(h.shape[:-1] + (n_heads, head_dim))

        q = proj("query", self.heads)
        k, v = proj("key", kvh), proj("value", kvh)
        if self.rope:
            q, k = apply_rope(q, pos), apply_rope(k, pos)

        def widen(t):
            """kv_heads → heads by group broadcast (no-op for MHA)."""
            if kvh == self.heads:
                return t
            return jnp.repeat(t, self.heads // kvh, axis=2)
        if self.decode:
            # append this step's K/V at the cache cursor, attend q against
            # the whole cache with a validity mask — O(max_len) per token
            # instead of O(L²) re-prefill.  The cursor is causal masking:
            # positions past it are NEG_INF'd, so no triangular mask needed.
            # CONTRACT: at most max_len tokens total.  The cursor is a
            # traced value, so overflow cannot raise here — past capacity,
            # dynamic_update_slice clamps and the newest token overwrites
            # slot max_len-1.  `generate` (the supported entry) checks
            # prompt+max_new_tokens against max_len eagerly; direct
            # decode-API users get a sticky ``cache['overflow']`` flag
            # (ADVICE r3: the silent clamp corrupted continuations with no
            # signal) — check it after the decode loop.
            if x.shape[1] != 1 and not self.decode_slots:
                raise ValueError(
                    f"decode mode consumes one token per call, got "
                    f"sequence length {x.shape[1]}")
            if self.kv_quant and not self.decode_slots:
                raise ValueError(
                    "kv_quant=True is a slot-table storage mode: it "
                    "requires decode_slots=True (the serving engine owns "
                    "the quantized table)")
            import jax

            b = x.shape[0]
            if self.decode_slots:
                # SLOT decode (serving/kv_cache.py): each batch row is an
                # independent slot with its own age.  The write index is
                # the caller-supplied per-slot position (= the slot's
                # current length), the write a per-row scatter, and the
                # validity mask length-driven — so the SAME compiled step
                # advances a slot mid-prefill-history and a slot hundreds
                # of tokens deep at once.  No cursor/overflow variables:
                # positions are external state owned by the serving
                # engine, which guards capacity at admission time
                # (prompt + max_new_tokens ≤ max_len — the host-side
                # twin of the scalar path's sticky overflow flag).
                # CHUNK-RESUME CONTRACT: because the position is caller-
                # supplied and validity is derived from it alone, prefill
                # may stop at any position and resume later (chunked
                # prefill) or start PAST zero over externally-written KV
                # (a prefix-cache hit restores blocks 0..p-1 and resumes
                # at p) — the per-token math is identical either way,
                # which is what makes chunked admission bitwise equal to
                # monolithic admission (tests/test_serving.py).
                # TOKEN-BLOCK CONTRACT (speculative verify): the same
                # mode also accepts a (B, L) block of L consecutive
                # tokens per slot — all L K/V vectors scatter into the
                # cache first, then each query attends under a PER-QUERY
                # validity mask (positions ≤ its own), so position j's
                # logits condition on exactly the block prefix 0..j plus
                # the cache: one batched step scores k draft tokens + the
                # committed token, and rejected positions are invalidated
                # by length bookkeeping alone
                # (serving/kv_cache.py verify_block).
                # MULTI-STEP CONTRACT (fused k-iteration decode,
                # serving/kv_cache.py advance_multi): a lax.scan drives
                # this same step k times with token feedback on device,
                # freezing each slot's position once it deactivates
                # (EOS/budget) — a deactivated row keeps scattering its
                # stale token at the SAME frozen position every
                # remaining iteration.  That rewrite is safe by the two
                # properties already stated above: the scatter is
                # per-(row, position) so it only ever touches the one
                # cell past the frozen length, and validity is derived
                # from the caller's length vector alone, so the junk
                # cell is invisible to attention until a real token
                # advances the length and overwrites it first.  No
                # active-mask plumbing reaches this layer — inactive
                # slots are a host-side fiction, which is what keeps
                # the fused program identical to k calls of the
                # single-step program (the bitwise-parity pin in
                # tests/test_serving_multistep.py).
                if pos is None:
                    raise ValueError(
                        "decode_slots=True needs per-slot positions "
                        "(B, 1) — the serving engine passes the slot "
                        "length vector")
                if self.paged_blocks:
                    # PAGED layout (vLLM PagedAttention): the cache
                    # variables are ONE pool of physical blocks shared by
                    # all slots + nothing per-slot on device — each row's
                    # writes scatter through its caller-supplied block
                    # table, and reads either gather the table back
                    # (bitwise the monolithic math — the prefill/oracle
                    # path) or run the fused Pallas kernel that follows
                    # the table in-kernel (the decode/verify hot op).
                    # Aliasing is invisible here by design: two tables
                    # pointing at one block read identical KV, which is
                    # exactly the zero-copy prefix share.
                    out = self._paged_attend(x, q, k, v, pos, block_tables,
                                             widen, kvh, head_dim)
                    out = out.reshape(out.shape[:-2]
                                      + (self.heads * head_dim,))
                    return nn.Dense(
                        self.hidden, dtype=self.dtype, name="out",
                        kernel_init=_part(
                            nn.initializers.lecun_normal(),
                            (meshlib.MODEL_AXIS, None), tp))(out)
                ready = self.has_variable("cache", "cached_key")
                store = jnp.int8 if self.kv_quant else self.dtype
                ck = self.variable(
                    "cache", "cached_key", jnp.zeros,
                    (b, self.max_len, kvh, head_dim), store)
                cv = self.variable(
                    "cache", "cached_value", jnp.zeros,
                    (b, self.max_len, kvh, head_dim), store)
                if self.kv_quant:
                    # one f32 max-abs scale per written K/V vector (slot
                    # × position × head), stored alongside the table in
                    # the same cache pytree — the slot dim shards
                    # identically (parallel/mesh.kv_slot_sharding handles
                    # the 3-dim leaf), and a write never requantizes
                    # older entries
                    ks = self.variable(
                        "cache", "key_scale", jnp.zeros,
                        (b, self.max_len, kvh), jnp.float32)
                    vs = self.variable(
                        "cache", "value_scale", jnp.zeros,
                        (b, self.max_len, kvh), jnp.float32)
                if not ready:
                    out = dense_attention(q, widen(k), widen(v),
                                          causal=True)
                elif x.shape[1] == 1 and not self.kv_quant:
                    idx = pos[:, 0]
                    rows = jnp.arange(b)
                    # cast to the table's dtype: the serving engine may
                    # store the KV table narrower than the compute dtype
                    # (SlotKVCache kv_dtype — bf16 halves KV memory); a
                    # same-dtype astype is the identity, so the default
                    # program is untouched
                    ck.value = ck.value.at[rows, idx].set(
                        k[:, 0].astype(ck.value.dtype))
                    cv.value = cv.value.at[rows, idx].set(
                        v[:, 0].astype(cv.value.dtype))
                    valid = (jnp.arange(self.max_len)[None, :]
                             <= idx[:, None]).astype(self.dtype)
                    out = dense_attention(
                        q, widen(ck.value), widen(cv.value),
                        causal=False, kv_mask=valid)
                else:
                    # token-block write (speculative verify) and/or int8
                    # storage: scatter every position's K/V (+ scale),
                    # then attend each query against the table under its
                    # own position mask — the L == 1 case of this path is
                    # the same math as the branch above
                    idx = pos                       # (B, L)
                    rows = jnp.arange(b)[:, None]
                    if self.kv_quant:
                        qk, sk = compression.int8_channel_encode(k)
                        qv, sv = compression.int8_channel_encode(v)
                        ck.value = ck.value.at[rows, idx].set(qk)
                        cv.value = cv.value.at[rows, idx].set(qv)
                        ks.value = ks.value.at[rows, idx].set(sk)
                        vs.value = vs.value.at[rows, idx].set(sv)
                        keys = compression.int8_channel_decode(
                            ck.value, ks.value, self.dtype)
                        vals = compression.int8_channel_decode(
                            cv.value, vs.value, self.dtype)
                    else:
                        ck.value = ck.value.at[rows, idx].set(
                            k.astype(ck.value.dtype))
                        cv.value = cv.value.at[rows, idx].set(
                            v.astype(cv.value.dtype))
                        keys, vals = ck.value, cv.value
                    valid = (jnp.arange(self.max_len)[None, None, :]
                             <= idx[:, :, None]).astype(self.dtype)
                    out = dense_attention(
                        q, widen(keys), widen(vals),
                        causal=False, kv_mask=valid)
                out = out.reshape(out.shape[:-2]
                                  + (self.heads * head_dim,))
                # same name="out" as the shared projection below: only one
                # branch ever executes, so the param tree stays identical
                # to every other mode — a training checkpoint serves as-is
                return nn.Dense(
                    self.hidden, dtype=self.dtype, name="out",
                    kernel_init=_part(nn.initializers.lecun_normal(),
                                      (meshlib.MODEL_AXIS, None), tp))(out)
            # has_variable is False exactly during .init(): create the cache
            # zeros but do NOT write/advance — init-time mutations persist
            # into the returned variables, which would hand `generate` a
            # cache already holding the dummy init token (cursor at 1)
            ready = self.has_variable("cache", "cached_key")
            ck = self.variable(
                "cache", "cached_key", jnp.zeros,
                (b, self.max_len, kvh, head_dim), self.dtype)
            cv = self.variable(
                "cache", "cached_value", jnp.zeros,
                (b, self.max_len, kvh, head_dim), self.dtype)
            cur = self.variable("cache", "cache_index",
                                lambda: jnp.zeros((), jnp.int32))
            ovf = self.variable("cache", "overflow",
                                lambda: jnp.zeros((), jnp.bool_))
            if not ready:
                out = dense_attention(q, widen(k), widen(v), causal=True)
            else:
                i = cur.value
                # sticky overflow marker: True once a token would land past
                # capacity (dynamic_update_slice is about to clamp)
                ovf.value = ovf.value | (i >= self.max_len)
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, k, (0, i, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, v, (0, i, 0, 0))
                cur.value = i + 1
                valid = (jnp.arange(self.max_len) <= i).astype(self.dtype)
                out = dense_attention(
                    q, widen(ck.value), widen(cv.value), causal=False,
                    kv_mask=jnp.broadcast_to(valid[None, :],
                                             (b, self.max_len)))
        elif self.attention_impl == "ring":
            out = ring_attention(q, widen(k), widen(v), axis=self.seq_axis,
                                 causal=True)
        elif self.attention_impl == "ring_flash":
            out = ring_flash_attention(q, widen(k), widen(v),
                                       axis=self.seq_axis, causal=True)
        elif self.attention_impl == "ulysses":
            out = ulysses_attention(q, widen(k), widen(v),
                                    axis=self.seq_axis, causal=True)
        elif self.attention_impl == "ulysses_flash":
            out = ulysses_flash_attention(q, widen(k), widen(v),
                                          axis=self.seq_axis, causal=True)
        elif self.attention_impl == "flash":
            from distributed_tensorflow_tpu.ops import flash_attention
            out = flash_attention(q, widen(k), widen(v), causal=True)
        else:
            out = dense_attention(q, widen(k), widen(v), causal=True)
        out = out.reshape(out.shape[:-2] + (self.heads * head_dim,))
        # row-parallel output projection — the pair's single all-reduce
        return nn.Dense(
            self.hidden, dtype=self.dtype, name="out",
            kernel_init=_part(nn.initializers.lecun_normal(),
                              (meshlib.MODEL_AXIS, None), tp))(out)

    def _paged_attend(self, x, q, k, v, pos, block_tables, widen,
                      kvh, head_dim):
        """Paged KV write + read (decode_slots + paged_blocks > 0).

        Cache variables are the shared physical pools; per-slot state is
        the caller's block table.  Writes scatter each (row, position)
        K/V vector into ``pool[bt[row, pos // blk], pos % blk]``; reads
        go fused (Pallas kernel) or unfused (gather + dense — bitwise
        the monolithic token-block branch's math over the gathered
        table, which is what keeps paged prefill exactly equal to
        monolithic prefill)."""
        b = x.shape[0]
        blk = self.paged_block
        if self.max_len % blk:
            raise ValueError(
                f"paged_block={blk} must divide max_len={self.max_len}")
        ready = self.has_variable("cache", "key_pool")
        store = jnp.int8 if self.kv_quant else self.dtype
        kp = self.variable(
            "cache", "key_pool", jnp.zeros,
            (self.paged_blocks, blk, kvh, head_dim), store)
        vp = self.variable(
            "cache", "value_pool", jnp.zeros,
            (self.paged_blocks, blk, kvh, head_dim), store)
        if self.kv_quant:
            ksp = self.variable(
                "cache", "key_scale_pool", jnp.zeros,
                (self.paged_blocks, blk, kvh), jnp.float32)
            vsp = self.variable(
                "cache", "value_scale_pool", jnp.zeros,
                (self.paged_blocks, blk, kvh), jnp.float32)
        if not ready:
            # .init(): create the pools, write nothing (the same
            # init-time guard as the monolithic cache)
            return dense_attention(q, widen(k), widen(v), causal=True)
        if block_tables is None:
            raise ValueError(
                "paged decode needs block_tables (B, max_blocks) — the "
                "serving engine passes each slot's block table")
        idx = pos                                    # (B, L)
        # positions past max_len (pad rows of a chunk-scan bucket) must
        # DROP like the monolithic scatter does — but gather CLAMPS, so
        # an unclamped table lookup would alias the slot's own last
        # block.  Route oob positions to an oob OFFSET instead: the
        # block-id gather is clamped harmlessly and the scatter's
        # default drop rule discards the write.
        j = idx // blk
        oob = j >= block_tables.shape[1]
        blk_ids = jnp.take_along_axis(
            block_tables, jnp.minimum(j, block_tables.shape[1] - 1), axis=1)
        off = jnp.where(oob, blk, idx % blk)
        if self.kv_quant:
            qk, sk = compression.int8_channel_encode(k)
            qv, sv = compression.int8_channel_encode(v)
            kp.value = kp.value.at[blk_ids, off].set(qk)
            vp.value = vp.value.at[blk_ids, off].set(qv)
            ksp.value = ksp.value.at[blk_ids, off].set(sk)
            vsp.value = vsp.value.at[blk_ids, off].set(sv)
        else:
            kp.value = kp.value.at[blk_ids, off].set(
                k.astype(kp.value.dtype))
            vp.value = vp.value.at[blk_ids, off].set(
                v.astype(vp.value.dtype))
        if self.paged_fused:
            from distributed_tensorflow_tpu.ops.paged_attention import (
                paged_attention)
            return paged_attention(
                q, kp.value, vp.value, block_tables, idx[:, 0],
                k_scale=ksp.value if self.kv_quant else None,
                v_scale=vsp.value if self.kv_quant else None,
            ).astype(self.dtype)
        # unfused: gather the logical table back through the block table
        # and run the SAME masked dense attention as the monolithic
        # token-block branch — garbage rows from unmapped entries sit
        # past the validity mask
        t = self.max_len
        keys = jnp.take(kp.value, block_tables, axis=0).reshape(
            b, t, kvh, head_dim)
        vals = jnp.take(vp.value, block_tables, axis=0).reshape(
            b, t, kvh, head_dim)
        if self.kv_quant:
            kscale = jnp.take(ksp.value, block_tables, axis=0).reshape(
                b, t, kvh)
            vscale = jnp.take(vsp.value, block_tables, axis=0).reshape(
                b, t, kvh)
            keys = compression.int8_channel_decode(keys, kscale, self.dtype)
            vals = compression.int8_channel_decode(vals, vscale, self.dtype)
        valid = (jnp.arange(t)[None, None, :]
                 <= idx[:, :, None]).astype(self.dtype)
        return dense_attention(q, widen(keys), widen(vals),
                               causal=False, kv_mask=valid)


class GPTBlock(nn.Module):
    """Pre-LN decoder block: x + attn(LN(x)); x + ffn(LN(x)).

    ``moe_experts > 0`` swaps the dense FFN for a routed MoE layer
    (models/moe.py MoELayer) over the block's tokens — the long-context
    MoE shape: under sequence parallelism each seq device routes its own
    token block to the globally-sharded experts (the dispatch einsums stay
    GSPMD over 'expert' while 'seq' is a manual shard_map axis,
    engines/composite.py).  The router's aux/z losses and overflow sow
    into ``intermediates`` exactly as in MoEClassifier."""

    hidden: int = 128
    heads: int = 4
    ffn: int = 512
    dropout_rate: float = 0.1
    attention_impl: str = "dense"
    seq_axis: str = "seq"
    partition_model: bool = False
    decode: bool = False
    max_len: int = 512
    rope: bool = False
    kv_heads: int | None = None
    dtype: jnp.dtype = jnp.float32
    moe_experts: int = 0         # 0 = dense FFN; >0 = routed experts
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    partition_experts: bool = False
    decode_slots: bool = False   # serving slot-table decode (see attention)
    kv_quant: bool = False       # int8 KV storage (see attention)
    paged_blocks: int = 0        # paged KV pool size (see attention)
    paged_block: int = 16        # tokens per physical block
    paged_fused: bool = False    # fused Pallas paged read (see attention)

    @nn.compact
    def __call__(self, x, train: bool = False, pos=None, block_tables=None):
        tp = self.partition_model
        y = CausalSelfAttention(self.hidden, self.heads, self.attention_impl,
                                self.seq_axis, tp, self.decode, self.max_len,
                                self.rope, self.kv_heads, self.dtype,
                                decode_slots=self.decode_slots,
                                kv_quant=self.kv_quant,
                                paged_blocks=self.paged_blocks,
                                paged_block=self.paged_block,
                                paged_fused=self.paged_fused)(
                                    nn.LayerNorm(dtype=self.dtype)(x), pos,
                                    block_tables)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype)(x)
        if self.moe_experts > 0:
            from distributed_tensorflow_tpu.models.moe import moe_ffn

            y = moe_ffn(y, hidden=self.ffn, moe_experts=self.moe_experts,
                        moe_top_k=self.moe_top_k,
                        moe_capacity_factor=self.moe_capacity_factor,
                        partition_experts=self.partition_experts,
                        partition_model=tp, dtype=self.dtype)
        else:
            # Megatron FFN: column-parallel up, row-parallel down
            y = nn.Dense(
                self.ffn, dtype=self.dtype,
                kernel_init=_part(nn.initializers.lecun_normal(),
                                  (None, meshlib.MODEL_AXIS), tp),
                bias_init=_part(nn.initializers.zeros_init(),
                                (meshlib.MODEL_AXIS,), tp))(y)
            y = nn.gelu(y)
            y = nn.Dense(
                self.hidden, dtype=self.dtype,
                kernel_init=_part(nn.initializers.lecun_normal(),
                                  (meshlib.MODEL_AXIS, None), tp))(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return x + y


class GPTLM(nn.Module):
    """Decoder-only causal LM: token ids (B, L) → next-token logits (B, L, V).

    ``causal_lm = True`` is the marker the harness/engines read to route
    LM-shaped labels ((B, L) targets sharded over data AND seq axes,
    engines/seq_parallel.py) — the model itself never shifts anything; the
    dataset supplies (inputs, next-token targets) pairs (data/loaders.py
    ``lm_synth``).
    """

    vocab_size: int = 256
    hidden: int = 128
    layers: int = 2
    heads: int = 4
    ffn: int = 512
    max_len: int = 512
    dropout_rate: float = 0.1
    attention_impl: str = "dense"
    seq_axis: str = "seq"
    partition_model: bool = False
    decode: bool = False       # KV-cache autoregressive mode (see `generate`)
    positional: str = "learned"  # learned | rope (rotary: no position
                                 # table; q/k rotated by absolute position
                                 # in every attention layer)
    kv_heads: int | None = None  # GQA/MQA: K/V heads < query heads
    tie_embeddings: bool = True
    moe_experts: int = 0         # >0: every block's FFN is a routed MoE
                                 # layer (models/moe.py) — the long-context
                                 # MoE shape; composes with ring/Ulysses
                                 # seq parallelism (engines/composite.py
                                 # ep×sp: experts GSPMD-sharded over
                                 # 'expert' while 'seq' stays manual)
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    partition_experts: bool = False
    remat: bool = False          # activation checkpointing: store only each
                                 # block's INPUT, recompute the block in
                                 # backward — activation memory drops from
                                 # O(layers · per-block intermediates) to
                                 # O(layers · hidden) + one block's
                                 # intermediates, at ~1/3 extra FLOPs.  The
                                 # long-context lever: composes with
                                 # ring/Ulysses seq parallelism (the ring's
                                 # ppermutes replay symmetrically on every
                                 # seq device during recompute).
    dtype: jnp.dtype = jnp.float32
    decode_slots: bool = False   # serving: the batch dim is a SLOT table
                                 # (serving/kv_cache.py) — the caller passes
                                 # per-slot ``positions`` and owns the
                                 # length/active bookkeeping; one compiled
                                 # decode step advances slots of any age
    kv_quant: bool = False       # int8 KV storage with per-vector f32
                                 # scales (decode_slots only; --serve-kv-
                                 # dtype int8 — the stored table is ~¼ of
                                 # f32, ~½ of bf16)
    paged_blocks: int = 0        # >0: paged KV layout (decode_slots only;
                                 # --serve-kv-layout paged) — one shared
                                 # physical block pool + caller-owned
                                 # per-slot block tables instead of
                                 # (slots, max_len) rows
    paged_block: int = 16        # tokens per physical block (divides
                                 # max_len)
    paged_fused: bool = False    # fused Pallas paged-attention read
                                 # (ops/paged_attention.py)

    causal_lm = True  # read by engines/harness to select the LM data layout

    @nn.compact
    def __call__(self, token_ids, train: bool = False, positions=None,
                 block_tables=None):
        seq_parallel = self.attention_impl in ("ring", "ring_flash",
                                               "ulysses", "ulysses_flash")
        lq = token_ids.shape[1]
        if self.decode_slots and not self.decode:
            raise ValueError("decode_slots=True requires decode=True "
                             "(slot serving is a KV-cache decode mode)")
        if positions is not None and not self.decode_slots:
            raise ValueError(
                "positions is only accepted in decode_slots mode — every "
                "other mode derives positions internally (cursor/offset)")
        if self.paged_blocks and not self.decode_slots:
            raise ValueError(
                "paged_blocks > 0 is a serving storage layout: it "
                "requires decode_slots=True (the serving engine owns the "
                "block tables)")
        if block_tables is not None and not self.paged_blocks:
            raise ValueError(
                "block_tables is only accepted in paged decode_slots "
                "mode (paged_blocks > 0)")
        if self.decode:
            if seq_parallel:
                # the hard constraint: ring/ulysses run inside shard_map
                # with a manual 'seq' axis whose collectives assume every
                # device holds a full-length sequence block — a one-token
                # decode step has no seq dimension to shard, so there is
                # nothing for the ring to rotate.  Decode instead uses
                # dense cache attention; multi-device decode shards the
                # BATCH over 'data' and (optionally, GSPMD) the heads/vocab
                # over 'model' — see `generate(mesh=...)`.
                raise ValueError(
                    "decode mode is incompatible with sequence-parallel "
                    "attention (ring/ring_flash/ulysses run in shard_map "
                    "over 'seq'; a 1-token step has no sequence to shard); "
                    "clone with attention_impl='dense' — `generate` does "
                    "this.  partition_model decode IS supported (GSPMD).")
            if self.decode_slots:
                # serving: per-slot positions come from the caller (the
                # slot length vector) — there is no shared cursor because
                # slots are at different depths by construction
                if positions is None:
                    raise ValueError(
                        "decode_slots=True needs positions (B, L): the "
                        "per-slot write index / position-embedding input")
                if positions.shape != token_ids.shape:
                    raise ValueError(
                        f"positions shape {positions.shape} must match "
                        f"token_ids shape {token_ids.shape}")
                pos = positions
            else:
                # the model-level cursor feeds the position embedding; each
                # attention layer keeps its own cache cursor in lockstep.
                # Not advanced during .init() (same guard as the attention
                # cache).
                ready = self.has_variable("cache", "pos_index")
                pcur = self.variable("cache", "pos_index",
                                     lambda: jnp.zeros((), jnp.int32))
                pos = pcur.value + jnp.arange(lq)[None, :]
                if ready:
                    pcur.value = pcur.value + lq
        elif seq_parallel:
            if lq * coll.axis_size(self.seq_axis) > self.max_len:
                raise ValueError(
                    f"sequence length {lq * coll.axis_size(self.seq_axis)} "
                    f"exceeds max_len={self.max_len}")
            # this device's token block starts at global position idx×lq
            offset = coll.axis_index(self.seq_axis) * lq
            pos = offset + jnp.arange(lq)[None, :]
        else:
            if lq > self.max_len:
                raise ValueError(
                    f"sequence length {lq} exceeds max_len={self.max_len}; "
                    f"raise max_len or shorten the input")
            pos = jnp.arange(lq)[None, :]

        embed = nn.Embed(
            self.vocab_size, self.hidden, dtype=self.dtype,
            name="token_embed",
            embedding_init=_part(nn.linear.default_embed_init,
                                 (meshlib.MODEL_AXIS, None),
                                 self.partition_model))
        if self.positional not in ("learned", "rope"):
            raise ValueError(
                f"unknown positional '{self.positional}'; learned | rope")
        rope = self.positional == "rope"
        x = embed(token_ids)
        if not rope:
            x = x + nn.Embed(self.max_len, self.hidden, dtype=self.dtype,
                             name="pos_embed")(pos)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        # remat: train (arg 2) is a static python bool; x and pos trace.
        # The wrapped class is instantiated with an explicit name pinned to
        # the unwrapped auto-name ("GPTBlock_{i}") — nn.remat renames the
        # class, and flax derives both the param-tree path AND the init RNG
        # stream from the module path, so without the pin a remat=True model
        # would initialize *different* params under *different* paths
        # (breaking remat/non-remat grad parity and cross-flag checkpoint
        # restore).
        if self.remat and self.moe_experts:
            raise ValueError(
                "remat + MoE blocks is unsupported: the router's sown "
                "intermediates (aux_loss/z_loss/overflow) would be re-sown "
                "during backward recompute, double-counting the balance "
                "losses; train MoE blocks without --remat")
        block_cls = (nn.remat(GPTBlock, static_argnums=(2,)) if self.remat
                     else GPTBlock)
        for i in range(self.layers):
            # slot decode threads pos regardless of rope: the attention
            # layer needs the per-slot write index, not just the rotation
            x = block_cls(self.hidden, self.heads, self.ffn,
                          self.dropout_rate, self.attention_impl,
                          self.seq_axis, self.partition_model,
                          self.decode, self.max_len, rope, self.kv_heads,
                          self.dtype, self.moe_experts, self.moe_top_k,
                          self.moe_capacity_factor, self.partition_experts,
                          decode_slots=self.decode_slots,
                          kv_quant=self.kv_quant,
                          paged_blocks=self.paged_blocks,
                          paged_block=self.paged_block,
                          paged_fused=self.paged_fused,
                          name=f"GPTBlock_{i}")(
                              x, train,
                              pos if (rope or self.decode_slots) else None,
                              block_tables)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        if self.tie_embeddings:
            # tied head: contraction against the (possibly vocab-sharded)
            # embedding — under TP the logits stay vocab-sharded through the
            # loss (Megatron vocab-parallel layout)
            logits = embed.attend(x)
        else:
            logits = nn.Dense(
                self.vocab_size, dtype=self.dtype, name="lm_head",
                kernel_init=_part(nn.initializers.lecun_normal(),
                                  (None, meshlib.MODEL_AXIS),
                                  self.partition_model))(x)
        return logits.astype(jnp.float32)


def generate(model: GPTLM, params, prompt, max_new_tokens: int, *,
             temperature: float = 1.0, greedy: bool = False, rng=None,
             mesh=None):
    """Autoregressive sampling with a KV cache: (B, Lp) prompt →
    (B, max_new_tokens) continuation.

    The inference counterpart the training framework would otherwise lack
    (no reference counterpart — the reference has no sequence models at
    all, SURVEY.md §2.2).  The model is cloned into decode mode (dense
    cache attention, dropout off); prompt tokens prefill the cache one at a
    time under `lax.scan`, then each new token costs one O(max_len)
    cache-attention step instead of an O(L²) re-prefill.  ``greedy=True``
    takes the argmax; otherwise tokens draw from
    ``softmax(logits / temperature)``.  Cache correctness is oracle-tested
    against teacher-forced full-forward rollout (tests/test_gpt.py).

    ``mesh`` enables multi-device decoding (GSPMD — the inference
    counterpart of the training-side parallelism):

    * the prompt batch and every cache leaf shard over the ``data`` axis
      (batch-parallel sampling: B must divide by the axis size);
    * with ``model.partition_model`` and a ``model`` mesh axis, params
      keep their Megatron layout — QKV/FFN matmuls stay head-sharded and
      the tied vocab-sharded head emits vocab-sharded logits whose
      argmax/categorical XLA resolves with its own collectives (TP
      decode).  Params already committed to the mesh (e.g. a TP engine's
      TrainState) are used in place; unsharded params replicate.
    * sequence-parallel attention cannot decode (see the in-model error:
      shard_map's manual 'seq' collectives need a sequence dimension a
      1-token step lacks) — ``generate`` always decodes with dense cache
      attention regardless of the training-time ``attention_impl``.

    Multi-device parity vs the single-device sampler is oracle-tested in
    tests/test_gpt.py.
    """
    import jax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    keep_tp = (mesh is not None and model.partition_model
               and meshlib.MODEL_AXIS in mesh.axis_names)
    dm = model.clone(decode=True, attention_impl="dense",
                     partition_model=keep_tp, dropout_rate=0.0)
    prompt = jnp.asarray(prompt)
    b, lp = prompt.shape
    if lp + max_new_tokens > model.max_len:
        raise ValueError(
            f"prompt ({lp}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"cache capacity max_len={model.max_len}")
    if rng is None:
        rng = jax.random.key(0)

    # fresh zero caches: shapes from an abstract init (eval_shape runs no
    # FLOPs — an eager dm.init here would pay a full unjitted forward pass
    # per generate call, dominating the cost the compiled-sampler cache
    # exists to avoid).  Every cache variable initializes to zeros, so
    # zeros-from-shape IS the init value.
    cache_shapes = jax.eval_shape(
        lambda: dm.init(jax.random.key(0), prompt[:, :1],
                        train=False))["cache"]
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)

    if mesh is not None:
        if meshlib.DATA_AXIS in mesh.axis_names:
            dp = mesh.shape[meshlib.DATA_AXIS]
            if b % dp:
                raise ValueError(
                    f"batch {b} not divisible by the data axis ({dp})")
            batch_spec = P(meshlib.DATA_AXIS)
        else:
            batch_spec = P()
        prompt = jax.device_put(
            prompt, NamedSharding(mesh, P(*batch_spec, None)))
        # cache leaves are (B, ...) tensors (KV, cursors are scalars):
        # shard the batch dim, replicate scalars
        cache = jax.tree.map(
            lambda t: jax.device_put(
                t, NamedSharding(
                    mesh,
                    P(*batch_spec, *([None] * (t.ndim - 1)))
                    if t.ndim else P())),
            cache)
        # params committed to this mesh (TP TrainState) are used in place;
        # anything else replicates onto the mesh
        repl = NamedSharding(mesh, P())
        target_devices = mesh.devices.tolist()

        def place(t):
            sh = getattr(t, "sharding", None)
            if isinstance(sh, NamedSharding) and (
                    sh.mesh is mesh
                    or sh.mesh.devices.tolist() == target_devices):
                return t
            return jax.device_put(t, repl)

        params = jax.tree.map(place, params)
        rng = jax.device_put(rng, repl)

    run = _compiled_sampler(dm, max_new_tokens, bool(greedy),
                            float(temperature))
    return run(params, cache, prompt, rng)


@functools.lru_cache(maxsize=32)
def _compiled_sampler(dm: GPTLM, max_new_tokens: int, greedy: bool,
                      temperature: float):
    """One jitted prefill+decode program per (model config, length, mode).

    linen Modules are frozen dataclasses (hashable by field values), so the
    lru_cache makes repeated `generate` calls — per-eval-batch sampling
    loops — reuse the compiled scans instead of paying full XLA compilation
    on every call (params/cache/prompt are traced arguments, not closure
    constants)."""
    import jax
    from jax import lax

    def one(params, cache, tok):
        """(cache, (B,) token) → (cache, (B, V) logits for the NEXT pos)."""
        logits, upd = dm.apply({"params": params, "cache": cache},
                               tok[:, None], train=False, mutable=["cache"])
        return upd["cache"], logits[:, -1]

    @jax.jit
    def run(params, cache, prompt, rng):
        # prefill: all but the last prompt token (their logits are unused)
        cache, _ = lax.scan(lambda c, t: (one(params, c, t)[0], None),
                            cache, prompt[:, :-1].T)

        def gen(carry, _):
            cache, tok, rng = carry
            cache, logits = one(params, cache, tok)
            rng, sub = jax.random.split(rng)
            if greedy:
                nxt = logits.argmax(-1)
            else:
                nxt = jax.random.categorical(
                    sub, logits / max(temperature, 1e-6))
            nxt = nxt.astype(tok.dtype)
            return (cache, nxt, rng), nxt

        (_, _, _), toks = lax.scan(gen, (cache, prompt[:, -1], rng),
                                   None, length=max_new_tokens)
        return toks.T  # (B, max_new_tokens)

    return run


# --------------------------------------------------------------------------
# Pipeline stages (engines/pipeline.py `stages=` plug-in): embed → S
# identical GPTBlock stages → final-LN + untied LM head.  The head is untied
# by construction — the pipeline stacks stage params over 'pipe', so the
# embedding (stage 0's params) is not addressable from the head stage;
# weight tying across pipeline stages would need a cross-stage ppermute of
# the embedding every step, which costs more than the untied head it saves.
# Dropout-free, like the BERT stages (models/bert.py:233-240): the schedule
# re-applies stages every tick, so rng-consuming ops would draw
# inconsistent masks.
# --------------------------------------------------------------------------


class GPTPipeEmbed(nn.Module):
    """Input stage: token (+ learned position) embeddings; under RoPE the
    position table disappears and rotation happens inside each block.

    ``seq_axis`` set (pp×sp): the stage sees a seq-SHARDED token block, so
    learned positions offset by block index × local length (global
    positions, same as GPTLM's seq-parallel path)."""

    vocab_size: int = 256
    hidden: int = 128
    max_len: int = 512
    partition_model: bool = False
    rope: bool = False
    seq_axis: str | None = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, token_ids):
        lq = token_ids.shape[1]
        sp = coll.axis_size(self.seq_axis) if self.seq_axis else 1
        if lq * sp > self.max_len:
            raise ValueError(
                f"sequence length {lq * sp} exceeds max_len={self.max_len}")
        x = nn.Embed(
            self.vocab_size, self.hidden, dtype=self.dtype,
            embedding_init=_part(nn.linear.default_embed_init,
                                 (meshlib.MODEL_AXIS, None),
                                 self.partition_model))(token_ids)
        if self.rope:
            return x
        offset = (coll.axis_index(self.seq_axis) * lq if self.seq_axis
                  else 0)
        pos = offset + jnp.arange(lq)[None, :]
        return x + nn.Embed(self.max_len, self.hidden,
                            dtype=self.dtype)(pos)


class GPTPipeBlock(nn.Module):
    """One pipeline stage: ``layers_per_stage`` pre-LN decoder blocks.

    Without a ``seq_axis``, pipeline microbatches carry FULL sequences
    (only the batch splits), so RoPE positions are simply arange(L).  With
    ``seq_axis`` set (pp×sp), the carry is a seq-sharded token block:
    attention must be a sequence-parallel impl ('ring'/'ring_flash'/
    'ulysses') and RoPE positions offset to global."""

    hidden: int = 128
    heads: int = 4
    ffn: int = 512
    layers_per_stage: int = 1
    partition_model: bool = False
    rope: bool = False
    kv_heads: int | None = None
    attention_impl: str = "dense"
    seq_axis: str | None = None
    dtype: jnp.dtype = jnp.float32
    moe_experts: int = 0         # >0: pp×ep — each stage block's FFN is a
                                 # routed MoE (models/moe.py); the engine
                                 # reads this field to wire the router
                                 # aux-loss plumbing (engines/pipeline.py)
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    partition_experts: bool = False

    @nn.compact
    def __call__(self, x):
        lq = x.shape[1]
        if self.seq_axis and self.attention_impl == "dense":
            raise ValueError(
                "seq_axis set but attention_impl is 'dense' — dense "
                "attention on a seq-sharded carry attends within local "
                "blocks only; use ring/ring_flash/ulysses")
        pos = None
        if self.rope:
            offset = (coll.axis_index(self.seq_axis) * lq if self.seq_axis
                      else 0)
            pos = offset + jnp.arange(lq)[None, :]
        for _ in range(self.layers_per_stage):
            x = GPTBlock(self.hidden, self.heads, self.ffn,
                         dropout_rate=0.0,
                         attention_impl=self.attention_impl,
                         seq_axis=self.seq_axis or "seq",
                         partition_model=self.partition_model,
                         rope=self.rope, kv_heads=self.kv_heads,
                         dtype=self.dtype,
                         moe_experts=self.moe_experts,
                         moe_top_k=self.moe_top_k,
                         moe_capacity_factor=self.moe_capacity_factor,
                         partition_experts=self.partition_experts)(x, pos=pos)
        return x


class GPTPipeHead(nn.Module):
    """Output stage: final LN → untied LM head (see module comment)."""

    vocab_size: int = 256
    hidden: int = 128
    partition_model: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=self.dtype)(x)
        logits = nn.Dense(
            self.vocab_size, dtype=self.dtype,
            kernel_init=_part(nn.initializers.lecun_normal(),
                              (None, meshlib.MODEL_AXIS),
                              self.partition_model))(x)
        return logits.astype(jnp.float32)


def gpt_pipeline_stages(
    vocab_size: int = 256,
    hidden: int = 128,
    heads: int = 4,
    ffn: int = 512,
    max_len: int = 512,
    layers_per_stage: int = 1,
    partition_model: bool = False,
    positional: str = "learned",
    kv_heads: int | None = None,
    attention_impl: str = "dense",
    seq_axis: str | None = None,
    dtype: jnp.dtype = jnp.float32,
    num_classes: int | None = None,  # alias for vocab_size (harness passes it)
    moe_experts: int = 0,
    moe_top_k: int = 1,
    moe_capacity_factor: float = 1.25,
    partition_experts: bool = False,
):
    """(embed, block, head) for ``PipelineEngine(stages=...)``: a GPT decoder
    of depth ``pipe_axis_size × layers_per_stage``.  ``partition_model=True``
    adds Megatron TP annotations for pp×tp; ``positional='rope'`` drops the
    position table and rotates q/k inside each block;
    ``attention_impl='ring'`` (etc.) + ``seq_axis='seq'`` makes the stages
    sequence-parallel for pp×sp (the carry rides the pipe ring as a
    seq-sharded token block).  ``moe_experts > 0`` +
    ``partition_experts=True`` swaps each block's FFN for a routed MoE
    sharded over an 'expert' mesh axis (pp×ep, engines/pipeline.py)."""
    if num_classes is not None:
        vocab_size = num_classes
    if positional not in ("learned", "rope"):
        raise ValueError(
            f"unknown positional '{positional}'; learned | rope")
    rope = positional == "rope"
    return (
        GPTPipeEmbed(vocab_size=vocab_size, hidden=hidden, max_len=max_len,
                     partition_model=partition_model, rope=rope,
                     seq_axis=seq_axis, dtype=dtype),
        GPTPipeBlock(hidden=hidden, heads=heads, ffn=ffn,
                     layers_per_stage=layers_per_stage,
                     partition_model=partition_model, rope=rope,
                     kv_heads=kv_heads, attention_impl=attention_impl,
                     seq_axis=seq_axis, dtype=dtype,
                     moe_experts=moe_experts, moe_top_k=moe_top_k,
                     moe_capacity_factor=moe_capacity_factor,
                     partition_experts=partition_experts),
        GPTPipeHead(vocab_size=vocab_size, hidden=hidden,
                    partition_model=partition_model, dtype=dtype),
    )
