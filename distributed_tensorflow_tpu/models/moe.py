"""Mixture-of-Experts classifier with Switch-style top-1 routing.

No reference counterpart (SURVEY.md §2.2: "EP (expert parallel): NO — no MoE
anywhere"); this is TPU-native new capability completing the parallelism
matrix (dp/tp/pp/sp/ep).

TPU-first design — the GShard/Switch dense-dispatch formulation, which is
what XLA partitions well:

* Expert FFN weights are *stacked* with a leading expert dimension and
  annotated ``with_partitioning`` on the ``expert`` mesh axis — each device
  on that axis holds ``E / ep`` experts.
* Routing is expressed as two einsums against a dispatch tensor
  ``[tokens, E, capacity]`` (build: top-1 gate → capacity-limited position
  via cumsum).  Static shapes throughout — capacity is computed at trace
  time — so everything jits; under GSPMD the dispatch einsum lowers to the
  all-to-all that moves token slots to their expert's device over ICI.
* Router math (softmax, load-balance stats) runs in f32 regardless of the
  model compute dtype (routing decisions are precision-sensitive).

The Switch load-balancing auxiliary loss is sown into the
``intermediates`` collection as ``aux_loss``; the expert-parallel engine
adds ``aux_weight ×`` it to the task loss.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.parallel import mesh as meshlib


class MoELayer(nn.Module):
    """Top-k (k ∈ {1, 2}) routed expert FFN over tokens (leading axis of x).

    ``router_top_k=1`` is Switch routing; ``2`` is GShard-style top-2 with
    renormalized gates and priority positions (top-1 assignments claim
    capacity slots before any top-2 assignment).  The layer sows, per
    call:
      * ``aux_loss``  — Switch load-balance loss (token fraction × mean
        router prob, over top-1 choices);
      * ``z_loss``    — router logit z-loss, mean(logsumexp(logits)²)
        (stabilizes router logits; weighted by the engine);
      * ``overflow``  — fraction of (token, choice) assignments dropped at
        the capacity limit.  A collapsed router shows up HERE, not as a
        mysterious accuracy loss: dropped tokens pass through the residual.

    ``partition_experts`` adds the ``with_partitioning('expert', ...)``
    annotations the expert-parallel engine reads; leave False on meshes
    without an 'expert' axis (plain DP) — the annotation names a mesh axis,
    so it must only be present when that axis exists.
    """

    num_experts: int = 8
    hidden: int = 256
    capacity_factor: float = 1.25
    router_top_k: int = 1
    partition_experts: bool = False
    partition_model: bool = False   # ep×tp: Megatron-split each expert's FFN
                                    # over the 'model' axis on top of the
                                    # expert sharding (GShard's 2-D expert
                                    # layout); requires partition_experts
    group_size: int | None = None   # GShard G×S grouped routing: tokens
                                    # route in independent groups of S with
                                    # per-group capacity k·cf·S/E.  The
                                    # dispatch/combine einsums cost
                                    # O(S·T·d) instead of O(T²·d) (E·C ∝ S,
                                    # not T) — the lever that keeps the
                                    # dense-dispatch formulation linear in
                                    # tokens at transformer scale.  None or
                                    # non-dividing = one group (exact
                                    # original semantics).
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        if self.router_top_k not in (1, 2):
            raise ValueError(
                f"router_top_k must be 1 or 2, got {self.router_top_k}")
        tokens, d = x.shape
        e = self.num_experts
        gs = self.group_size
        if gs is not None and 0 < gs < tokens and tokens % gs == 0:
            g, s = tokens // gs, gs
        else:
            g, s = 1, tokens
        xg = x.reshape(g, s, d)
        # capacity scales with k (GShard): top-2 makes 2·s assignments per
        # group, so unscaled slots would drop ≥37% even under perfectly
        # uniform routing and the overflow metric would read ~0.4 forever
        capacity = max(1, int(self.router_top_k * self.capacity_factor
                              * s / e + 0.999999))

        # --- router (f32) ------------------------------------------------
        gate_w = self.param("gate", nn.initializers.lecun_normal(), (d, e),
                            jnp.float32)
        logits = jnp.einsum("gsd,de->gse", xg.astype(jnp.float32), gate_w)
        probs = jax.nn.softmax(logits, axis=-1)
        top1 = jnp.argmax(probs, axis=-1)                       # [G, S]
        mask1 = jax.nn.one_hot(top1, e, dtype=jnp.float32)      # [G, S, E]

        # Switch aux loss: E · Σ_e (token fraction · mean router prob),
        # per group, averaged over groups (one group = original formula)
        aux = e * jnp.mean(jnp.sum(mask1.mean(axis=1) * probs.mean(axis=1),
                                   axis=-1))
        self.sow("intermediates", "aux_loss", aux)
        # router z-loss: keeps logits from drifting to magnitudes where
        # softmax saturates and routing gradients vanish
        self.sow("intermediates", "z_loss",
                 jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2))

        # --- capacity-limited dispatch/combine tensors -------------------
        if self.router_top_k == 1:
            gates = [probs]                  # top-1 gate = raw router prob
            masks = [mask1]
        else:
            # second choice: argmax with the first masked out; gates
            # renormalized over the chosen pair (GShard)
            probs2 = probs * (1.0 - mask1)
            mask2 = jax.nn.one_hot(jnp.argmax(probs2, axis=-1), e,
                                   dtype=jnp.float32)
            p1 = jnp.sum(probs * mask1, axis=-1, keepdims=True)
            p2 = jnp.sum(probs * mask2, axis=-1, keepdims=True)
            denom = jnp.maximum(p1 + p2, 1e-9)
            gates = [mask1 * (p1 / denom), mask2 * (p2 / denom)]
            masks = [mask1, mask2]

        dispatch = jnp.zeros((g, s, e, capacity), jnp.float32)
        combine = jnp.zeros((g, s, e, capacity), jnp.float32)
        offset = jnp.zeros((g, e), jnp.float32)  # slots claimed by earlier k
        assigned = kept = 0.0
        for mask, gate in zip(masks, gates):
            position = ((jnp.cumsum(mask, axis=1) - 1.0) * mask
                        + offset[:, None, :])
            keep = mask * (position < capacity)
            offset = offset + mask.sum(axis=1)
            pos_onehot = jax.nn.one_hot(position.astype(jnp.int32), capacity,
                                        dtype=jnp.float32)   # [G, S, E, C]
            dispatch = dispatch + keep[..., None] * pos_onehot
            combine = combine + keep[..., None] * pos_onehot * gate[..., None]
            assigned = assigned + mask.sum()
            kept = kept + keep.sum()

        self.sow("intermediates", "overflow",
                 1.0 - kept / jnp.maximum(assigned, 1.0))

        # --- expert FFN (stacked weights, expert axis sharded) -----------
        if self.partition_model and not self.partition_experts:
            raise ValueError(
                "partition_model on MoELayer means ep×tp (Megatron split "
                "inside each expert) and requires partition_experts=True")
        init1 = init2 = nn.initializers.lecun_normal()
        if self.partition_experts:
            # ep×tp: within each expert, w1 is column-parallel (hidden dim
            # sharded over 'model') and w2 row-parallel (contraction dim
            # sharded) — the [E/ep, C, hidden] activation stays model-sharded
            # between them and GSPMD emits one psum per expert FFN pair,
            # exactly the Megatron layout lifted over the stacked expert dim
            tp_axis = meshlib.MODEL_AXIS if self.partition_model else None
            init1 = nn.with_partitioning(
                nn.initializers.lecun_normal(),
                (meshlib.EXPERT_AXIS, None, tp_axis))
            init2 = nn.with_partitioning(
                nn.initializers.lecun_normal(),
                (meshlib.EXPERT_AXIS, tp_axis, None))
        w1 = self.param("w1", init1, (e, d, self.hidden), jnp.float32)
        w2 = self.param("w2", init2, (e, self.hidden, d), jnp.float32)

        expert_in = jnp.einsum("gsec,gsd->gecd", dispatch.astype(self.dtype),
                               xg.astype(self.dtype))
        h = jax.nn.relu(jnp.einsum("gecd,edh->gech", expert_in,
                                   w1.astype(self.dtype)))
        expert_out = jnp.einsum("gech,ehd->gecd", h, w2.astype(self.dtype))
        y = jnp.einsum("gsec,gecd->gsd", combine.astype(self.dtype),
                       expert_out)
        return y.reshape(tokens, d)


_MOE_GROUP_TARGET = 1024  # ~GShard group size: big enough that per-group
                          # capacity statistics are stable, small enough
                          # that the T×(E·C) dispatch einsums stay linear
                          # in total tokens


_MOE_GROUP_FLOOR = 256    # below this, per-group capacity k·cf·S/E gets so
                          # small that ordinary routing imbalance inside a
                          # group drops tokens wholesale — better one big
                          # group (quadratic dispatch) than quality loss


def _moe_group_size(tokens: int, target: int = _MOE_GROUP_TARGET):
    """Largest power-of-two divisor of ``tokens`` in [floor, target]
    (static, trace-time).  None — one group, exact original semantics —
    when tokens already fit in ≤target, or when the only dividing
    power-of-two would make groups smaller than the floor (e.g. 2000
    tokens divide by 16 but not 512: tiny groups drop tokens under any
    routing imbalance, so the quadratic one-group dispatch is the better
    trade)."""
    if tokens <= target:
        return None
    s = target
    while s >= _MOE_GROUP_FLOOR and tokens % s:
        s //= 2
    return s if s >= _MOE_GROUP_FLOOR else None


def moe_ffn(x, *, hidden: int, moe_experts: int, moe_top_k: int,
            moe_capacity_factor: float, partition_experts: bool,
            partition_model: bool, dtype) -> jnp.ndarray:
    """Routed-FFN swap for a transformer block: (B, L, D) tokens →
    (B, L, D) through a MoELayer over the flattened B·L tokens, routed in
    GShard groups of ≤ _MOE_GROUP_TARGET tokens (see MoELayer.group_size —
    keeps the dispatch einsums linear in B·L at transformer scale).

    The single definition of the transformer-block MoE dispatch, shared
    by GPTBlock (models/gpt.py) and TransformerLayer (models/bert.py) so
    the two families cannot diverge.  Must be called inside the caller's
    ``@nn.compact`` ``__call__`` — the MoELayer submodule is created in
    the caller's flax scope (auto-named ``MoELayer_i`` there).
    ``partition_model`` only takes effect together with
    ``partition_experts`` (the GShard 2-D layout needs the expert axis
    first)."""
    b, l, d = x.shape
    y = MoELayer(num_experts=moe_experts, hidden=hidden,
                 capacity_factor=moe_capacity_factor,
                 router_top_k=moe_top_k,
                 partition_experts=partition_experts,
                 partition_model=partition_model and partition_experts,
                 group_size=_moe_group_size(b * l),
                 dtype=dtype)(x.reshape(b * l, d))
    return y.reshape(b, l, d)


class MoEClassifier(nn.Module):
    """embed → (residual MoE layer) × depth → head, over flattened inputs.

    Plays the reference model_fn role (reference initializer.py:12-21) for
    the expert-parallel mode: same (images → logits) contract as the MLP,
    with the hidden FFN replaced by routed experts.
    """

    num_classes: int = 10
    num_experts: int = 8
    embed_dim: int = 128
    expert_hidden: int = 256
    depth: int = 1
    capacity_factor: float = 1.25
    router_top_k: int = 1
    dropout_rate: float = 0.1
    partition_experts: bool = False
    partition_model: bool = False   # ep×tp (see MoELayer)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.embed_dim, dtype=self.dtype)(x))
        for _ in range(self.depth):
            y = MoELayer(num_experts=self.num_experts,
                         hidden=self.expert_hidden,
                         capacity_factor=self.capacity_factor,
                         router_top_k=self.router_top_k,
                         partition_experts=self.partition_experts,
                         partition_model=self.partition_model,
                         dtype=self.dtype)(x)
            x = x + y  # residual: dropped (over-capacity) tokens pass through
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
