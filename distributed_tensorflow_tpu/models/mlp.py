"""MNIST MLP — parity with the reference's default model_fn.

Reference architecture (reference initializer.py:14-19):
Flatten(28,28,1) → Dense(512, relu) → Dropout(0.2) → Dense(10).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    num_classes: int = 10
    hidden: int = 512
    dropout_rate: float = 0.2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
