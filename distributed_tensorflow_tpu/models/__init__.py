"""L3 model plug-in point.

The reference's contract is a user-editable ``model_fn() -> tf.keras.Model``
(reference initializer.py:12-21, README.md:12).  Here ``model_fn`` returns a
``flax.linen.Module`` whose ``__call__(x, train: bool)`` produces logits; the
registry gives named access for the CLI, and users can still pass their own
callable exactly like the reference.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.mlp import MLP
from distributed_tensorflow_tpu.models.cnn import CNN

_REGISTRY: dict[str, Callable[..., nn.Module]] = {}

_DTYPES = {
    "float32": jnp.float32, "f32": jnp.float32, "fp32": jnp.float32,
    "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
    "float16": jnp.float16, "f16": jnp.float16, "fp16": jnp.float16,
}


def resolve_dtype(dtype) -> jnp.dtype:
    """Map a CLI string ('bfloat16', 'bf16', ...) or dtype to a jnp dtype.

    Mixed precision on TPU: models compute in ``dtype`` (bf16 feeds the MXU
    at full rate and halves HBM traffic for activations) while flax keeps
    parameters in float32 (``param_dtype`` default), so optimizer math and
    gradient accumulation stay full-precision.
    """
    if isinstance(dtype, str):
        key = dtype.lower()
        if key not in _DTYPES:
            raise KeyError(f"unknown dtype '{dtype}'; known: {sorted(_DTYPES)}")
        return _DTYPES[key]
    return dtype


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


@register("mlp")
@register("mnist_mlp")
def _mlp(num_classes: int = 10, **kw) -> nn.Module:
    """The reference's default model_fn: Flatten→Dense(512,relu)→Dropout(0.2)
    →Dense(10) (reference initializer.py:14-19)."""
    return MLP(num_classes=num_classes, **kw)


@register("cnn")
@register("mnist_cnn")
def _cnn(num_classes: int = 10, **kw) -> nn.Module:
    return CNN(num_classes=num_classes, **kw)


@register("fashion_mlp")
def _fashion_mlp(num_classes: int = 10, **kw) -> nn.Module:
    return MLP(num_classes=num_classes, **kw)


def create_model(name: str, num_classes: int = 10, **kw) -> nn.Module:
    """Instantiate a registered model (lazy imports keep startup light)."""
    if "dtype" in kw:
        kw["dtype"] = resolve_dtype(kw["dtype"])
    if name in ("resnet20", "resnet"):
        from distributed_tensorflow_tpu.models.resnet import ResNet20

        return ResNet20(num_classes=num_classes, **kw)
    if name in ("bert_tiny", "bert"):
        from distributed_tensorflow_tpu.models.bert import BertTinyClassifier

        return BertTinyClassifier(num_classes=num_classes, **kw)
    if name in ("moe", "moe_mlp"):
        from distributed_tensorflow_tpu.models.moe import MoEClassifier

        return MoEClassifier(num_classes=num_classes, **kw)
    if name in ("gpt", "gpt_tiny"):
        from distributed_tensorflow_tpu.models.gpt import GPTLM

        # an LM's "classes" are its tokens: the harness threads the
        # dataset's num_classes (= vocab size for data/loaders.py lm_synth)
        # through the same parameter every classifier uses
        kw.setdefault("vocab_size", num_classes)
        return GPTLM(**kw)
    if name not in _REGISTRY:
        raise KeyError(f"unknown model '{name}'; known: {sorted(_REGISTRY)} "
                       f"+ resnet20, bert_tiny, moe, gpt")
    return _REGISTRY[name](num_classes=num_classes, **kw)


def get_model_fn(name: str, num_classes: int = 10, **kw) -> Callable[[], nn.Module]:
    """Reference-style zero-arg model_fn (reference initializer.py:12)."""
    return lambda: create_model(name, num_classes=num_classes, **kw)
