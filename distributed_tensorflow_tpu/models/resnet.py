"""ResNet-20 for CIFAR-10 — the reference's ghost second workload.

The reference never committed its CIFAR-10 experiments (reference
.gitignore:1-4 lists `cifar10.py`, `cifar10_train.py`), but BASELINE.json
names "CIFAR-10 ResNet-20, -m centralized -cs async" as a benchmark config.
Classic He et al. CIFAR variant: 3 stages × 3 basic blocks, widths 16/32/64.

TPU notes: BatchNorm is replaced by GroupNorm so the step function stays a
pure params→params map with no mutable batch-stats collection — no
cross-device batch-stat sync needed (the usual BN-under-DP footgun), and the
engines' single-pytree TrainState stays uniform across models.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = nn.GroupNorm(num_groups=8, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.GroupNorm(num_groups=8, dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False, dtype=self.dtype)(residual)
            residual = nn.GroupNorm(num_groups=8, dtype=self.dtype)(residual)
        return nn.relu(y + residual)


class ResNet20(nn.Module):
    num_classes: int = 10
    widths: tuple[int, ...] = (16, 32, 64)
    blocks_per_stage: int = 3
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if x.ndim == 3:
            x = x[..., None]
        x = nn.Conv(self.widths[0], (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=8, dtype=self.dtype)(x)
        x = nn.relu(x)
        for stage, width in enumerate(self.widths):
            for block in range(self.blocks_per_stage):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BasicBlock(width, strides, dtype=self.dtype)(x)
        x = x.mean(axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
