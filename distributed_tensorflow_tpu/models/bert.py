"""BERT-tiny sequence classifier — BASELINE.json's stretch config.

The reference has no attention or sequence models anywhere (SURVEY.md §2.2:
its only model is an MLP on 28×28, reference initializer.py:14-19);
BASELINE.json adds "BERT-tiny GLUE fine-tune" as a stretch benchmark.
Standard BERT-tiny shape: 2 layers, hidden 128, 2 heads, FFN 512.

Attention is pluggable (``attention_impl``):
  'dense'   — ordinary full attention; any mesh, no seq sharding.
  'flash'   — Pallas flash-attention kernel (ops.flash_attention): exact
              same math as 'dense' but blockwise in VMEM — O(L) memory,
              the TPU-native choice for long single-device sequences.
  'ring'    — ring attention over the ``seq`` mesh axis; the model must run
              inside `jax.shard_map` with the token dim sharded over 'seq'
              (see engines.seq_parallel).  K/V blocks rotate via ppermute.
  'ulysses' — all-to-all head-parallel attention over 'seq'; same contract,
              plus num_heads % seq_axis_size == 0.

Input is int32 token ids (B, L_local); 0 is the padding id and is masked out
of attention.  The classification head reads the [CLS] position (global
index 0); under sequence parallelism only seq-device 0 holds it, so the head
uses a broadcast from that device.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from distributed_tensorflow_tpu.parallel import collectives as coll
from distributed_tensorflow_tpu.parallel.ring_attention import (
    dense_attention, ring_attention, ulysses_attention)


class SelfAttention(nn.Module):
    hidden: int = 128
    heads: int = 2
    attention_impl: str = "dense"
    seq_axis: str = "seq"
    dropout_rate: float = 0.0   # attention-probability dropout (dense only:
                                # blockwise ring/ulysses skip it, as flash-
                                # style attention implementations do)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, pad_mask, train: bool = False):
        head_dim = self.hidden // self.heads
        proj = lambda name: nn.DenseGeneral(  # noqa: E731
            features=(self.heads, head_dim), dtype=self.dtype, name=name)
        q, k, v = proj("query")(x), proj("key")(x), proj("value")(x)
        if self.attention_impl == "ring":
            out = ring_attention(q, k, v, axis=self.seq_axis, kv_mask=pad_mask)
        elif self.attention_impl == "ulysses":
            out = ulysses_attention(q, k, v, axis=self.seq_axis, kv_mask=pad_mask)
        elif self.attention_impl == "flash":
            from distributed_tensorflow_tpu.ops import flash_attention
            out = flash_attention(q, k, v, kv_mask=pad_mask)
        else:
            prob_fn = None
            if self.dropout_rate > 0.0:
                drop = nn.Dropout(self.dropout_rate, deterministic=not train)
                prob_fn = lambda p: drop(p)  # noqa: E731
            out = dense_attention(q, k, v, kv_mask=pad_mask, prob_fn=prob_fn)
        return nn.DenseGeneral(features=self.hidden, axis=(-2, -1),
                               dtype=self.dtype, name="out")(out)


class TransformerLayer(nn.Module):
    hidden: int = 128
    heads: int = 2
    ffn: int = 512
    dropout_rate: float = 0.1
    attention_impl: str = "dense"
    seq_axis: str = "seq"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, pad_mask, train: bool = False):
        y = SelfAttention(self.hidden, self.heads, self.attention_impl,
                          self.seq_axis, self.dropout_rate,
                          self.dtype)(x, pad_mask, train)
        x = nn.LayerNorm(dtype=self.dtype)(x + y)
        y = nn.Dense(self.ffn, dtype=self.dtype)(x)
        y = nn.gelu(y)
        y = nn.Dense(self.hidden, dtype=self.dtype)(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return nn.LayerNorm(dtype=self.dtype)(x + y)


class BertTinyClassifier(nn.Module):
    num_classes: int = 2
    vocab_size: int = 8192
    hidden: int = 128
    layers: int = 2
    heads: int = 2
    ffn: int = 512
    max_len: int = 512
    dropout_rate: float = 0.1
    attention_impl: str = "dense"
    seq_axis: str = "seq"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, token_ids, train: bool = False):
        seq_parallel = self.attention_impl in ("ring", "ulysses")
        pad_mask = (token_ids > 0).astype(self.dtype)
        lq = token_ids.shape[1]
        # nn.Embed clamps out-of-range gathers silently — fail loudly instead
        global_len = lq * (coll.axis_size(self.seq_axis) if seq_parallel else 1)
        if global_len > self.max_len:
            raise ValueError(
                f"sequence length {global_len} exceeds max_len={self.max_len}; "
                f"raise max_len or shorten the input")
        if seq_parallel:
            # local block's global positions: block index × local length
            offset = coll.axis_index(self.seq_axis) * lq
            pos = offset + jnp.arange(lq)[None, :]
        else:
            pos = jnp.arange(lq)[None, :]
        x = nn.Embed(self.vocab_size, self.hidden, dtype=self.dtype)(token_ids)
        x = x + nn.Embed(self.max_len, self.hidden, dtype=self.dtype)(pos)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        for _ in range(self.layers):
            x = TransformerLayer(self.hidden, self.heads, self.ffn,
                                 self.dropout_rate, self.attention_impl,
                                 self.seq_axis, self.dtype)(x, pad_mask, train)
        cls = x[:, 0]  # [CLS]: global position 0
        if seq_parallel:
            # only seq-device 0 holds the real [CLS]; replicate it so the
            # head computes identically on every seq device
            cls = coll.broadcast_from(cls, self.seq_axis, src=0)
        cls = nn.tanh(nn.Dense(self.hidden, dtype=self.dtype)(cls))
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(cls)
        return logits.astype(jnp.float32)
