"""BERT-tiny sequence classifier — BASELINE.json's stretch config.

The reference has no attention or sequence models anywhere (SURVEY.md §2.2:
its only model is an MLP on 28×28, reference initializer.py:14-19);
BASELINE.json adds "BERT-tiny GLUE fine-tune" as a stretch benchmark.
Standard BERT-tiny shape: 2 layers, hidden 128, 2 heads, FFN 512.

Input is int32 token ids (B, L); 0 is the padding id and is masked out of
attention.  Classification head reads the [CLS] position (index 0).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class TransformerLayer(nn.Module):
    hidden: int = 128
    heads: int = 2
    ffn: int = 512
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, pad_mask, train: bool = False):
        attn_mask = nn.make_attention_mask(pad_mask, pad_mask)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.heads, dtype=self.dtype,
            dropout_rate=self.dropout_rate, deterministic=not train,
        )(x, x, mask=attn_mask)
        x = nn.LayerNorm(dtype=self.dtype)(x + y)
        y = nn.Dense(self.ffn, dtype=self.dtype)(x)
        y = nn.gelu(y)
        y = nn.Dense(self.hidden, dtype=self.dtype)(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return nn.LayerNorm(dtype=self.dtype)(x + y)


class BertTinyClassifier(nn.Module):
    num_classes: int = 2
    vocab_size: int = 8192
    hidden: int = 128
    layers: int = 2
    heads: int = 2
    ffn: int = 512
    max_len: int = 512
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, token_ids, train: bool = False):
        pad_mask = (token_ids > 0).astype(self.dtype)
        pos = jnp.arange(token_ids.shape[1])[None, :]
        x = nn.Embed(self.vocab_size, self.hidden, dtype=self.dtype)(token_ids)
        x = x + nn.Embed(self.max_len, self.hidden, dtype=self.dtype)(pos)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        for _ in range(self.layers):
            x = TransformerLayer(self.hidden, self.heads, self.ffn,
                                 self.dropout_rate, self.dtype)(x, pad_mask, train)
        cls = x[:, 0]  # [CLS] position
        cls = nn.tanh(nn.Dense(self.hidden, dtype=self.dtype)(cls))
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(cls)
        return logits.astype(jnp.float32)
