"""BERT-tiny sequence classifier — BASELINE.json's stretch config.

The reference has no attention or sequence models anywhere (SURVEY.md §2.2:
its only model is an MLP on 28×28, reference initializer.py:14-19);
BASELINE.json adds "BERT-tiny GLUE fine-tune" as a stretch benchmark.
Standard BERT-tiny shape: 2 layers, hidden 128, 2 heads, FFN 512.

Attention is pluggable (``attention_impl``):
  'dense'      — ordinary full attention; any mesh, no seq sharding.
  'flash'      — Pallas flash-attention kernel (ops.flash_attention): exact
                 same math as 'dense' but blockwise in VMEM — O(L) memory,
                 the TPU-native choice for long single-device sequences.
  'ring'       — ring attention over the ``seq`` mesh axis; the model must
                 run inside `jax.shard_map` with the token dim sharded over
                 'seq' (see engines.seq_parallel).  K/V rotate via ppermute.
  'ring_flash' — ring schedule with the Pallas flash kernel as the local
                 block math (parallel.ring_attention.ring_flash_attention):
                 long-context memory scaling AND the kernel's on-chip wins
                 (BASELINE.md §attention).  Same contract as 'ring'.
  'ulysses'    — all-to-all head-parallel attention over 'seq'; same
                 contract, plus num_heads % seq_axis_size == 0.
  'ulysses_flash' — Ulysses reshard with the Pallas flash kernel as the
                 local math (each device holds the FULL sequence for H/n
                 heads after the all-to-all — exactly the single-device
                 flash case).  Same contract as 'ulysses'.

Input is int32 token ids (B, L_local); 0 is the padding id and is masked out
of attention.  The classification head reads the [CLS] position (global
index 0); under sequence parallelism only seq-device 0 holds it, so the head
uses a broadcast from that device.

``partition_model=True`` adds Megatron-style ``with_partitioning``
annotations over the ``model`` mesh axis for GSPMD tensor parallelism
(engines/tensor_parallel.py): QKV projections column-parallel (heads
sharded), attention output row-parallel, FFN split column→row, token
embedding vocab-sharded.  The activation between each col/row pair stays
model-sharded and XLA emits exactly one all-reduce per pair — no reference
counterpart (the reference replicates whole models, reference client.py:72).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from distributed_tensorflow_tpu.parallel import collectives as coll
from distributed_tensorflow_tpu.parallel import mesh as meshlib
from distributed_tensorflow_tpu.parallel.ring_attention import (
    dense_attention, ring_attention, ring_flash_attention,
    ulysses_attention, ulysses_flash_attention)


def _part(init, spec, enabled: bool):
    """Megatron annotation, applied only when the model is TP-partitioned
    (unannotated modules keep plain initializers so every non-GSPMD engine
    sees ordinary unboxed params)."""
    return nn.with_partitioning(init, spec) if enabled else init


class SelfAttention(nn.Module):
    hidden: int = 128
    heads: int = 2
    attention_impl: str = "dense"
    seq_axis: str = "seq"
    dropout_rate: float = 0.0   # attention-probability dropout (dense only:
                                # blockwise ring/ulysses skip it, as flash-
                                # style attention implementations do)
    partition_model: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, pad_mask, train: bool = False):
        head_dim = self.hidden // self.heads
        tp = self.partition_model
        # column-parallel QKV: kernel (hidden, heads*head_dim) with the packed
        # output dim sharded — when tp divides heads, the head reshape leaves
        # each model-device a contiguous slice of heads; otherwise GSPMD
        # reshards around the reshape (correct, but cross-head tp stops
        # paying off).  Plain Dense, not DenseGeneral: flax re-traces
        # DenseGeneral's boxed pre-reshape kernel at apply time, which breaks
        # under partial-manual shard_map meshes.
        def proj(name):
            h = nn.Dense(
                self.heads * head_dim, dtype=self.dtype, name=name,
                kernel_init=_part(nn.initializers.lecun_normal(),
                                  (None, meshlib.MODEL_AXIS), tp),
                bias_init=_part(nn.initializers.zeros_init(),
                                (meshlib.MODEL_AXIS,), tp))(x)
            return h.reshape(h.shape[:-1] + (self.heads, head_dim))

        q, k, v = proj("query"), proj("key"), proj("value")
        if self.attention_impl == "ring":
            out = ring_attention(q, k, v, axis=self.seq_axis, kv_mask=pad_mask)
        elif self.attention_impl == "ring_flash":
            out = ring_flash_attention(q, k, v, axis=self.seq_axis,
                                       kv_mask=pad_mask)
        elif self.attention_impl == "ulysses":
            out = ulysses_attention(q, k, v, axis=self.seq_axis, kv_mask=pad_mask)
        elif self.attention_impl == "ulysses_flash":
            out = ulysses_flash_attention(q, k, v, axis=self.seq_axis,
                                          kv_mask=pad_mask)
        elif self.attention_impl == "flash":
            from distributed_tensorflow_tpu.ops import flash_attention
            out = flash_attention(q, k, v, kv_mask=pad_mask)
        else:
            prob_fn = None
            if self.dropout_rate > 0.0:
                drop = nn.Dropout(self.dropout_rate, deterministic=not train)
                prob_fn = lambda p: drop(p)  # noqa: E731
            out = dense_attention(q, k, v, kv_mask=pad_mask, prob_fn=prob_fn)
        # row-parallel output: contraction over the packed (sharded) head dim
        # — XLA inserts the single all-reduce of the pair here
        out = out.reshape(out.shape[:-2] + (self.heads * head_dim,))
        return nn.Dense(
            self.hidden, dtype=self.dtype, name="out",
            kernel_init=_part(nn.initializers.lecun_normal(),
                              (meshlib.MODEL_AXIS, None), tp))(out)


class TransformerLayer(nn.Module):
    """Post-LN encoder layer.  ``moe_experts > 0`` swaps the dense FFN for
    a routed MoE layer over the layer's tokens (models/moe.py MoELayer,
    same contract as models/gpt.py GPTBlock: router diagnostics sow into
    ``intermediates``; under seq parallelism each seq device routes its
    own token block to the 'expert'-sharded experts)."""

    hidden: int = 128
    heads: int = 2
    ffn: int = 512
    dropout_rate: float = 0.1
    attention_impl: str = "dense"
    seq_axis: str = "seq"
    partition_model: bool = False
    dtype: jnp.dtype = jnp.float32
    moe_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    partition_experts: bool = False

    @nn.compact
    def __call__(self, x, pad_mask, train: bool = False):
        tp = self.partition_model
        y = SelfAttention(self.hidden, self.heads, self.attention_impl,
                          self.seq_axis, self.dropout_rate, tp,
                          self.dtype)(x, pad_mask, train)
        x = nn.LayerNorm(dtype=self.dtype)(x + y)
        if self.moe_experts > 0:
            from distributed_tensorflow_tpu.models.moe import moe_ffn

            y = moe_ffn(x, hidden=self.ffn, moe_experts=self.moe_experts,
                        moe_top_k=self.moe_top_k,
                        moe_capacity_factor=self.moe_capacity_factor,
                        partition_experts=self.partition_experts,
                        partition_model=tp, dtype=self.dtype)
        else:
            # Megatron FFN: column-parallel expand, row-parallel contract —
            # the (B, L, ffn) activation never leaves its model shard
            y = nn.Dense(
                self.ffn, dtype=self.dtype,
                kernel_init=_part(nn.initializers.lecun_normal(),
                                  (None, meshlib.MODEL_AXIS), tp),
                bias_init=_part(nn.initializers.zeros_init(),
                                (meshlib.MODEL_AXIS,), tp))(x)
            y = nn.gelu(y)
            y = nn.Dense(
                self.hidden, dtype=self.dtype,
                kernel_init=_part(nn.initializers.lecun_normal(),
                                  (meshlib.MODEL_AXIS, None), tp))(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return nn.LayerNorm(dtype=self.dtype)(x + y)


class BertEmbeddings(nn.Module):
    """Token + position embeddings → LayerNorm.  Shared by the monolithic
    classifier and the pipeline embed stage; callers supply the position ids
    (seq-parallel blocks pass offset positions) and own the max_len check."""

    vocab_size: int = 8192
    hidden: int = 128
    max_len: int = 512
    partition_model: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, token_ids, pos):
        # vocab-sharded token embedding (Megatron): the vocab dim is the one
        # that grows; GSPMD renders the sharded gather as masked-lookup+psum
        x = nn.Embed(
            self.vocab_size, self.hidden, dtype=self.dtype,
            embedding_init=_part(nn.linear.default_embed_init,
                                 (meshlib.MODEL_AXIS, None),
                                 self.partition_model))(token_ids)
        x = x + nn.Embed(self.max_len, self.hidden, dtype=self.dtype)(pos)
        return nn.LayerNorm(dtype=self.dtype)(x)


class BertPooler(nn.Module):
    """[CLS] readout: tanh pooler → classifier logits (f32 for the softmax).
    Shared by the monolithic classifier and the pipeline head."""

    num_classes: int = 2
    hidden: int = 128
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, cls):
        cls = nn.tanh(nn.Dense(self.hidden, dtype=self.dtype)(cls))
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(cls)
        return logits.astype(jnp.float32)


class BertTinyClassifier(nn.Module):
    num_classes: int = 2
    vocab_size: int = 8192
    hidden: int = 128
    layers: int = 2
    heads: int = 2
    ffn: int = 512
    max_len: int = 512
    dropout_rate: float = 0.1
    attention_impl: str = "dense"
    seq_axis: str = "seq"
    partition_model: bool = False
    remat: bool = False          # activation checkpointing per encoder
                                 # layer (see models/gpt.py GPTLM.remat)
    moe_experts: int = 0         # >0: every layer's FFN is a routed MoE
                                 # (models/moe.py; see GPTLM.moe_experts)
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    partition_experts: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, token_ids, train: bool = False):
        seq_parallel = self.attention_impl in ("ring", "ring_flash",
                                               "ulysses", "ulysses_flash")
        pad_mask = (token_ids > 0).astype(self.dtype)
        lq = token_ids.shape[1]
        # nn.Embed clamps out-of-range gathers silently — fail loudly instead
        global_len = lq * (coll.axis_size(self.seq_axis) if seq_parallel else 1)
        if global_len > self.max_len:
            raise ValueError(
                f"sequence length {global_len} exceeds max_len={self.max_len}; "
                f"raise max_len or shorten the input")
        if seq_parallel:
            # local block's global positions: block index × local length
            offset = coll.axis_index(self.seq_axis) * lq
            pos = offset + jnp.arange(lq)[None, :]
        else:
            pos = jnp.arange(lq)[None, :]
        x = BertEmbeddings(self.vocab_size, self.hidden, self.max_len,
                           self.partition_model, self.dtype)(token_ids, pos)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        # remat: train (arg 3) is a static python bool; x/pad_mask trace.
        # Explicit name pins the module path to the unwrapped auto-name —
        # nn.remat renames the class, and flax derives param paths + init
        # RNG from the path, so without the pin remat=True would draw
        # different params under different tree paths (see models/gpt.py).
        if self.remat and self.moe_experts:
            raise ValueError(
                "remat + MoE layers is unsupported: the router's sown "
                "intermediates would be re-sown during backward recompute "
                "(see models/gpt.py)")
        layer_cls = (nn.remat(TransformerLayer, static_argnums=(3,))
                     if self.remat else TransformerLayer)
        for i in range(self.layers):
            x = layer_cls(self.hidden, self.heads, self.ffn,
                          self.dropout_rate, self.attention_impl,
                          self.seq_axis, self.partition_model,
                          self.dtype, self.moe_experts, self.moe_top_k,
                          self.moe_capacity_factor, self.partition_experts,
                          name=f"TransformerLayer_{i}")(x, pad_mask, train)
        cls = x[:, 0]  # [CLS]: global position 0
        if seq_parallel:
            # only seq-device 0 holds the real [CLS]; replicate it so the
            # head computes identically on every seq device
            cls = coll.broadcast_from(cls, self.seq_axis, src=0)
        return BertPooler(self.num_classes, self.hidden, self.dtype)(cls)


# --------------------------------------------------------------------------
# GPipe stage modules (engines/pipeline.py `stages=` plug-in): the encoder
# splits into embed → S identical TransformerLayer stages → [CLS] head.  The
# pipeline carry is (hidden_states, pad_mask) — the mask must travel with the
# activations because later stages never see the token ids.  Deterministic by
# construction (no dropout): the GPipe schedule re-applies embed/head every
# tick, so rng-consuming ops would draw inconsistent masks across ticks.
# --------------------------------------------------------------------------


class BertPipeEmbed(nn.Module):
    """Input stage: token + position embeddings → (hidden, pad_mask) carry."""

    vocab_size: int = 8192
    hidden: int = 128
    max_len: int = 512
    partition_model: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, token_ids):
        pad_mask = (token_ids > 0).astype(self.dtype)
        if token_ids.shape[1] > self.max_len:
            raise ValueError(
                f"sequence length {token_ids.shape[1]} exceeds "
                f"max_len={self.max_len}")
        pos = jnp.arange(token_ids.shape[1])[None, :]
        x = BertEmbeddings(self.vocab_size, self.hidden, self.max_len,
                           self.partition_model, dtype=self.dtype)(
                               token_ids, pos)
        return x, pad_mask


class BertPipeBlock(nn.Module):
    """One pipeline stage: ``layers_per_stage`` transformer layers
    (hidden-preserving, so stages stack and shard P('pipe')).

    ``partition_model=True`` adds the Megatron annotations for pp×tp: the
    stacked stage params then shard ('pipe', …Megatron spec…) and GSPMD
    owns the in-stage model-axis collectives (engines/pipeline.py)."""

    hidden: int = 128
    heads: int = 2
    ffn: int = 512
    layers_per_stage: int = 1
    partition_model: bool = False
    dtype: jnp.dtype = jnp.float32
    moe_experts: int = 0         # >0: pp×ep — routed MoE FFN per layer
                                 # (models/moe.py; engines/pipeline.py reads
                                 # this field for the aux-loss plumbing)
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    partition_experts: bool = False

    @nn.compact
    def __call__(self, carry):
        x, pad_mask = carry
        for _ in range(self.layers_per_stage):
            x = TransformerLayer(self.hidden, self.heads, self.ffn,
                                 dropout_rate=0.0, attention_impl="dense",
                                 partition_model=self.partition_model,
                                 dtype=self.dtype,
                                 moe_experts=self.moe_experts,
                                 moe_top_k=self.moe_top_k,
                                 moe_capacity_factor=self.moe_capacity_factor,
                                 partition_experts=self.partition_experts)(
                                     x, pad_mask)
        return x, pad_mask


class BertPipeHead(nn.Module):
    """Output stage: the shared [CLS] pooler over the carry's activations."""

    num_classes: int = 2
    hidden: int = 128
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, carry):
        x, _ = carry
        return BertPooler(self.num_classes, self.hidden, self.dtype)(x[:, 0])


def bert_pipeline_stages(
    num_classes: int = 2,
    vocab_size: int = 8192,
    hidden: int = 128,
    heads: int = 2,
    ffn: int = 512,
    max_len: int = 512,
    layers_per_stage: int = 1,
    partition_model: bool = False,
    dtype: jnp.dtype = jnp.float32,
    moe_experts: int = 0,
    moe_top_k: int = 1,
    moe_capacity_factor: float = 1.25,
    partition_experts: bool = False,
):
    """(embed, block, head) for ``PipelineEngine(stages=...)``: a BERT
    encoder of depth ``pipe_axis_size × layers_per_stage``.
    ``partition_model=True`` adds Megatron TP annotations for pp×tp;
    ``moe_experts > 0`` + ``partition_experts=True`` makes each layer's FFN
    a routed MoE sharded over an 'expert' mesh axis (pp×ep,
    engines/pipeline.py)."""
    return (
        BertPipeEmbed(vocab_size=vocab_size, hidden=hidden, max_len=max_len,
                      partition_model=partition_model, dtype=dtype),
        BertPipeBlock(hidden=hidden, heads=heads, ffn=ffn,
                      layers_per_stage=layers_per_stage,
                      partition_model=partition_model, dtype=dtype,
                      moe_experts=moe_experts, moe_top_k=moe_top_k,
                      moe_capacity_factor=moe_capacity_factor,
                      partition_experts=partition_experts),
        BertPipeHead(num_classes=num_classes, hidden=hidden, dtype=dtype),
    )
