// Native framed-socket transport (control plane).
//
// C++ rendering of the reference's wire layer
// (/root/reference/centralized/network.py:4-28): every message is a 4-byte
// big-endian length prefix followed by the payload, written/read with
// blocking exactly-n semantics.  In the TPU framework tensors never travel
// over sockets (XLA collectives own the data plane); this transport carries
// the supervisor/benchmark channel and any reference-protocol peer, so it
// stays byte-compatible with the reference's framing.
//
// Exported as a C ABI for ctypes.  All functions return negative values on
// error; recv returns 0 payload length only for genuine zero-length frames
// and DTW_CLOSED (-1) on orderly peer close, mirroring the Python recvall
// contract (reference network.py:20-28 returns None on EOF).

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr int64_t DTW_CLOSED = -1;
constexpr int64_t DTW_ERROR = -2;
constexpr int64_t DTW_TOOBIG = -3;

// Blocking write of exactly n bytes (EINTR-safe).
int send_all(int fd, const uint8_t* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t w = ::send(fd, buf + off, n - off, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    off += static_cast<size_t>(w);
  }
  return 0;
}

// Blocking read of exactly n bytes; 0 on success, DTW_CLOSED on EOF.
int64_t recv_all(int fd, uint8_t* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t r = ::recv(fd, buf + off, n - off, 0);
    if (r == 0) return DTW_CLOSED;
    if (r < 0) {
      if (errno == EINTR) continue;
      return DTW_ERROR;
    }
    off += static_cast<size_t>(r);
  }
  return 0;
}

}  // namespace

extern "C" {

// Send one frame: 4-byte big-endian length + payload.
int64_t dtw_send_frame(int fd, const uint8_t* payload, uint32_t len) {
  uint32_t be = htonl(len);
  uint8_t header[4];
  std::memcpy(header, &be, 4);
  if (send_all(fd, header, 4) != 0) return DTW_ERROR;
  if (len > 0 && send_all(fd, payload, len) != 0) return DTW_ERROR;
  return 0;
}

// Receive one frame into out (capacity cap).  Returns payload length,
// DTW_CLOSED on orderly close before/within the header, DTW_TOOBIG when the
// frame exceeds cap (frame is consumed and discarded to keep the stream in
// sync), DTW_ERROR otherwise.
int64_t dtw_recv_frame(int fd, uint8_t* out, uint32_t cap) {
  uint8_t header[4];
  int64_t rc = recv_all(fd, header, 4);
  if (rc != 0) return rc;
  uint32_t be;
  std::memcpy(&be, header, 4);
  uint32_t len = ntohl(be);
  if (len > cap) {
    uint8_t sink[4096];
    uint32_t left = len;
    while (left > 0) {
      uint32_t take = left < sizeof(sink) ? left : sizeof(sink);
      rc = recv_all(fd, sink, take);
      if (rc != 0) return rc;
      left -= take;
    }
    return DTW_TOOBIG;
  }
  if (len > 0) {
    rc = recv_all(fd, out, len);
    if (rc != 0) return rc;
  }
  return static_cast<int64_t>(len);
}

// Consume the next frame's 4-byte header and return the payload length
// (for exact-size allocation before dtw_recv_body).  recv_all loops over
// partial reads and retries EINTR, so a signal or a header straddling TCP
// segments can't be misread; a peer closing before a complete header is an
// orderly close (DTW_CLOSED), matching recvall's None contract (reference
// network.py:20-28).
int64_t dtw_recv_header(int fd) {
  uint8_t header[4];
  int64_t rc = recv_all(fd, header, 4);
  if (rc != 0) return rc;
  uint32_t be;
  std::memcpy(&be, header, 4);
  return static_cast<int64_t>(ntohl(be));
}

// Read exactly len payload bytes following dtw_recv_header.  0 on success.
int64_t dtw_recv_body(int fd, uint8_t* out, uint32_t len) {
  return recv_all(fd, out, len);
}

// Connect to host:port (numeric or resolvable).  Returns fd or DTW_ERROR.
int64_t dtw_connect(const char* host, int port) {
  char portstr[16];
  std::snprintf(portstr, sizeof(portstr), "%d", port);
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(host, portstr, &hints, &res) != 0) return DTW_ERROR;
  int fd = -1;
  for (addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return DTW_ERROR;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

// Listen on port (0 = ephemeral).  Returns listening fd or DTW_ERROR.
int64_t dtw_listen(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return DTW_ERROR;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 64) != 0) {
    ::close(fd);
    return DTW_ERROR;
  }
  return fd;
}

// Bound port of a listening fd (for port=0 ephemeral binds).
int64_t dtw_port(int fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len) != 0)
    return DTW_ERROR;
  return ntohs(addr.sin_port);
}

// Accept one connection.  Returns connected fd or DTW_ERROR.
int64_t dtw_accept(int listen_fd) {
  for (;;) {
    int fd = ::accept(listen_fd, nullptr, nullptr);
    if (fd >= 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    if (errno != EINTR) return DTW_ERROR;
  }
}

int64_t dtw_close(int fd) { return ::close(fd) == 0 ? 0 : DTW_ERROR; }

}  // extern "C"
