// ThreadSanitizer driver for the native pipeline's concurrency.
//
// The reference's only concurrency-safety mechanism is one lock + one
// barrier with no race detection of any kind (SURVEY.md §5 "Race
// detection/sanitizers: NO").  Here the multithreaded runtime component is
// pipeline.cc (producer thread + gather worker pool + consumer), and this
// driver exercises its full surface — epoch runs, mid-epoch restarts
// (abort path), partial batches, and teardown with a live producer — as a
// standalone binary the build compiles with -fsanitize=thread
// (native.build_race_test()); tests/test_native.py runs it and fails on
// any ThreadSanitizer report.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

extern "C" {
void* dtp_create(const uint8_t* x, const int32_t* y, int64_t n,
                 int64_t row_bytes, int64_t batch, int gather_threads,
                 int prefetch_depth);
int64_t dtp_start_epoch(void* handle, const int64_t* perm, int64_t m);
int64_t dtp_next(void* handle, uint8_t* out_x, int32_t* out_y);
void dtp_destroy(void* handle);
}

int main() {
  const int64_t n = 1024, row = 64, batch = 96;
  std::vector<uint8_t> x(n * row);
  std::vector<int32_t> y(n);
  for (int64_t i = 0; i < n; ++i) {
    y[i] = static_cast<int32_t>(i);
    std::memset(x.data() + i * row, static_cast<int>(i & 0xff), row);
  }
  std::vector<int64_t> perm(n);
  for (int64_t i = 0; i < n; ++i) perm[i] = (i * 7) % n;

  std::vector<uint8_t> out_x(batch * row);
  std::vector<int32_t> out_y(batch);

  // gather workers forced on (threads=4) so the task handoff runs under TSAN
  void* p = dtp_create(x.data(), y.data(), n, row, batch, 4, 3);
  if (p == nullptr) return 2;

  // full epochs: every row must come back exactly once, content intact
  for (int e = 0; e < 5; ++e) {
    if (dtp_start_epoch(p, perm.data(), n) != 0) return 3;
    int64_t total = 0;
    for (;;) {
      int64_t rows = dtp_next(p, out_x.data(), out_y.data());
      if (rows <= 0) break;
      for (int64_t i = 0; i < rows; ++i) {
        int64_t src = perm[total + i];
        if (out_y[i] != static_cast<int32_t>(src)) return 4;
        if (out_x[i * row] != static_cast<uint8_t>(src & 0xff)) return 5;
      }
      total += rows;
    }
    if (total != n) return 6;
  }

  // mid-epoch restarts while the producer is staging (abort path)
  for (int e = 0; e < 20; ++e) {
    if (dtp_start_epoch(p, perm.data(), n) != 0) return 7;
    for (int k = 0; k < e % 4; ++k)
      if (dtp_next(p, out_x.data(), out_y.data()) < 0) return 8;
  }

  // teardown with a live, partially-consumed epoch
  dtp_start_epoch(p, perm.data(), n);
  dtp_next(p, out_x.data(), out_y.data());
  dtp_destroy(p);
  std::printf("tsan-driver-ok\n");
  return 0;
}
