// Native host input pipeline: multithreaded batch gather with prefetch.
//
// TPU-native equivalent of the reference's tf.data input path
// (/root/reference/initializer.py:24-55: shard → batch → shuffle).  The
// device step consumes one global batch per step; this runtime gathers the
// next batches' rows (a permutation-indexed gather over the in-memory
// dataset) on a C++ thread pool and stages them in a bounded prefetch queue,
// so host input prep overlaps device compute instead of serializing with it.
//
// Determinism contract: the permutation is COMPUTED IN PYTHON (same
// numpy-seeded order as the pure-Python pipeline) and passed in, so native
// and Python paths yield byte-identical epochs; C++ owns only the parallel
// gather and the staging queue.
//
// C ABI for ctypes.  One producer thread slices each batch across a small
// worker pool; `dtp_next` pops the oldest staged batch (blocking) and
// recycles its buffer.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Buffer {
  std::vector<uint8_t> x;
  std::vector<int32_t> y;
  int64_t rows = 0;
};

struct Pipeline {
  const uint8_t* x = nullptr;   // dataset examples, row-major contiguous
  const int32_t* y = nullptr;   // labels
  int64_t n = 0;                // dataset rows
  int64_t row_bytes = 0;        // bytes per example
  int64_t batch = 0;            // rows per full batch
  int gather_threads = 1;

  std::vector<int64_t> perm;    // epoch order (set by dtp_start_epoch)
  int64_t cursor = 0;           // next row index into perm

  // staging queue: producer fills free buffers, consumer pops ready ones
  std::deque<Buffer*> ready;
  std::deque<Buffer*> free_bufs;
  std::vector<Buffer> pool;

  std::mutex mu;
  std::condition_variable cv_ready;
  std::condition_variable cv_free;
  std::thread producer;
  bool epoch_active = false;    // producer has batches left to stage
  bool abort = false;           // unblock+exit producer (epoch restart)
  bool shutdown = false;

  // persistent gather worker pool (threads live for the pipeline's
  // lifetime; the producer submits one gather task per batch and also
  // works on it itself — no per-batch thread create/join)
  std::vector<std::thread> workers;
  std::mutex task_mu;
  std::condition_variable cv_task;
  std::condition_variable cv_task_done;
  const int64_t* task_idx = nullptr;
  uint8_t* task_out_x = nullptr;
  int32_t* task_out_y = nullptr;
  int64_t task_rows = 0;
  std::atomic<int64_t> task_next{0};
  int task_pending = 0;         // workers still to finish the current task
  uint64_t task_seq = 0;        // bumped per submitted task
  bool workers_shutdown = false;

  ~Pipeline() {
    {
      std::lock_guard<std::mutex> lk(mu);
      shutdown = true;
    }
    cv_free.notify_all();
    cv_ready.notify_all();
    if (producer.joinable()) producer.join();
    {
      std::lock_guard<std::mutex> lk(task_mu);
      workers_shutdown = true;
    }
    cv_task.notify_all();
    for (auto& w : workers) w.join();
  }
};

// Rows per work-stealing grab: big enough to amortize the atomic, small
// enough to balance across workers.
constexpr int64_t kGatherChunk = 64;

void gather_chunks(Pipeline* p) {
  for (;;) {
    int64_t lo = p->task_next.fetch_add(kGatherChunk);
    if (lo >= p->task_rows) return;
    int64_t hi = std::min(p->task_rows, lo + kGatherChunk);
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(p->task_out_x + i * p->row_bytes,
                  p->x + p->task_idx[i] * p->row_bytes,
                  static_cast<size_t>(p->row_bytes));
      p->task_out_y[i] = p->y[p->task_idx[i]];
    }
  }
}

void gather_worker_loop(Pipeline* p) {
  uint64_t seen = 0;
  for (;;) {
    {
      std::unique_lock<std::mutex> lk(p->task_mu);
      p->cv_task.wait(lk, [p, seen] {
        return p->workers_shutdown || p->task_seq != seen;
      });
      if (p->workers_shutdown) return;
      seen = p->task_seq;
    }
    gather_chunks(p);
    {
      std::lock_guard<std::mutex> lk(p->task_mu);
      if (--p->task_pending == 0) p->cv_task_done.notify_one();
    }
  }
}

// Parallel row gather: out[i] = x[idx[i]] for i in [0, rows).  Called only
// from the producer thread (single submitter by construction).
void gather_rows(Pipeline* p, const int64_t* idx, int64_t rows,
                 uint8_t* out_x, int32_t* out_y) {
  if (p->workers.empty() || rows < 2 * kGatherChunk) {
    for (int64_t i = 0; i < rows; ++i) {
      std::memcpy(out_x + i * p->row_bytes, p->x + idx[i] * p->row_bytes,
                  static_cast<size_t>(p->row_bytes));
      out_y[i] = p->y[idx[i]];
    }
    return;
  }
  {
    std::lock_guard<std::mutex> lk(p->task_mu);
    p->task_idx = idx;
    p->task_out_x = out_x;
    p->task_out_y = out_y;
    p->task_rows = rows;
    p->task_next.store(0);
    p->task_pending = static_cast<int>(p->workers.size());
    ++p->task_seq;
  }
  p->cv_task.notify_all();
  gather_chunks(p);  // the producer pulls chunks too
  std::unique_lock<std::mutex> lk(p->task_mu);
  p->cv_task_done.wait(lk, [p] { return p->task_pending == 0; });
}

void producer_loop(Pipeline* p) {
  for (;;) {
    Buffer* buf = nullptr;
    int64_t start, rows;
    {
      std::unique_lock<std::mutex> lk(p->mu);
      if (p->shutdown || p->abort ||
          p->cursor >= static_cast<int64_t>(p->perm.size())) {
        p->epoch_active = false;
        p->cv_ready.notify_all();
        return;
      }
      p->cv_free.wait(lk, [p] {
        return p->shutdown || p->abort || !p->free_bufs.empty();
      });
      if (p->shutdown || p->abort) {
        p->epoch_active = false;
        p->cv_ready.notify_all();
        return;
      }
      buf = p->free_bufs.front();
      p->free_bufs.pop_front();
      start = p->cursor;
      rows = std::min(p->batch, static_cast<int64_t>(p->perm.size()) - start);
      p->cursor += rows;
    }
    gather_rows(p, p->perm.data() + start, rows, buf->x.data(),
                buf->y.data());
    buf->rows = rows;
    {
      std::lock_guard<std::mutex> lk(p->mu);
      p->ready.push_back(buf);
    }
    p->cv_ready.notify_one();
  }
}

}  // namespace

extern "C" {

// Create a pipeline over an in-memory dataset (pointers must stay valid for
// the pipeline's lifetime — the Python wrapper keeps the arrays alive).
void* dtp_create(const uint8_t* x, const int32_t* y, int64_t n,
                 int64_t row_bytes, int64_t batch, int gather_threads,
                 int prefetch_depth) {
  if (x == nullptr || y == nullptr || n <= 0 || row_bytes <= 0 || batch <= 0)
    return nullptr;
  auto* p = new Pipeline();
  p->x = x;
  p->y = y;
  p->n = n;
  p->row_bytes = row_bytes;
  p->batch = batch;
  p->gather_threads = gather_threads < 1 ? 1 : gather_threads;
  int depth = prefetch_depth < 1 ? 1 : prefetch_depth;
  p->pool.resize(static_cast<size_t>(depth));
  for (auto& b : p->pool) {
    b.x.resize(static_cast<size_t>(batch * row_bytes));
    b.y.resize(static_cast<size_t>(batch));
    p->free_bufs.push_back(&b);
  }
  // persistent gather workers (producer participates, so spawn one fewer)
  for (int t = 1; t < p->gather_threads; ++t)
    p->workers.emplace_back(gather_worker_loop, p);
  return p;
}

// Begin an epoch over `perm` (row indices into the dataset, length m ≤ n —
// a shard passes only its own indices).  Restarts the producer thread.
int64_t dtp_start_epoch(void* handle, const int64_t* perm, int64_t m) {
  auto* p = static_cast<Pipeline*>(handle);
  if (p == nullptr || perm == nullptr || m < 0) return -2;
  if (p->producer.joinable()) {
    {
      std::lock_guard<std::mutex> lk(p->mu);
      p->abort = true;
    }
    p->cv_free.notify_all();
    p->producer.join();
  }
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->abort = false;
    for (int64_t i = 0; i < m; ++i)
      if (perm[i] < 0 || perm[i] >= p->n) return -2;
    p->perm.assign(perm, perm + m);
    p->cursor = 0;
    // recycle any batches left staged from an abandoned epoch
    while (!p->ready.empty()) {
      p->free_bufs.push_back(p->ready.front());
      p->ready.pop_front();
    }
    p->epoch_active = m > 0;
  }
  if (m > 0) p->producer = std::thread(producer_loop, p);
  return 0;
}

// Pop the next staged batch into caller buffers (out_x: batch*row_bytes,
// out_y: batch int32).  Returns rows gathered (< batch only for the final
// partial batch), 0 when the epoch is exhausted, -2 on bad handle.
int64_t dtp_next(void* handle, uint8_t* out_x, int32_t* out_y) {
  auto* p = static_cast<Pipeline*>(handle);
  if (p == nullptr) return -2;
  Buffer* buf = nullptr;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_ready.wait(lk, [p] {
      return p->shutdown || !p->ready.empty() || !p->epoch_active;
    });
    if (p->shutdown) return 0;
    if (p->ready.empty()) return 0;  // epoch done
    buf = p->ready.front();
    p->ready.pop_front();
  }
  std::memcpy(out_x, buf->x.data(), static_cast<size_t>(buf->rows * p->row_bytes));
  std::memcpy(out_y, buf->y.data(), static_cast<size_t>(buf->rows) * sizeof(int32_t));
  int64_t rows = buf->rows;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->free_bufs.push_back(buf);
  }
  p->cv_free.notify_one();
  return rows;
}

void dtp_destroy(void* handle) { delete static_cast<Pipeline*>(handle); }

}  // extern "C"
