"""Native (C++) runtime components, loaded via ctypes.

The reference's runtime-around-the-compute is Python sockets + tf.data
(/root/reference/centralized/network.py, initializer.py:24-55).  Here the
equivalent runtime pieces are C++:

  src/wire.cc      — framed socket transport (byte-compatible with the
                     reference's 4-byte big-endian framing)
  src/pipeline.cc  — multithreaded batch-gather input pipeline with a
                     bounded prefetch queue (overlaps host input prep with
                     device steps)

The library builds on demand with g++ (baked into the image; pybind11 is
not, so the ABI is plain C + ctypes).  Everything degrades gracefully: if
the toolchain or a build is unavailable, ``load()`` returns None and pure
Python paths take over.  Set ``DTF_TPU_NO_NATIVE=1`` to force Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from pathlib import Path

_SRC_DIR = Path(__file__).parent / "src"
_LIB_NAME = "libdtf_native.so"
_lib: ctypes.CDLL | None = None
_load_failed = False


def _lib_path() -> Path:
    return Path(__file__).parent / "_build" / _LIB_NAME


def build(force: bool = False) -> Path | None:
    """Compile src/*.cc into the package-local _build/ dir; None on failure."""
    out = _lib_path()
    sources = [s for s in sorted(_SRC_DIR.glob("*.cc"))
               if not s.stem.endswith("_test")]
    if not sources:
        return None
    if out.exists() and not force:
        newest = max(s.stat().st_mtime for s in sources)
        if out.stat().st_mtime >= newest:
            return out
    out.parent.mkdir(parents=True, exist_ok=True)
    # atomic build: compile to a temp name, rename over (parallel pytest safe)
    with tempfile.NamedTemporaryFile(
            dir=out.parent, suffix=".so", delete=False) as tmp:
        tmp_path = Path(tmp.name)
    cmd = [
        os.environ.get("CXX", "g++"), "-O3", "-std=c++17", "-shared", "-fPIC",
        "-pthread", "-Wall", *map(str, sources), "-o", str(tmp_path),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        tmp_path.unlink(missing_ok=True)
        return None
    tmp_path.replace(out)
    return out


def build_race_test() -> Path | None:
    """Build the ThreadSanitizer driver over pipeline.cc (race detection for
    the native runtime — a capability the reference lacks outright,
    SURVEY.md §5).  Returns the binary path, or None when the toolchain or
    libtsan is unavailable.  Run it; any 'WARNING: ThreadSanitizer' output
    (exit code 66 under default TSAN options) is a detected race.
    """
    out = Path(__file__).parent / "_build" / "pipeline_tsan_test"
    sources = [_SRC_DIR / "pipeline.cc", _SRC_DIR / "pipeline_tsan_test.cc"]
    if not all(s.exists() for s in sources):
        return None
    if out.exists() and out.stat().st_mtime >= max(
            s.stat().st_mtime for s in sources):
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    cmd = [
        os.environ.get("CXX", "g++"), "-O1", "-g", "-std=c++17", "-pthread",
        "-fsanitize=thread", *map(str, sources), "-o", str(out),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
    except (OSError, subprocess.SubprocessError):
        return None
    return out


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None when unavailable."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed or os.environ.get("DTF_TPU_NO_NATIVE"):
        return None
    path = build()
    if path is None:
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError:
        _load_failed = True
        return None
    _declare(lib)
    _lib = lib
    return lib


def is_available() -> bool:
    return load() is not None


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    # wire.cc
    lib.dtw_send_frame.argtypes = [c.c_int, c.c_char_p, c.c_uint32]
    lib.dtw_send_frame.restype = c.c_int64
    lib.dtw_recv_frame.argtypes = [c.c_int, c.c_void_p, c.c_uint32]
    lib.dtw_recv_frame.restype = c.c_int64
    lib.dtw_recv_header.argtypes = [c.c_int]
    lib.dtw_recv_header.restype = c.c_int64
    lib.dtw_recv_body.argtypes = [c.c_int, c.c_void_p, c.c_uint32]
    lib.dtw_recv_body.restype = c.c_int64
    lib.dtw_connect.argtypes = [c.c_char_p, c.c_int]
    lib.dtw_connect.restype = c.c_int64
    lib.dtw_listen.argtypes = [c.c_int]
    lib.dtw_listen.restype = c.c_int64
    lib.dtw_port.argtypes = [c.c_int]
    lib.dtw_port.restype = c.c_int64
    lib.dtw_accept.argtypes = [c.c_int]
    lib.dtw_accept.restype = c.c_int64
    lib.dtw_close.argtypes = [c.c_int]
    lib.dtw_close.restype = c.c_int64
    # pipeline.cc
    lib.dtp_create.argtypes = [c.c_void_p, c.c_void_p, c.c_int64, c.c_int64,
                               c.c_int64, c.c_int, c.c_int]
    lib.dtp_create.restype = c.c_void_p
    lib.dtp_start_epoch.argtypes = [c.c_void_p, c.c_void_p, c.c_int64]
    lib.dtp_start_epoch.restype = c.c_int64
    lib.dtp_next.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
    lib.dtp_next.restype = c.c_int64
    lib.dtp_destroy.argtypes = [c.c_void_p]
    lib.dtp_destroy.restype = None
