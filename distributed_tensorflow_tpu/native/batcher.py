"""Python wrapper over the native prefetching batch pipeline.

Byte-identical semantics to the pure-Python ``data.pipeline.iter_batches``
(same numpy permutation, same final-batch zero-padding + mask — the padding
contract documented there), but the permutation-indexed row gather runs on a
C++ thread pool and batches are staged in a bounded prefetch queue, so the
next batch is already assembled while the device executes the current step.
The reference's input path has no such overlap — tf.data prep and training
interleave on the same Python process (reference initializer.py:24-55).
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator

import numpy as np

from distributed_tensorflow_tpu import native

Batch = tuple[np.ndarray, np.ndarray, np.ndarray]


class _EpochIterator:
    """Iterator over one epoch that owns the batcher's busy claim.

    Releases the claim on exhaustion, close(), or garbage collection — even
    if iteration never started (a plain generator's try/finally would not
    run for an unstarted generator, leaking the claim forever).  ``close()``
    is part of the shared batch-iterator contract (data/pipeline.py):
    read-ahead consumers like data.device_prefetch call it when their
    consumer stops early, so the claim is released deterministically
    instead of at GC time.
    """

    def __init__(self, batcher: "NativeBatcher", gen):
        self._batcher = batcher
        self._gen = gen

    def __iter__(self):
        return self

    def __next__(self) -> Batch:
        try:
            return next(self._gen)
        except BaseException:
            self.close()
            raise

    def close(self) -> None:
        if self._batcher is not None:
            self._gen.close()
            self._batcher.busy = False
            self._batcher = None

    def __del__(self):
        self.close()


class NativeBatcher:
    """Reusable pipeline over one in-memory dataset.

    Keeps the dataset arrays alive for the C++ side and reuses the staging
    buffers across epochs.  Not thread-safe; one consumer at a time.

    Labels are SCALAR per row (the (batch,) int32 staging buffer below):
    datasets with per-row label arrays — the LM next-token layout — must
    use the Python path; ``Dataset.batches`` gates on ``y.ndim`` so the
    C++ gather can never silently flatten (B, L) targets.
    """

    def __init__(self, x: np.ndarray, y: np.ndarray, batch_size: int,
                 gather_threads: int | None = None, prefetch_depth: int = 2):
        lib = native.load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self._lib = lib
        # C-contiguous views the C++ side will index into (kept alive here)
        self._x = np.ascontiguousarray(x)
        self._y = np.ascontiguousarray(y, dtype=np.int32)
        self.batch_size = batch_size
        self.row_shape = self._x.shape[1:]
        self._row_bytes = self._x.itemsize * int(np.prod(self.row_shape, dtype=np.int64))
        if gather_threads is None:
            gather_threads = min(8, os.cpu_count() or 1)
        self._handle = lib.dtp_create(
            self._x.ctypes.data_as(ctypes.c_void_p),
            self._y.ctypes.data_as(ctypes.c_void_p),
            len(self._x), self._row_bytes, batch_size,
            gather_threads, prefetch_depth)
        if not self._handle:
            raise RuntimeError("dtp_create failed")
        self._full_mask = np.ones(batch_size, np.float32)
        self.busy = False  # an epoch iterator currently owns the C++ handle

    def epoch(self, *, shuffle: bool = True, seed: int = 0, epoch: int = 0,
              drop_remainder: bool = False) -> Iterator[Batch]:
        """Yield (x, y, mask) batches for one epoch — the iter_batches contract.

        One iterator at a time: the C++ handle holds a single epoch's
        cursor, so a second concurrent iterator would hijack it.  ``busy``
        is claimed eagerly here (not at first next()) and released when the
        returned iterator is exhausted, closed, or garbage-collected —
        including before its first next() (_EpochIterator owns the claim);
        callers that need concurrency create another NativeBatcher
        (Dataset.batches does this automatically).
        """
        if self.busy:
            raise RuntimeError(
                "NativeBatcher is busy: another epoch iterator is active; "
                "create a separate NativeBatcher for concurrent iteration")
        self.busy = True
        return _EpochIterator(self, self._epoch_body(
            shuffle=shuffle, seed=seed, epoch=epoch,
            drop_remainder=drop_remainder))

    def _epoch_body(self, *, shuffle, seed, epoch, drop_remainder):
        n = len(self._x)
        idx = np.arange(n, dtype=np.int64)
        if shuffle:
            # identical permutation to data.pipeline.iter_batches
            np.random.default_rng((seed, epoch)).shuffle(idx)
        rc = self._lib.dtp_start_epoch(
            self._handle, idx.ctypes.data_as(ctypes.c_void_p), n)
        if rc != 0:
            raise RuntimeError(f"dtp_start_epoch failed ({rc})")
        while True:
            # fresh arrays per batch: dtp_next fills them directly, so the
            # consumer owns the memory (no copy-out, no reuse hazards)
            out_x = np.empty((self.batch_size, *self.row_shape), self._x.dtype)
            out_y = np.empty(self.batch_size, np.int32)
            rows = self._lib.dtp_next(
                self._handle,
                out_x.ctypes.data_as(ctypes.c_void_p),
                out_y.ctypes.data_as(ctypes.c_void_p))
            if rows <= 0:
                return
            if rows < self.batch_size:
                if drop_remainder:
                    return
                out_x[rows:] = 0
                out_y[rows:] = 0
                mask = np.zeros(self.batch_size, np.float32)
                mask[:rows] = 1.0
                yield out_x, out_y, mask
                return
            yield out_x, out_y, self._full_mask.copy()

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.dtp_destroy(self._handle)
            self._handle = None

    def __del__(self):
        self.close()
