"""distributed_tensorflow_tpu — a TPU-native distributed training framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
framework jpadrao/distributed-tensorflow (parameter-server sync/async DP and
collective-allreduce DP over TCP/pickle and TF RING collectives —
/root/reference/centralized/server.py, /root/reference/decentralized/native/
dist_keras.py).  Here every training mode is a single-program multiple-data
(SPMD) program over a `jax.sharding.Mesh`; gradients/parameters ride ICI via
XLA collectives (`psum`/`ppermute`) instead of pickled TCP messages.

Layering (SURVEY.md §7.2):
  L0  parallel.mesh         — device discovery, Mesh construction, multi-host init
  L1  parallel.collectives  — named collective wrappers (the "wire" replacement)
  L2  engines.*             — sync / async-local / allreduce / gossip step engines
  L3  models.*, data.*      — model_fn / dataset_fn plug-in points
  L4  cli                   — initializer.py-compatible launcher
  L5  utils.harness         — timing window, eval, supervisor-style reporting
"""

__version__ = "0.1.0"

from distributed_tensorflow_tpu.parallel import mesh, collectives  # noqa: F401
