"""distributed_tensorflow_tpu — a TPU-native distributed training framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
framework jpadrao/distributed-tensorflow (parameter-server sync/async DP and
collective-allreduce DP over TCP/pickle and TF RING collectives —
/root/reference/centralized/server.py, /root/reference/decentralized/native/
dist_keras.py).  Here every training mode is a single-program multiple-data
(SPMD) program over a `jax.sharding.Mesh`; gradients/parameters ride ICI via
XLA collectives (`psum`/`ppermute`) instead of pickled TCP messages.

Layering (SURVEY.md §7.2):
  L0  parallel.mesh         — device discovery, Mesh construction, multi-host init
  L1  parallel.collectives  — named collective wrappers (the "wire" replacement)
  L2  engines.*             — sync / async-local / allreduce / gossip step engines
  L3  models.*, data.*      — model_fn / dataset_fn plug-in points
  L4  cli                   — initializer.py-compatible launcher
  L5  utils.harness         — timing window, eval, supervisor-style reporting
"""

__version__ = "0.1.0"


def _honor_platform_env() -> None:
    """Re-assert the user's JAX platform env choice over preloaded plugins.

    Environments that preload a PJRT plugin from sitecustomize (e.g. a
    remote-TPU tunnel) may force ``jax_platforms`` via ``jax.config`` at
    interpreter start, which silently overrides the ``JAX_PLATFORMS`` /
    ``JAX_PLATFORM_NAME`` env vars the fake-CPU-mesh recipes use (README).
    Re-applying the env choice at package import — before any backend is
    initialized in every supported entry path (CLI, examples, library use:
    all import this package before touching a jax device API) — means no
    entry script needs its own boilerplate, and a forgotten preamble can't
    hang on an unreachable accelerator.

    Precedence matches JAX's own: a non-empty ``JAX_PLATFORMS`` wins,
    the deprecated ``JAX_PLATFORM_NAME`` is the fallback (the README
    recipe sets ``JAX_PLATFORMS="" JAX_PLATFORM_NAME=cpu``, which lands
    on cpu through the fallback).  No-op when neither env var is set.
    Known tradeoff: with an env var SET, this import-time hook re-applies
    it over any earlier programmatic ``jax.config.update`` — that is the
    point (the sitecustomize preload IS such an update).  An embedding
    application that wants a different platform than its env vars say
    should update ``jax.config`` AFTER importing this package, or unset
    the env vars.  If that host process already *initialized* a backend
    before importing us, the re-assert cannot take effect for this
    process — a RuntimeWarning says so instead of no-opping silently."""
    import os

    plats = os.environ.get("JAX_PLATFORMS")
    if plats:
        # verbatim: jax_platforms entries are case-sensitive lookups
        # against registered backend/plugin names — lowercasing here would
        # break a PJRT plugin registered under a non-lowercase name
        want = plats
    else:
        name = os.environ.get("JAX_PLATFORM_NAME")
        # jax itself lowercases JAX_PLATFORM_NAME (xla_bridge) — match it
        # so e.g. JAX_PLATFORM_NAME=CPU selects cpu instead of erroring
        want = name.lower() if name else None
    if want:
        import jax

        active: set = set()
        try:
            # passive peek at initialized backends: the public
            # backends() accessor would itself initialize one
            from jax._src import xla_bridge as _xla_bridge

            active = set(_xla_bridge._backends)
        except Exception:  # pragma: no cover - jax internals moved
            pass
        jax.config.update("jax_platforms", want)
        wanted: set = set()
        for token in want.split(","):
            token = token.strip()
            if not token:
                continue
            try:
                # aliases name backend sets ('gpu' → cuda/rocm): expand so
                # a live cuda backend under JAX_PLATFORMS=gpu doesn't warn
                wanted.update(_xla_bridge.expand_platform_alias(token.lower()))
            except Exception:
                pass
            wanted.add(token)
        # the WARNING check is case-insensitive on both sides (the config
        # value itself stays verbatim): a live 'MyPlugin' backend under
        # JAX_PLATFORMS=MyPlugin is a match, not a conflict
        wanted_ci = {w.lower() for w in wanted}
        active_ci = {a.lower() for a in active}
        if active_ci and not (active_ci & wanted_ci):
            # a backend is live on a platform the env did NOT ask for: the
            # config update above cannot take effect for this process
            import warnings

            warnings.warn(
                f"distributed_tensorflow_tpu: a JAX backend is already "
                f"initialized on {sorted(active)}, so re-asserting "
                f"jax_platforms={want!r} from the environment cannot take "
                f"effect for this process; import this package (or set "
                f"jax.config) before touching any jax device API",
                RuntimeWarning, stacklevel=3)


_honor_platform_env()

from distributed_tensorflow_tpu.parallel import mesh, collectives  # noqa: E402,F401
