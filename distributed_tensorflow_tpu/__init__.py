"""distributed_tensorflow_tpu — a TPU-native distributed training framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
framework jpadrao/distributed-tensorflow (parameter-server sync/async DP and
collective-allreduce DP over TCP/pickle and TF RING collectives —
/root/reference/centralized/server.py, /root/reference/decentralized/native/
dist_keras.py).  Here every training mode is a single-program multiple-data
(SPMD) program over a `jax.sharding.Mesh`; gradients/parameters ride ICI via
XLA collectives (`psum`/`ppermute`) instead of pickled TCP messages.

Layering (SURVEY.md §7.2):
  L0  parallel.mesh         — device discovery, Mesh construction, multi-host init
  L1  parallel.collectives  — named collective wrappers (the "wire" replacement)
  L2  engines.*             — sync / async-local / allreduce / gossip step engines
  L3  models.*, data.*      — model_fn / dataset_fn plug-in points
  L4  cli                   — initializer.py-compatible launcher
  L5  utils.harness         — timing window, eval, supervisor-style reporting
"""

__version__ = "0.1.0"


def _honor_platform_env() -> None:
    """Re-assert the user's JAX platform env choice over preloaded plugins.

    Environments that preload a PJRT plugin from sitecustomize (e.g. a
    remote-TPU tunnel) may force ``jax_platforms`` via ``jax.config`` at
    interpreter start, which silently overrides the ``JAX_PLATFORMS`` /
    ``JAX_PLATFORM_NAME`` env vars the fake-CPU-mesh recipes use (README).
    Re-applying the env choice at package import — before any backend is
    initialized in every supported entry path (CLI, examples, library use:
    all import this package before touching a jax device API) — means no
    entry script needs its own boilerplate, and a forgotten preamble can't
    hang on an unreachable accelerator.

    Precedence matches JAX's own: a non-empty ``JAX_PLATFORMS`` wins,
    the deprecated ``JAX_PLATFORM_NAME`` is the fallback (the README
    recipe sets ``JAX_PLATFORMS="" JAX_PLATFORM_NAME=cpu``, which lands
    on cpu through the fallback).  No-op when neither env var is set.
    Known tradeoff: with an env var SET, this import-time hook re-applies
    it over any earlier programmatic ``jax.config.update`` — that is the
    point (the sitecustomize preload IS such an update).  An embedding
    application that wants a different platform than its env vars say
    should update ``jax.config`` AFTER importing this package, or unset
    the env vars."""
    import os

    want = (os.environ.get("JAX_PLATFORMS")
            or os.environ.get("JAX_PLATFORM_NAME"))
    if want:
        import jax

        # jax itself lowercases JAX_PLATFORM_NAME (xla_bridge) while
        # jax_platforms lookups are case-sensitive — normalize so e.g.
        # JAX_PLATFORM_NAME=CPU selects cpu instead of erroring
        jax.config.update("jax_platforms", want.lower())


_honor_platform_env()

from distributed_tensorflow_tpu.parallel import mesh, collectives  # noqa: E402,F401
