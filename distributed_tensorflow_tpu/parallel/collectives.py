"""L1 collectives: the framework's "wire" layer.

TPU-native replacement for both of the reference's transports
(SURVEY.md §2.3):

* the hand-rolled length-prefixed pickle-over-TCP framing
  (reference centralized/network.py:4-28), and
* TF's `CollectiveCommunication.RING` allreduce
  (reference decentralized/native/dist_keras.py:77-78).

Tensors never touch host sockets here: every function below lowers to an XLA
collective that rides ICI (intra-slice) or DCN (cross-slice).  All functions
are pure and must be called inside a `jax.shard_map`-mapped function over a
mesh axis; they are unit-tested on the 8-device CPU fake mesh.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def axis_size(axis: str) -> int:
    return lax.axis_size(axis)


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def all_reduce_sum(tree: PyTree, axis: str) -> PyTree:
    """Sum across the mesh axis (RING allreduce equivalent)."""
    return lax.psum(tree, axis_name=axis)


def all_reduce_mean(tree: PyTree, axis: str) -> PyTree:
    """Mean across the mesh axis — the gradient-combine step of sync DP.

    Replaces one round of the reference's per-worker `('train', grads)` push /
    weights pull over TCP (reference client.py:85-90, server.py:86-107).
    """
    return lax.pmean(tree, axis_name=axis)


def all_gather(x: jax.Array, axis: str, *, tiled: bool = False) -> jax.Array:
    return lax.all_gather(x, axis_name=axis, tiled=tiled)


def reduce_scatter_sum(x: jax.Array, axis: str) -> jax.Array:
    """Sum-then-shard along leading dim (`psum_scatter`)."""
    return lax.psum_scatter(x, axis_name=axis, tiled=True)


def all_to_all(x: jax.Array, axis: str, split_axis: int, concat_axis: int) -> jax.Array:
    """All-to-all over the mesh axis (used by Ulysses-style sequence parallelism)."""
    return lax.all_to_all(x, axis_name=axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ring_shift(tree: PyTree, axis: str, shift: int = 1) -> PyTree:
    """Rotate values around the mesh-axis ring by ``shift`` positions.

    Device i receives the value from device (i - shift) mod n.  This is the
    building block for gossip averaging and ring attention.
    """
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.tree.map(lambda x: lax.ppermute(x, axis_name=axis, perm=perm), tree)


def neighbor_mean(tree: PyTree, axis: str, degree: int = 1) -> PyTree:
    """Average with ``degree`` ring neighbors on each side — gossip averaging.

    Implements for real the reference's declared-but-unimplemented
    `graph`/`custom` decentralized strategies (reference initializer.py:175-181
    raise NotImplementedError; the vestigial `-d` degree flag is reference
    initializer.py:90-92).  Each device's value becomes the mean of itself and
    its `2*degree` nearest ring neighbors.
    """
    if degree <= 0:
        return tree
    n = lax.axis_size(axis)
    if 2 * degree + 1 >= n:
        # neighborhood covers the whole ring — full averaging (also handles
        # tiny meshes like n=2 where fwd/bwd neighbors coincide and naive
        # clamping would silently disable mixing)
        return lax.pmean(tree, axis_name=axis)

    def mix(x):
        acc = x
        for d in range(1, degree + 1):
            fwd = [(i, (i + d) % n) for i in range(n)]
            bwd = [(i, (i - d) % n) for i in range(n)]
            acc = acc + lax.ppermute(x, axis_name=axis, perm=fwd)
            acc = acc + lax.ppermute(x, axis_name=axis, perm=bwd)
        return acc / (2 * degree + 1)

    return jax.tree.map(mix, tree)


def broadcast_from(tree: PyTree, axis: str, src: int = 0) -> PyTree:
    """Broadcast device ``src``'s value to every device on the axis.

    Replaces the reference's initial-weights broadcast on the 'start'
    message (reference server.py:70-84, client.py:67-72).
    """
    idx = lax.axis_index(axis)

    def sel(x):
        mask = (idx == src).astype(x.dtype)
        return lax.psum(x * mask, axis_name=axis)

    return jax.tree.map(sel, tree)
