"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no attention and no sequence axis anywhere (SURVEY.md §2.2:
its only model is an MLP on 28×28, reference initializer.py:14-19) — this
module is TPU-native *new* capability required for long-context training:
sequences longer than one device's memory are sharded over a ``seq`` mesh
axis and attention runs without ever materializing the full (L, L) score
matrix on one chip.

Two standard strategies, both built on the L1 collectives layer:

* **Ring attention** (`ring_attention`): K/V blocks rotate around the mesh
  ring via `ppermute` while each device's Q stays put; partial softmax
  results merge with the numerically-stable running log-sum-exp (the
  blockwise/flash accumulation).  Communication is nearest-neighbor only —
  the cheapest pattern on a TPU torus (ICI), overlapping compute with the
  next block's transfer.
* **Ulysses** (`ulysses_attention`): `all_to_all` reshards activations from
  sequence-sharded to head-sharded, runs ordinary dense attention on full
  sequences for a subset of heads, and reshards back.  Needs
  ``num_heads % axis_size == 0``.

All functions must be called inside `jax.shard_map` with the sequence dim
sharded over ``axis``.  Shapes: q/k/v are (batch, seq_local, heads, head_dim).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_tpu.parallel.collectives import ring_shift

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() NaN-free when
                 # an entire block is masked (first causal blocks)


def _block_scores(q, k, scale):
    # (B, Lq, H, D) x (B, Lk, H, D) -> (B, H, Lq, Lk)
    return jnp.einsum("blhd,bmhd->bhlm", q, k) * scale


def dense_attention(q, k, v, causal: bool = False, scale: float | None = None,
                    kv_mask=None, prob_fn=None):
    """Single-device reference attention (test oracle and small-seq path).

    ``kv_mask``: optional (B, Lk) key-validity mask; masked keys get NEG_INF.
    ``prob_fn``: optional transform of the post-softmax probabilities —
    the hook for attention-probability dropout (blockwise ring attention
    cannot support it; flash-style implementations conventionally drop it).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = _block_scores(q, k, scale)
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        qpos = jnp.arange(lq)[:, None]
        kpos = jnp.arange(lk)[None, :]
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :] > 0, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if prob_fn is not None:
        p = prob_fn(p)
    return jnp.einsum("bhlm,bmhd->blhd", p, v)


def ring_attention(q, k, v, axis: str, causal: bool = False,
                   scale: float | None = None, kv_mask=None):
    """Blockwise ring attention over the ``axis`` mesh ring.

    Device i holds Q/K/V for sequence block i.  At ring step t it attends
    Q_i against the K/V block that originated at device (i - t) mod n, then
    passes its current K/V to device i+1.  After n steps every Q block has
    seen every K/V block; the running (max, sum, acc) merge makes the result
    exactly softmax(QKᵀ)V, independent of arrival order.
    """
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, lq, h, d = q.shape
    lk = k.shape[1]

    # derive from k so the mask inherits k's varying-axes type (the fori_loop
    # carry requires input/output types — incl. vma — to match exactly)
    mask0 = kv_mask if kv_mask is not None else jnp.ones_like(k[..., 0, 0])

    def process(t, m, l, acc, k_cur, v_cur, mk_cur):
        src = (idx - t) % n  # which global block k_cur/v_cur came from
        s = _block_scores(q, k_cur, scale)  # (B,H,Lq,Lk)
        if causal:
            qpos = idx * lq + jnp.arange(lq)
            kpos = src * lk + jnp.arange(lk)
            s = jnp.where(qpos[:, None] >= kpos[None, :], s, NEG_INF)
        s = jnp.where(mk_cur[:, None, None, :] > 0, s, NEG_INF)
        m_blk = s.max(axis=-1)                     # (B,H,Lq)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[..., None])          # (B,H,Lq,Lk)
        corr = jnp.exp(m - m_new)                  # (B,H,Lq)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhlm,bmhd->blhd", p, v_cur)
        acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
        return m_new, l_new, acc_new

    def body(t, carry):
        m, l, acc, k_cur, v_cur, mk_cur = carry
        # rotate-then-process: n-1 rotations total (the naive
        # process-then-rotate shape wastes a final dead K/V/mask transfer)
        k_cur, v_cur, mk_cur = ring_shift((k_cur, v_cur, mk_cur), axis)
        m, l, acc = process(t, m, l, acc, k_cur, v_cur, mk_cur)
        return m, l, acc, k_cur, v_cur, mk_cur

    # accumulators derived from q so they inherit q's varying-axes type
    # (works whether the surrounding shard_map has one mesh axis or several)
    qt = jnp.moveaxis(q[..., 0], 1, 2)  # (B, H, Lq)
    m0 = jnp.full_like(qt, NEG_INF)
    l0 = jnp.zeros_like(qt)
    acc0 = jnp.zeros_like(q)
    # block 0 (own K/V) costs no communication; the loop does the other n-1
    m, l, acc = process(0, m0, l0, acc0, k, v, mask0)
    if n > 1:
        m, l, acc, _, _, _ = lax.fori_loop(1, n, body, (m, l, acc, k, v, mask0))
    # rows with no unmasked key (impossible under causal self-attn, but keep
    # the division safe) fall back to 0
    l = jnp.maximum(l, 1e-30)
    return acc / l.transpose(0, 2, 1)[..., None]


def ulysses_attention(q, k, v, axis: str, causal: bool = False,
                      scale: float | None = None, kv_mask=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Reshard (B, L/n, H, D) → (B, L, H/n, D) with one `all_to_all`, run dense
    attention on the full sequence for H/n heads, reshard back.  Two
    all-to-alls per tensor vs n ppermute hops for ring — better when H
    divides well and the full-sequence scores fit in memory.
    """
    n = lax.axis_size(axis)
    if q.shape[2] % n != 0:
        raise ValueError(f"num_heads {q.shape[2]} not divisible by axis size {n}")

    def to_heads(x):  # (B, L/n, H, D) -> (B, L, H/n, D)
        return lax.all_to_all(x, axis_name=axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(x):    # (B, L, H/n, D) -> (B, L/n, H, D)
        return lax.all_to_all(x, axis_name=axis, split_axis=1, concat_axis=2,
                              tiled=True)

    full_mask = None
    if kv_mask is not None:  # (B, L/n) → (B, L): every device needs all keys
        full_mask = lax.all_gather(kv_mask, axis_name=axis, axis=1, tiled=True)
    out = dense_attention(to_heads(q), to_heads(k), to_heads(v),
                          causal=causal, scale=scale, kv_mask=full_mask)
    return to_seq(out)
