"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no attention and no sequence axis anywhere (SURVEY.md §2.2:
its only model is an MLP on 28×28, reference initializer.py:14-19) — this
module is TPU-native *new* capability required for long-context training:
sequences longer than one device's memory are sharded over a ``seq`` mesh
axis and attention runs without ever materializing the full (L, L) score
matrix on one chip.

Two standard strategies, both built on the L1 collectives layer:

* **Ring attention** (`ring_attention`): K/V blocks rotate around the mesh
  ring via `ppermute` while each device's Q stays put; partial softmax
  results merge with the numerically-stable running log-sum-exp (the
  blockwise/flash accumulation).  Communication is nearest-neighbor only —
  the cheapest pattern on a TPU torus (ICI), overlapping compute with the
  next block's transfer.
* **Ulysses** (`ulysses_attention`): `all_to_all` reshards activations from
  sequence-sharded to head-sharded, runs ordinary dense attention on full
  sequences for a subset of heads, and reshards back.  Needs
  ``num_heads % axis_size == 0``.

All functions must be called inside `jax.shard_map` with the sequence dim
sharded over ``axis``.  Shapes: q/k/v are (batch, seq_local, heads, head_dim).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_tpu.parallel.collectives import ring_shift

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() NaN-free when
                 # an entire block is masked (first causal blocks)


def _block_scores(q, k, scale):
    # (B, Lq, H, D) x (B, Lk, H, D) -> (B, H, Lq, Lk)
    return jnp.einsum("blhd,bmhd->bhlm", q, k) * scale


def dense_attention(q, k, v, causal: bool = False, scale: float | None = None,
                    kv_mask=None, prob_fn=None):
    """Single-device reference attention (test oracle and small-seq path).

    ``kv_mask``: optional key-validity mask; masked keys get NEG_INF.
    (B, Lk) applies per batch row to every query; (B, Lq, Lk) applies per
    QUERY — the multi-position slot-decode verify step needs each query in
    a token block to see only cache positions at or before its own.
    ``prob_fn``: optional transform of the post-softmax probabilities —
    the hook for attention-probability dropout (blockwise ring attention
    cannot support it; flash-style implementations conventionally drop it).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = _block_scores(q, k, scale)
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        qpos = jnp.arange(lq)[:, None]
        kpos = jnp.arange(lk)[None, :]
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    if kv_mask is not None:
        m = (kv_mask[:, None, :, :] if kv_mask.ndim == 3
             else kv_mask[:, None, None, :])
        s = jnp.where(m > 0, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if prob_fn is not None:
        p = prob_fn(p)
    return jnp.einsum("bhlm,bmhd->blhd", p, v)


def ring_attention(q, k, v, axis: str, causal: bool = False,
                   scale: float | None = None, kv_mask=None):
    """Blockwise ring attention over the ``axis`` mesh ring.

    Device i holds Q/K/V for sequence block i.  At ring step t it attends
    Q_i against the K/V block that originated at device (i - t) mod n, then
    passes its current K/V to device i+1.  After n steps every Q block has
    seen every K/V block; the running (max, sum, acc) merge makes the result
    exactly softmax(QKᵀ)V, independent of arrival order.
    """
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, lq, h, d = q.shape
    lk = k.shape[1]

    # derive from k so the mask inherits k's varying-axes type (the fori_loop
    # carry requires input/output types — incl. vma — to match exactly)
    mask0 = kv_mask if kv_mask is not None else jnp.ones_like(k[..., 0, 0])

    def process(t, m, l, acc, k_cur, v_cur, mk_cur):
        src = (idx - t) % n  # which global block k_cur/v_cur came from
        s = _block_scores(q, k_cur, scale)  # (B,H,Lq,Lk)
        if causal:
            qpos = idx * lq + jnp.arange(lq)
            kpos = src * lk + jnp.arange(lk)
            s = jnp.where(qpos[:, None] >= kpos[None, :], s, NEG_INF)
        s = jnp.where(mk_cur[:, None, None, :] > 0, s, NEG_INF)
        m_blk = s.max(axis=-1)                     # (B,H,Lq)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[..., None])          # (B,H,Lq,Lk)
        corr = jnp.exp(m - m_new)                  # (B,H,Lq)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhlm,bmhd->blhd", p, v_cur)
        acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
        return m_new, l_new, acc_new

    def body(t, carry):
        m, l, acc, k_cur, v_cur, mk_cur = carry
        # rotate-then-process: n-1 rotations total (the naive
        # process-then-rotate shape wastes a final dead K/V/mask transfer)
        k_cur, v_cur, mk_cur = ring_shift((k_cur, v_cur, mk_cur), axis)
        m, l, acc = process(t, m, l, acc, k_cur, v_cur, mk_cur)
        return m, l, acc, k_cur, v_cur, mk_cur

    # accumulators derived from q so they inherit q's varying-axes type
    # (works whether the surrounding shard_map has one mesh axis or several)
    qt = jnp.moveaxis(q[..., 0], 1, 2)  # (B, H, Lq)
    m0 = jnp.full_like(qt, NEG_INF)
    l0 = jnp.zeros_like(qt)
    acc0 = jnp.zeros_like(q)
    # block 0 (own K/V) costs no communication; the loop does the other n-1
    m, l, acc = process(0, m0, l0, acc0, k, v, mask0)
    if n > 1:
        m, l, acc, _, _, _ = lax.fori_loop(1, n, body, (m, l, acc, k, v, mask0))
    # rows with no unmasked key (impossible under causal self-attn, but keep
    # the division safe) fall back to 0
    l = jnp.maximum(l, 1e-30)
    return acc / l.transpose(0, 2, 1)[..., None]


def _ulysses(q, k, v, axis: str, causal: bool, scale, kv_mask, attn_fn):
    """Shared Ulysses reshard: (B, L/n, H, D) → (B, L, H/n, D) with one
    `all_to_all`, run ``attn_fn`` on the full sequence for H/n heads,
    reshard back.  Two all-to-alls per tensor vs n ppermute hops for ring —
    better when H divides well and the local math handles the full
    sequence."""
    n = lax.axis_size(axis)
    if q.shape[2] % n != 0:
        raise ValueError(f"num_heads {q.shape[2]} not divisible by axis size {n}")

    def to_heads(x):  # (B, L/n, H, D) -> (B, L, H/n, D)
        return lax.all_to_all(x, axis_name=axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(x):    # (B, L, H/n, D) -> (B, L/n, H, D)
        return lax.all_to_all(x, axis_name=axis, split_axis=1, concat_axis=2,
                              tiled=True)

    full_mask = None
    if kv_mask is not None:  # (B, L/n) → (B, L): every device needs all keys
        full_mask = lax.all_gather(kv_mask, axis_name=axis, axis=1, tiled=True)
    out = attn_fn(to_heads(q), to_heads(k), to_heads(v),
                  causal=causal, scale=scale, kv_mask=full_mask)
    return to_seq(out)


def ulysses_attention(q, k, v, axis: str, causal: bool = False,
                      scale: float | None = None, kv_mask=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism with XLA
    dense local attention — see ``_ulysses``."""
    return _ulysses(q, k, v, axis, causal, scale, kv_mask, dense_attention)


def ulysses_flash_attention(q, k, v, axis: str, causal: bool = False,
                            scale: float | None = None, kv_mask=None):
    """Ulysses reshard with the Pallas flash kernel as the local math.

    After the all-to-all each device holds the FULL sequence for H/n
    heads — exactly the single-device flash case, so the fused kernel
    (ops/flash_attention.py: on-chip tiles, never materializes the (L, L)
    scores, causal block skipping, custom-vjp backward) applies verbatim.
    The communication pattern is identical to ``ulysses_attention``; only
    the O(L²) local compute changes — the same relationship ring_flash
    has to ring."""
    from distributed_tensorflow_tpu.ops.flash_attention import flash_attention

    return _ulysses(q, k, v, axis, causal, scale, kv_mask, flash_attention)


# ---------------------------------------------------------------------------
# ring attention with Pallas flash local math
# ---------------------------------------------------------------------------
#
# Same schedule as `ring_attention`, but each (Q_i, K_src) pairing runs the
# flash kernel (ops/flash_attention.py) instead of XLA blockwise math: the
# local (Lq, Lk) score tile lives in VMEM, never HBM.  The cross-block
# softmax merge happens here on the kernels' (out, lse) pairs, and — because
# the kernel wrappers are raw primitives, not differentiable — the whole
# ring carries its own `jax.custom_vjp`: the backward runs a second ring
# pass in which (k, v, dk, dv) rotate together and every device adds its
# block's contribution from the flash backward kernels, using the GLOBAL
# lse/delta saved from the forward (the standard ring-flash-attention
# decomposition).
#
# Causal masking never needs in-kernel positional offsets: a block pairing
# is entirely past (src < idx → plain full attention), diagonal (src == idx
# → the kernel's own causal mask), or entirely future (skipped — the ring
# analogue of the kernel's `pl.when` block skipping, ~2× fewer FLOPs).
# The branches run under `lax.switch` on a device-varying index; they are
# collective-free (a pallas_call is not a collective), which is what makes
# per-device branching legal inside shard_map.


def _merge_blocks(acc, lse, out_b, lse_b):
    """Numerically-stable merge of (acc, lse) with a new block's (out, lse):
    softmax-weighted combination in f32."""
    lse_new = jnp.logaddexp(lse, lse_b)
    alpha = jnp.exp(lse - lse_new)       # (B, H, Lq)
    beta = jnp.exp(lse_b - lse_new)
    acc = (acc * alpha.transpose(0, 2, 1)[..., None]
           + out_b.astype(jnp.float32) * beta.transpose(0, 2, 1)[..., None])
    return acc, lse_new


def _ring_flash_fwd_pass(q, k, v, mask, axis, causal, scale, bq, bk,
                         interpret):
    from distributed_tensorflow_tpu.ops.flash_attention import flash_fwd_block

    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)

    def block(src, k_cur, v_cur, mk_cur):
        def full(_):
            return flash_fwd_block(q, k_cur, v_cur, mk_cur, scale=scale,
                                   causal=False, block_q=bq, block_k=bk,
                                   interpret=interpret)

        def diag(_):
            return flash_fwd_block(q, k_cur, v_cur, mk_cur, scale=scale,
                                   causal=True, block_q=bq, block_k=bk,
                                   interpret=interpret)

        def skip(_):
            qt = jnp.moveaxis(q[..., 0], 1, 2).astype(jnp.float32)
            return jnp.zeros_like(q), jnp.full_like(qt, NEG_INF)

        if not causal:
            return full(None)
        # 0: future (skip), 1: diagonal (causal), 2: past (full)
        branch = jnp.int32(0) + (src <= idx) + (src < idx)
        return lax.switch(branch, [skip, diag, full], None)

    qt = jnp.moveaxis(q[..., 0], 1, 2).astype(jnp.float32)  # (B, H, Lq)
    acc0 = jnp.zeros_like(q, dtype=jnp.float32)
    lse0 = jnp.full_like(qt, NEG_INF)
    out_b, lse_b = block(idx, k, v, mask)
    acc, lse = _merge_blocks(acc0, lse0, out_b, lse_b)

    def body(t, carry):
        acc, lse, k_cur, v_cur, mk_cur = carry
        k_cur, v_cur, mk_cur = ring_shift((k_cur, v_cur, mk_cur), axis)
        src = (idx - t) % n
        out_b, lse_b = block(src, k_cur, v_cur, mk_cur)
        acc, lse = _merge_blocks(acc, lse, out_b, lse_b)
        return acc, lse, k_cur, v_cur, mk_cur

    if n > 1:  # block 0 (own K/V) above costs no communication
        acc, lse, _, _, _ = lax.fori_loop(
            1, n, body, (acc, lse, k, v, mask))
    return acc.astype(q.dtype), lse


def _ring_flash_bwd_pass(q, k, v, mask, lse, delta, do, axis, causal, scale,
                         bq, bk, interpret):
    from distributed_tensorflow_tpu.ops.flash_attention import flash_bwd_block

    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)

    def block_grads(src, k_cur, v_cur, mk_cur):
        def full(_):
            return flash_bwd_block(q, k_cur, v_cur, mk_cur, do, lse, delta,
                                   scale=scale, causal=False, block_q=bq,
                                   block_k=bk, interpret=interpret)

        def diag(_):
            return flash_bwd_block(q, k_cur, v_cur, mk_cur, do, lse, delta,
                                   scale=scale, causal=True, block_q=bq,
                                   block_k=bk, interpret=interpret)

        def skip(_):
            return (jnp.zeros_like(q, dtype=jnp.float32),
                    jnp.zeros_like(k_cur, dtype=jnp.float32),
                    jnp.zeros_like(v_cur, dtype=jnp.float32))

        if not causal:
            return full(None)
        branch = jnp.int32(0) + (src <= idx) + (src < idx)
        return lax.switch(branch, [skip, diag, full], None)

    def accumulate(t, dq, k_cur, v_cur, mk_cur, dk_cur, dv_cur):
        src = (idx - t) % n
        dq_c, dk_c, dv_c = block_grads(src, k_cur, v_cur, mk_cur)
        return dq + dq_c, dk_cur + dk_c, dv_cur + dv_c

    def body(t, carry):
        dq, k_cur, v_cur, mk_cur, dk_cur, dv_cur = carry
        dq, dk_cur, dv_cur = accumulate(t, dq, k_cur, v_cur, mk_cur,
                                        dk_cur, dv_cur)
        # dk/dv ride WITH their k/v block so every device adds its
        # contribution to the right accumulator
        k_cur, v_cur, mk_cur, dk_cur, dv_cur = ring_shift(
            (k_cur, v_cur, mk_cur, dk_cur, dv_cur), axis)
        return dq, k_cur, v_cur, mk_cur, dk_cur, dv_cur

    dq0 = jnp.zeros_like(q, dtype=jnp.float32)
    dk0 = jnp.zeros_like(k, dtype=jnp.float32)
    dv0 = jnp.zeros_like(v, dtype=jnp.float32)
    # n-1 full process+rotate rounds, then the last block's accumulation
    # with a final hop of ONLY (dk, dv) — k/v/mask values would be
    # discarded after it (the same dead-transfer avoidance the forward
    # ring documents)
    dq, k_l, v_l, mk_l, dk_l, dv_l = lax.fori_loop(
        0, n - 1, body, (dq0, k, v, mask, dk0, dv0))
    dq, dk_l, dv_l = accumulate(n - 1, dq, k_l, v_l, mk_l, dk_l, dv_l)
    if n > 1:
        dk_l, dv_l = ring_shift((dk_l, dv_l), axis)
    return dq.astype(q.dtype), dk_l.astype(k.dtype), dv_l.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring_flash(q, k, v, mask, axis, causal, scale, bq, bk, interpret):
    out, _ = _ring_flash_fwd_pass(q, k, v, mask, axis, causal, scale,
                                  bq, bk, interpret)
    return out


def _ring_flash_fwd(q, k, v, mask, axis, causal, scale, bq, bk, interpret):
    out, lse = _ring_flash_fwd_pass(q, k, v, mask, axis, causal, scale,
                                    bq, bk, interpret)
    return out, (q, k, v, mask, out, lse)


def _ring_flash_bwd(axis, causal, scale, bq, bk, interpret, res, do):
    q, k, v, mask, out, lse = res
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).transpose(0, 2, 1)               # (B, H, Lq)
    dq, dk, dv = _ring_flash_bwd_pass(q, k, v, mask, lse, delta, do,
                                      axis, causal, scale, bq, bk, interpret)
    return dq, dk, dv, jnp.zeros_like(mask)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(q, k, v, axis: str, causal: bool = False,
                         scale: float | None = None, kv_mask=None,
                         block_q: int = 512, block_k: int = 1024,
                         interpret: bool | None = None):
    """Ring attention whose local block math is the Pallas flash kernel.

    Drop-in for :func:`ring_attention` (same contract: call inside
    `jax.shard_map` with the sequence dim sharded over ``axis``); the
    difference is WHERE the block scores live — flash keeps each
    (Lq, Lk_block) tile in VMEM instead of materializing it in HBM, and
    entirely-future causal blocks are skipped without launching a kernel.
    On-chip kernel evidence: BASELINE.md §attention (3.1×/4.1× vs XLA dense
    at L = 1k/4k on v5e)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    mask = (kv_mask if kv_mask is not None
            else jnp.ones_like(k[..., 0, 0]))
    mask = mask.astype(jnp.float32)
    return _ring_flash(q, k, v, mask, axis, causal, scale,
                       block_q, block_k, interpret)
