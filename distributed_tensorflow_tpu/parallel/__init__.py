"""Parallelism layer: mesh runtime (L0), collectives (L1), the
gradient-compression codecs that shrink what the collectives carry, and
the overlap layer that hides their latency behind backward compute."""

from distributed_tensorflow_tpu.parallel import (  # noqa: F401
    collectives, compression, mesh, overlap)
