"""Parallelism layer: mesh runtime (L0) and collectives (L1)."""

from distributed_tensorflow_tpu.parallel import collectives, mesh  # noqa: F401
