"""Parallelism layer: mesh runtime (L0), collectives (L1), and the
gradient-compression codecs that shrink what the collectives carry."""

from distributed_tensorflow_tpu.parallel import (  # noqa: F401
    collectives, compression, mesh)
