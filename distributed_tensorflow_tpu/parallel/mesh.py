"""L0 mesh/runtime: device discovery and Mesh construction.

TPU-native replacement for the reference's process/cluster bootstrap:
`multiprocessing.Process` spawning (reference initializer.py:134-145,
169-173), the TF_CONFIG cluster env (reference dist_keras.py:70-75), and the
`-tt server|worker -ti I -sa ADDR` multi-machine role dispatch (reference
initializer.py:147-155).  On TPU a "node" is a device on a
`jax.sharding.Mesh`; one Python process per host drives all local devices,
and multi-host pods coordinate through `jax.distributed.initialize` instead
of hand-rolled TCP.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical mesh axis names used throughout the framework.
DATA_AXIS = "data"      # data parallelism (the reference's only axis)
MODEL_AXIS = "model"    # tensor parallelism
SEQ_AXIS = "seq"        # sequence/context parallelism (ring attention)
PIPE_AXIS = "pipe"      # pipeline parallelism
EXPERT_AXIS = "expert"  # expert parallelism (MoE)


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def create_mesh(
    n_devices: int | None = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    shape: Sequence[int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh over the first ``n_devices`` devices.

    ``n_devices`` plays the role of the reference's ``-n`` flag
    (reference initializer.py:83-85), but counts TPU devices instead of
    spawned processes.  With ``shape`` a multi-axis mesh (e.g. ``(4, 2)``
    over ``("data", "model")``) is built; otherwise a 1-D mesh over
    ``axis_names[0]``.
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is None:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(
            f"requested {n_devices} devices but only {len(devs)} available; "
            f"for CPU testing set XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}"
        )
    devs = devs[:n_devices]
    if shape is None:
        shape = (n_devices,)
        axis_names = tuple(axis_names[:1])
    else:
        shape = tuple(shape)
        axis_names = tuple(axis_names)
        prod = 1
        for s in shape:
            prod *= s
        if prod != n_devices:
            raise ValueError(f"mesh shape {shape} does not cover {n_devices} devices")
    import numpy as np

    return Mesh(np.asarray(devs).reshape(shape), axis_names)


def multihost_initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join a multi-host pod.

    TPU-native equivalent of the reference's multi-machine launch
    (``-tt server|worker -ti I -sa ADDR``, reference initializer.py:147-155):
    instead of one hand-rolled TCP parameter server plus N clients, every
    host calls this and then runs the *same* SPMD program; XLA routes tensor
    traffic over ICI/DCN.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def host_to_global(arr, sharding: NamedSharding):
    """Place a host array (same values on every process) onto a mesh.

    Single-process: plain device_put.  Multi-process: device_put rejects
    shardings spanning non-addressable devices, so build the global array
    via make_array_from_callback — each process serves exactly its
    addressable shards from its host copy (the multi-host rendering of the
    reference's per-worker dataset shard, reference initializer.py:44).
    """
    import numpy as np

    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(
        np.shape(arr), sharding, lambda idx: arr[idx])


def local_to_global(arr, sharding: NamedSharding):
    """Place a PROCESS-LOCAL array as this process's portion of a global
    array (each process contributes different rows — the multi-host input
    sharding the reference gets from per-worker `.shard(n_nodes, index)`,
    reference initializer.py:44).  Contrast `host_to_global`, which assumes
    every process holds the same full array."""
    import numpy as np

    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(arr))


def state_to_global(tree, shardings):
    """Place a pytree of device values (identical on every process) onto the
    mesh with the given sharding(s).

    Single-process: plain device_put.  Multi-process: a jit identity with
    ``out_shardings`` — jit treats the process-local inputs as replicated
    global values and emits the resharding, which device_put cannot do for
    non-addressable devices.  Handles typed PRNG-key leaves, unlike
    make_array_from_callback.
    """
    if jax.process_count() == 1:
        return jax.device_put(tree, shardings)
    return jax.jit(lambda s: s, out_shardings=shardings)(tree)


def data_sharding(mesh: Mesh, ndim: int = 1, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding for a batch: leading dim split over the data axis."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def per_device_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding for per-device state stacks (leading axis == mesh axis size)."""
    return NamedSharding(mesh, P(axis))


def kv_slot_sharding(mesh: Mesh, ndim: int, *,
                     shard_heads: bool = False,
                     head_dim_index: int = 2) -> NamedSharding:
    """Sharding for a serving KV-slot buffer (serving/kv_cache.py).

    The canonical leaf is ``(slots, max_len, kv_heads, head_dim)``: the
    slot dim splits over ``data`` (each data shard owns a contiguous block
    of request slots — the serving analogue of batch parallelism), and
    with ``shard_heads`` the kv-head dim additionally splits over
    ``model`` (the tensor-parallel head layout of
    engines/tensor_parallel.py, so a TP-trained model's cache lives where
    its QKV projections already are).  ``ndim < head_dim_index + 1``
    leaves (per-slot length/active vectors) shard the slot dim only.
    Axes absent from the mesh replicate."""
    spec = [None] * ndim
    if ndim and DATA_AXIS in mesh.axis_names:
        spec[0] = DATA_AXIS
    if shard_heads and MODEL_AXIS in mesh.axis_names \
            and ndim > head_dim_index:
        spec[head_dim_index] = MODEL_AXIS
    return NamedSharding(mesh, P(*spec))


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh request, resolvable on real TPUs or the CPU fake mesh."""

    n_devices: int | None = None
    axis_names: tuple[str, ...] = (DATA_AXIS,)
    shape: tuple[int, ...] | None = None

    def build(self) -> Mesh:
        return create_mesh(self.n_devices, self.axis_names, self.shape)


def fake_cpu_env(n: int = 8) -> dict[str, str]:
    """Env vars that make JAX expose ``n`` CPU devices (the SPMD analogue of
    the reference's fork-based fake cluster, reference initializer.py:134-145).

    Must be set before the first ``import jax`` in the target process.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    return {
        "JAX_PLATFORM_NAME": "cpu",
        "JAX_PLATFORMS": "",
        "XLA_FLAGS": f"{flags} --xla_force_host_platform_device_count={n}".strip(),
    }
