"""Gradient-compression codecs: reduced-precision cross-device sync.

The paper's whole subject is the cost of exchanging gradients between
workers; in this TPU-native port that exchange is the per-step
`pmean`/`reduce_scatter` payload the tracer's ``collective_profile``
measures.  This module makes that payload a knob — three codecs behind one
interface, selected by ``--grad-compression {none,bf16,int8}``:

* ``none``  — bitwise-identical passthrough: every collective delegates
  verbatim to :mod:`parallel.collectives`, so the compiled program is the
  same HLO as before the codec existed.
* ``bf16``  — cast to bfloat16 for the exchange: the wire carries
  2 bytes/param instead of 4, and the ring reduction itself runs in bf16
  (the result is widened back to f32 for the optimizer only AFTER the
  collective — nothing widens the in-flight accumulation, the standard
  trade of the production bf16-gradient-allreduce trick).
* ``int8``  — per-leaf max-abs scale + stochastic rounding to int8
  (1 byte/param + one f32 scale per leaf on the wire); f32 master params
  are untouched — only the exchanged value is quantized.  The reduction
  is the standard two-phase compressed allreduce (see
  :class:`Int8Codec`), so per-device traffic is genuinely ~¼ of the
  uncompressed ring allreduce at any device count.  Stochastic rounding
  makes the quantizer unbiased in expectation (the 1-bit-SGD /
  error-feedback lineage's prerequisite), verified in
  tests/test_compression.py.

Two application modes, matching how each engine owns its collective:

* **Explicit collectives** (the shard_map engines — sync DP's gradient
  psum, async local-SGD's periodic parameter ``pmean``, gossip's
  ``neighbor_mean``): the codec wraps the collective itself —
  ``all_reduce_sum``/``all_reduce_mean``/``neighbor_mean`` below encode on
  the sending device, move the compressed representation through the XLA
  collective (bf16 psum / int8 all_to_all+all_gather / int8 ppermute),
  and decode on the receiving side.  The compressed dtype is what
  crosses ICI.
* **Compiler-inserted collectives** (the GSPMD engines — fsdp's
  reduce-scatter, tensor-parallel/composite/expert's data-axis
  all-reduce): XLA owns the collective, so the codec applies
  ``roundtrip`` — quantize→dequantize on the gradient straight after AD —
  which reproduces the *numerics* of a compressed exchange (identical
  quantization error on every replica) while the collective itself still
  moves the original dtype.  ``Engine.grad_collective_bytes`` reports the
  codec's payload accounting in both modes; on these engines it is the
  accounting figure, not the executed transfer (the engine docstrings and
  README say which mode applies where).

All collective wrappers must be called inside a shard_map-mapped function
over the named axis, like their :mod:`parallel.collectives` counterparts
(``jax.vmap`` with an ``axis_name`` emulates them for tests).
"""

from __future__ import annotations

from typing import Any, Iterable

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_tpu.parallel import collectives as coll

PyTree = Any

CODECS = ("none", "bf16", "int8")


def _numel(shape) -> int:
    """Element count of a shape tuple — the one place the wire-bytes
    accounting multiplies dimensions."""
    size = 1
    for d in shape:
        size *= int(d)
    return size

# fold tag separating the codec's rounding stream from every other
# consumer of an engine's step rng ("comp" in ASCII) — engines derive
# their key via codec_rng() so the derivation lives in ONE place
_RNG_TAG = 0x636F6D70


def _axis_size(axis: str) -> int:
    """Static mesh-axis size inside a mapped function.  ``lax.axis_size``
    where it exists; ``psum(1, axis)`` (constant-folded to the static
    size) on older jax — this module must import-and-run on containers
    whose jax predates the engine layer's floor, because the codec math
    itself is exercised there via ``vmap`` axis emulation."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis_name=axis)


def codec_rng(rng: jax.Array) -> jax.Array:
    """The codec's rounding key for a step, derived from the engine's step
    rng.  Engines pass a per-DEVICE rng when each device quantizes its own
    local value (sync grads, async/gossip params — independence is what
    averages the rounding noise out), and an axis-INVARIANT rng when the
    quantized value is replicated (the GSPMD roundtrip — a per-device key
    would silently diverge the replicas)."""
    return jax.random.fold_in(rng, _RNG_TAG)


def _leaf_rngs(tree: PyTree, rng):
    """One independent key per leaf (same traversal order as tree.map), or
    all-None when no rng was provided (deterministic rounding)."""
    leaves, treedef = jax.tree.flatten(tree)
    if rng is None:
        return jax.tree.unflatten(treedef, [None] * len(leaves))
    return jax.tree.unflatten(
        treedef, [jax.random.fold_in(rng, i) for i in range(len(leaves))])


class GradCodec:
    """``none``: bitwise passthrough.  Base class of the real codecs —
    every method here delegates verbatim to :mod:`parallel.collectives`
    (or is the identity), so engines can call the codec unconditionally
    and the default compiles to exactly the pre-codec program."""

    name = "none"

    # ------------------------------------------------------------- payload
    def leaf_wire_bytes(self, shape, dtype) -> int:
        """Bytes this leaf occupies on the wire (one collective round)."""
        size = _numel(shape)
        return size * jnp.dtype(dtype).itemsize

    def wire_bytes(self, leaves: Iterable[Any]) -> int:
        """Total wire payload of one collective round over ``leaves``
        (anything with ``.shape``/``.dtype`` — concrete or abstract)."""
        return int(sum(self.leaf_wire_bytes(a.shape, a.dtype)
                       for a in leaves))

    # --------------------------------------------------------- collectives
    def all_reduce_sum(self, tree: PyTree, axis: str, *, rng=None) -> PyTree:
        del rng
        return coll.all_reduce_sum(tree, axis)

    def all_reduce_mean(self, tree: PyTree, axis: str, *, rng=None) -> PyTree:
        del rng
        return coll.all_reduce_mean(tree, axis)

    def neighbor_mean(self, tree: PyTree, axis: str, degree: int = 1, *,
                      rng=None) -> PyTree:
        del rng
        return coll.neighbor_mean(tree, axis, degree)

    # ----------------------------------------------------- GSPMD roundtrip
    def roundtrip(self, tree: PyTree, *, rng=None) -> PyTree:
        """Quantize→dequantize each leaf in place (no collective): the
        numerics of a compressed exchange for engines whose collective is
        compiler-inserted.  Identity here."""
        del rng
        return tree


class Bf16Codec(GradCodec):
    """Cast to bfloat16 for the exchange; the collective — including the
    ring reduction's in-flight additions — runs in bf16, and the result
    is widened back to float32 only after it.

    Only floating leaves wider than 2 bytes are cast; anything already
    bf16/f16 (or integral) passes through at its own width."""

    name = "bf16"

    @staticmethod
    def _compressible(dtype) -> bool:
        dtype = jnp.dtype(dtype)
        return (jnp.issubdtype(dtype, jnp.floating)
                and dtype.itemsize > 2)

    def leaf_wire_bytes(self, shape, dtype) -> int:
        size = _numel(shape)
        if self._compressible(dtype):
            return size * 2
        return size * jnp.dtype(dtype).itemsize

    def _through(self, tree, fn):
        """Run ``fn`` on the bf16 rendering of each compressible leaf; the
        collective inside ``fn`` then moves (and accumulates) bf16 — the
        wire dtype IS the compressed dtype — and the result is widened
        back to the leaf's original dtype."""
        def leaf(x):
            if self._compressible(x.dtype):
                return fn(x.astype(jnp.bfloat16)).astype(x.dtype)
            return fn(x)

        return jax.tree.map(leaf, tree)

    def all_reduce_sum(self, tree, axis, *, rng=None):
        del rng
        return self._through(tree, lambda x: lax.psum(x, axis_name=axis))

    def all_reduce_mean(self, tree, axis, *, rng=None):
        del rng
        return self._through(tree, lambda x: lax.pmean(x, axis_name=axis))

    def neighbor_mean(self, tree, axis, degree=1, *, rng=None):
        del rng
        return self._through(
            tree, lambda x: coll.neighbor_mean(x, axis, degree))

    def roundtrip(self, tree, *, rng=None):
        del rng
        return self._through(tree, lambda x: x)


def _int8_encode(x: jax.Array, rng) -> tuple[jax.Array, jax.Array]:
    """(q, scale): per-leaf max-abs scale, values stochastically rounded
    to int8 in [-127, 127].  With ``rng`` the rounding is stochastic —
    E[q·scale] == x exactly (floor(v + u), u ~ U[0,1)) — so quantization
    noise averages out across devices/steps instead of biasing the
    descent direction; without ``rng`` it rounds to nearest."""
    x32 = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(x32)) / 127.0
    scale = jnp.maximum(scale, jnp.finfo(jnp.float32).tiny)
    v = x32 / scale
    if rng is None:
        q = jnp.round(v)
    else:
        q = jnp.floor(v + jax.random.uniform(rng, x.shape, jnp.float32))
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def _int8_decode(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)


def int8_channel_encode(x: jax.Array,
                        axis: int = -1) -> tuple[jax.Array, jax.Array]:
    """(q, scale): max-abs scale reduced over ``axis`` (one f32 scale per
    remaining index), values rounded TO NEAREST into int8 [-127, 127].

    The serving KV cache's quantizer (serving/kv_cache.py kv_dtype=int8):
    each written K/V vector gets its own scale — per slot × position ×
    head, reduced over head_dim — so a write never has to requantize
    older cache entries, and decoding is deterministic (no stochastic
    rounding: a served token stream must be a pure function of the
    params + prompt, the same rule as greedy sampling)."""
    x32 = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(x32), axis=axis) / 127.0
    scale = jnp.maximum(scale, jnp.finfo(jnp.float32).tiny)
    q = jnp.clip(jnp.round(x32 / jnp.expand_dims(scale, axis)),
                 -127, 127).astype(jnp.int8)
    return q, scale


def int8_channel_decode(q: jax.Array, scale: jax.Array, dtype,
                        axis: int = -1) -> jax.Array:
    """Inverse of :func:`int8_channel_encode` (broadcasts the scale back
    over ``axis``)."""
    return (q.astype(jnp.float32)
            * jnp.expand_dims(scale, axis).astype(jnp.float32)).astype(dtype)


class Int8Codec(GradCodec):
    """Per-leaf scale + stochastic rounding to int8; f32 master values
    preserved (only the exchanged copy is quantized).

    The reduce is the standard two-phase compressed allreduce (the
    1-bit-SGD-lineage layout): each leaf is split into one chunk per
    device; phase 1 quantizes the local value and ``all_to_all``s the
    int8 chunks so device *i* can sum everyone's dequantized chunk *i*
    (per-device scales ride a scalar all-gather, so Σ qⱼ·sⱼ keeps each
    sender's scale exact — an int8-domain sum would need one global
    scale and would overflow at 8 summands); phase 2 re-quantizes the
    reduced chunk and ``all_gather``s it back.  Both phases move int8, so
    per-device traffic is ~2·(n-1)/n · size/4 bytes — the uncompressed
    ring allreduce's bandwidth shape at ¼ the bytes, at ANY device count
    (a naive gather-of-everything would scale received bytes with n and
    lose the win beyond n=8).  Transient memory is one extra f32 copy of
    the leaf (the (n, size/n) dequant buffer).  The reduced value passes
    through TWO stochastic roundings (each unbiased, so the composition
    is too); decoded error per element is bounded by Σⱼ sⱼ + s₂ — one
    quantum per sender plus one for the re-quantized sum."""

    name = "int8"

    @staticmethod
    def _compressible(dtype) -> bool:
        dtype = jnp.dtype(dtype)
        return jnp.issubdtype(dtype, jnp.floating) and dtype.itemsize > 1

    def leaf_wire_bytes(self, shape, dtype) -> int:
        size = _numel(shape)
        if self._compressible(dtype):
            return size + 4  # int8 payload + one f32 scale per leaf
        return size * jnp.dtype(dtype).itemsize

    def _reduce(self, tree, axis, rng, mean: bool):
        n = _axis_size(axis)

        def leaf(x, key):
            if not self._compressible(x.dtype):
                red = lax.pmean if mean else lax.psum
                return red(x, axis_name=axis)
            size = x.size
            m = -(-size // n)  # chunk length (ceil; zero-padded tail)
            flat = jnp.pad(x.reshape(-1).astype(jnp.float32),
                           (0, n * m - size))
            # phase 1: quantize the whole local leaf once (one scale),
            # all_to_all the int8 chunks — device i receives chunk i of
            # every sender
            q, s = _int8_encode(flat.reshape(n, m), key)
            qx = lax.all_to_all(q, axis_name=axis, split_axis=0,
                                concat_axis=0)               # (n, m) int8
            sg = lax.all_gather(s, axis_name=axis)           # (n,) f32
            chunk = (qx.astype(jnp.float32) * sg[:, None]).sum(axis=0)
            # phase 2: re-quantize the reduced chunk, share it back
            q2, s2 = _int8_encode(
                chunk, None if key is None else jax.random.fold_in(key, 1))
            qg = lax.all_gather(q2, axis_name=axis)          # (n, m) int8
            sg2 = lax.all_gather(s2, axis_name=axis)         # (n,) f32
            total = (qg.astype(jnp.float32) * sg2[:, None]).reshape(-1)
            total = total[:size].reshape(x.shape)
            if mean:
                total = total / n
            return total.astype(x.dtype)

        return jax.tree.map(leaf, tree, _leaf_rngs(tree, rng))

    def all_reduce_sum(self, tree, axis, *, rng=None):
        return self._reduce(tree, axis, rng, mean=False)

    def all_reduce_mean(self, tree, axis, *, rng=None):
        return self._reduce(tree, axis, rng, mean=True)

    def neighbor_mean(self, tree, axis, degree=1, *, rng=None):
        if degree <= 0:
            return tree
        n = _axis_size(axis)
        if 2 * degree + 1 >= n:
            # whole-ring neighborhood — same degenerate case as the
            # uncompressed mix (collectives.neighbor_mean)
            return self.all_reduce_mean(tree, axis, rng=rng)

        def leaf(x, key):
            if not self._compressible(x.dtype):
                return coll.neighbor_mean(x, axis, degree)
            q, s = _int8_encode(x, key)
            acc = _int8_decode(q, s, jnp.float32)
            for d in range(1, degree + 1):
                fwd = [(i, (i + d) % n) for i in range(n)]
                bwd = [(i, (i - d) % n) for i in range(n)]
                for perm in (fwd, bwd):
                    # neighbors receive the int8 rendering + scale — the
                    # ring hop moves 1 byte/param, like the reductions
                    qp = lax.ppermute(q, axis_name=axis, perm=perm)
                    sp = lax.ppermute(s, axis_name=axis, perm=perm)
                    acc = acc + _int8_decode(qp, sp, jnp.float32)
            return (acc / (2 * degree + 1)).astype(x.dtype)

        return jax.tree.map(leaf, tree, _leaf_rngs(tree, rng))

    def roundtrip(self, tree, *, rng=None):
        def leaf(x, key):
            if not self._compressible(x.dtype):
                return x
            q, s = _int8_encode(x, key)
            return _int8_decode(q, s, x.dtype)

        return jax.tree.map(leaf, tree, _leaf_rngs(tree, rng))


_CODEC_CLASSES = {c.name: c for c in (GradCodec, Bf16Codec, Int8Codec)}


def codec_active(codec: GradCodec) -> bool:
    """True when the codec changes the collective program: a real
    compression codec, or the 'none' passthrough wrapped in bucketing
    (parallel/overlap.BucketedCodec — bucketed-none still replaces the
    monolithic exchange with per-bucket collectives the latency-hiding
    scheduler can overlap).  Engines branch on this instead of
    ``codec.name != 'none'`` wherever bucketing alone must activate the
    explicit-collective step."""
    return codec.name != "none" or bool(getattr(codec, "bucketed", False))


def make_codec(compression: str | GradCodec | None) -> GradCodec:
    """Resolve a ``--grad-compression`` value (or a ready codec instance)
    to a :class:`GradCodec`."""
    if compression is None:
        return GradCodec()
    if isinstance(compression, GradCodec):
        return compression
    try:
        return _CODEC_CLASSES[compression]()
    except KeyError:
        raise ValueError(
            f"unknown grad_compression '{compression}'; "
            f"known: {', '.join(CODECS)}") from None
