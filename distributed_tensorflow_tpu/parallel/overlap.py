"""Communication/compute overlap: bucketed gradient collectives.

PR 3 (parallel/compression.py) cut collective *bytes*; this layer attacks
collective *latency* — the serialized tail where the data-parallel engines
sit idle waiting for the gradient exchange after the whole backward pass.
Three pieces, composed:

* **Bucketing** (:func:`plan_buckets` / :class:`BucketedCodec`): the grad
  pytree is partitioned into size-targeted buckets (``--grad-bucket-mb``,
  ~4 MB by default at the API level) in REVERSE flatten order — the
  flatten order tracks the forward pass, so its reverse approximates the
  order backward produces gradients, meaning the first buckets become
  data-ready earliest in the backward.  Each bucket's collective depends
  only on ITS slice of the backward, so XLA's latency-hiding scheduler
  (enabled by the flags ``utils/harness.enable_overlap_flags`` sets) can
  issue bucket k's exchange while the backward for bucket k+1 is still
  computing — instead of one monolithic all-reduce that depends on every
  gradient at once.  The partition is exact (every leaf element covered
  once), deterministic (a pure function of the leaves' shapes/dtypes, so
  every process of a pod plans identically), and splits leaves larger
  than the target across buckets.

* **Codec composition**: :class:`BucketedCodec` wraps a PR 3 codec and
  applies it per BUCKET instead of per leaf — one int8 scale per ~4 MB
  bucket rather than one per (possibly tiny) leaf, with the wire-byte
  accounting scaled the same way (``Engine.grad_collective_bytes`` stays
  honest: int8 overhead is 4 B × n_buckets, not 4 B × n_leaves).

* **Microbatch independence** (``--grad-accum`` K > 1): the sync engine's
  accumulation scan moves the bucketed reduce INSIDE the scan body when
  bucketing is on (engines/sync.py), so microbatch i's exchange is
  data-independent of microbatch i+1's backward — the scheduler can run
  them concurrently.  The GSPMD engines' accumulation
  (base.gspmd_grad_accum) already has this shape: each scan iteration
  carries its own compiler-inserted reduce.

Opt-in like every prior optimisation: ``--grad-bucket-mb 0`` (the
default) leaves the codec unwrapped and every engine compiles its exact
pre-overlap program.

The **probe** (:func:`probe_engine_overlap`) closes the measurement loop:
it times the engine's full step, a collective-free twin, and the
collective alone, and splits the difference into ``exposed_s`` (collective
seconds still on the critical path) vs ``hidden_s`` (collective seconds
the schedule buried under compute).  ``exposed_s`` is the number the
run report / bench emit as ``grad_collective_exposed_s`` and ``analyze
diff`` gates lower-is-better (BASELINE.md): the MLPerf way — report the
time, then make it disappear.
"""

from __future__ import annotations

import statistics
import time
from typing import Any, Iterable, NamedTuple

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.parallel import compression

PyTree = Any

# size target of one gradient bucket when a caller asks for bucketing
# without naming a size — ~4 MB balances per-collective launch overhead
# against scheduling granularity (too-small buckets drown in dispatch
# cost, too-large ones serialize like the monolithic reduce)
DEFAULT_BUCKET_MB = 4.0


class Slice(NamedTuple):
    """One contiguous run of a flattened leaf: elements
    ``[start, stop)`` of ``leaves[leaf].reshape(-1)``."""

    leaf: int
    start: int
    stop: int


class Bucket(NamedTuple):
    """One collective unit: same-dtype slices totalling ``size`` elements
    (≤ the byte target, except when a single slice alone exceeds it —
    never: slices are cut to fit, so a bucket only exceeds the target when
    the target is under one element)."""

    dtype: Any
    size: int
    slices: tuple[Slice, ...]


def plan_buckets(leaves: Iterable[Any], bucket_bytes: int) -> tuple[Bucket, ...]:
    """Partition ``leaves`` (anything with ``.shape``/``.dtype``) into
    size-targeted buckets in REVERSE leaf order (see module docstring).

    Invariants (tested in tests/test_overlap.py):
      * exact: every element of every non-empty leaf appears in exactly
        one slice of exactly one bucket;
      * deterministic: a pure function of the leaves' (shape, dtype)
        sequence — identical on every process of a pod;
      * single-dtype buckets (the collective/codec runs one dtype per
        bucket; a dtype change closes the current bucket);
      * bucket payload ≤ ``bucket_bytes`` (leaves larger than the target
        are split across buckets at element granularity).
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be > 0, got {bucket_bytes}")
    leaves = list(leaves)
    buckets: list[Bucket] = []
    cur: list[Slice] = []
    cur_dtype: Any = None
    cur_size = 0

    def close() -> None:
        nonlocal cur, cur_size
        if cur:
            buckets.append(Bucket(dtype=cur_dtype, size=cur_size,
                                  slices=tuple(cur)))
        cur, cur_size = [], 0

    for idx in reversed(range(len(leaves))):
        leaf = leaves[idx]
        dtype = jnp.dtype(leaf.dtype)
        n = 1
        for d in leaf.shape:
            n *= int(d)
        if n == 0:
            continue  # empty leaf: nothing to exchange
        # capacity in ELEMENTS of this dtype; at least 1 so a target
        # below one element still makes (single-element) progress
        cap = max(bucket_bytes // max(dtype.itemsize, 1), 1)
        if cur and cur_dtype != dtype:
            close()
        cur_dtype = dtype
        start = 0
        while start < n:
            if cur_size >= cap:
                close()
            take = min(cap - cur_size, n - start)
            cur.append(Slice(idx, start, start + take))
            cur_size += take
            start += take
    close()
    return tuple(buckets)


def pack_buckets(leaves: list[Any], plan: tuple[Bucket, ...]) -> list[jax.Array]:
    """One flat 1-D array per bucket, concatenating its slices in plan
    order.  Pure reshape/slice/concat — no value changes, so packing
    followed by :func:`unpack_buckets` is bitwise identity."""
    flats: dict[int, jax.Array] = {}

    def flat(i: int) -> jax.Array:
        if i not in flats:
            flats[i] = jnp.reshape(leaves[i], (-1,))
        return flats[i]

    out = []
    for b in plan:
        parts = [flat(s.leaf)[s.start:s.stop] for s in b.slices]
        out.append(parts[0] if len(parts) == 1 else jnp.concatenate(parts))
    return out


def unpack_buckets(bucket_arrays: list[Any], plan: tuple[Bucket, ...],
                   leaves: list[Any]) -> list[Any]:
    """Inverse of :func:`pack_buckets`: reassemble each leaf from its
    bucket slices.  Leaves the plan skipped (empty) pass through from
    ``leaves`` unchanged."""
    pieces: dict[int, list[tuple[int, Any]]] = {}
    for b, arr in zip(plan, bucket_arrays):
        off = 0
        for s in b.slices:
            ln = s.stop - s.start
            pieces.setdefault(s.leaf, []).append((s.start, arr[off:off + ln]))
            off += ln
    new = list(leaves)
    for i, segs in pieces.items():
        segs.sort(key=lambda t: t[0])
        flat = segs[0][1] if len(segs) == 1 else jnp.concatenate(
            [p for _, p in segs])
        new[i] = jnp.reshape(flat, leaves[i].shape)
    return new


class BucketedCodec(compression.GradCodec):
    """A PR 3 codec applied per BUCKET instead of per leaf.

    Wraps any :class:`compression.GradCodec`: every collective (and the
    GSPMD ``roundtrip``) packs the tree into the deterministic bucket
    plan, runs the inner codec over the bucket list (a pytree — the inner
    codec's per-leaf machinery, including its per-leaf rng derivation and
    int8 scales, becomes per-BUCKET machinery for free), and unpacks.
    ``wire_bytes`` is scaled the same way, keeping the engines'
    wire-vs-raw accounting honest once bucketing lands (int8: one 4-byte
    scale per bucket, not per leaf).

    ``name`` stays the INNER codec's name so telemetry
    (``grad_compression`` fields) keeps one vocabulary; ``bucketed`` /
    ``bucket_mb`` mark the wrapper for engines and reports."""

    bucketed = True

    def __init__(self, inner: compression.GradCodec,
                 bucket_mb: float = DEFAULT_BUCKET_MB):
        if getattr(inner, "bucketed", False):
            raise ValueError("codec is already bucketed")
        if not bucket_mb or bucket_mb < 0:
            raise ValueError(
                f"grad_bucket_mb must be > 0 to bucket (0 disables "
                f"bucketing entirely), got {bucket_mb}")
        self.inner = inner
        self.bucket_bytes = max(int(round(bucket_mb * (1 << 20))), 1)
        self._plans: dict[tuple, tuple[Bucket, ...]] = {}

    @property
    def name(self) -> str:  # type: ignore[override]
        return self.inner.name

    @property
    def bucket_mb(self) -> float:
        return self.bucket_bytes / (1 << 20)

    # ------------------------------------------------------------- plans
    def plan_for(self, leaves: list[Any]) -> tuple[Bucket, ...]:
        """The (cached) bucket plan for this leaf structure — keyed by
        shapes+dtypes only, so tracers and concrete arrays share plans
        and every process plans identically."""
        key = tuple((tuple(leaf.shape), str(jnp.dtype(leaf.dtype)))
                    for leaf in leaves)
        plan = self._plans.get(key)
        if plan is None:
            plan = plan_buckets(leaves, self.bucket_bytes)
            self._plans[key] = plan
        return plan

    def plan_for_tree(self, tree: PyTree) -> tuple[Bucket, ...]:
        return self.plan_for(jax.tree.leaves(tree))

    def _through(self, tree: PyTree, op) -> PyTree:
        leaves, treedef = jax.tree.flatten(tree)
        plan = self.plan_for(leaves)
        out = op(pack_buckets(leaves, plan))
        return jax.tree.unflatten(treedef, unpack_buckets(out, plan, leaves))

    # ----------------------------------------------------------- payload
    def leaf_wire_bytes(self, shape, dtype) -> int:
        # per-leaf wire attribution is ill-posed under bucketing: the
        # per-bucket overhead (e.g. int8's one scale per BUCKET) belongs
        # to leaves jointly, so any per-leaf number would not sum to
        # wire_bytes(leaves) — the exact dishonesty this wrapper removes.
        # Refuse rather than mislead.
        raise NotImplementedError(
            "BucketedCodec has no per-leaf wire accounting (bucket "
            "overhead is shared across leaves) — use wire_bytes(leaves) "
            "over the full gradient tree")

    def wire_bytes(self, leaves: Iterable[Any]) -> int:
        plan = self.plan_for(list(leaves))
        return int(sum(self.inner.leaf_wire_bytes((b.size,), b.dtype)
                       for b in plan))

    # ------------------------------------------------------- collectives
    def all_reduce_sum(self, tree, axis, *, rng=None):
        return self._through(
            tree, lambda b: self.inner.all_reduce_sum(b, axis, rng=rng))

    def all_reduce_mean(self, tree, axis, *, rng=None):
        return self._through(
            tree, lambda b: self.inner.all_reduce_mean(b, axis, rng=rng))

    def neighbor_mean(self, tree, axis, degree=1, *, rng=None):
        return self._through(
            tree, lambda b: self.inner.neighbor_mean(b, axis, degree,
                                                     rng=rng))

    def roundtrip(self, tree, *, rng=None):
        return self._through(
            tree, lambda b: self.inner.roundtrip(b, rng=rng))


def make_overlap_codec(grad_compression, grad_bucket_mb: float
                       ) -> compression.GradCodec:
    """Resolve (--grad-compression, --grad-bucket-mb) to one codec:
    the plain PR 3 codec at bucket 0 (bitwise pre-overlap programs), the
    bucketed wrapper otherwise."""
    codec = compression.make_codec(grad_compression)
    if grad_bucket_mb:
        codec = BucketedCodec(codec, grad_bucket_mb)
    return codec


class ProbeLocalCodec(compression.GradCodec):
    """Probe-only codec: every collective is elided (identity), so a step
    built with it is the engine's COMPUTE-ONLY twin — same backward, same
    optimizer, no gradient exchange.  Results are numerically wrong
    across devices and must be discarded; the probe times it, nothing
    else."""

    name = "probe_local"

    def all_reduce_sum(self, tree, axis, *, rng=None):
        del axis, rng
        return tree

    def all_reduce_mean(self, tree, axis, *, rng=None):
        del axis, rng
        return tree

    def neighbor_mean(self, tree, axis, degree=1, *, rng=None):
        del axis, degree, rng
        return tree


# --------------------------------------------------------------- probing

def overlap_split(full_s: float, compute_s: float,
                  collective_s: float) -> dict[str, float]:
    """Split measured step times into exposed vs hidden collective
    seconds.

    * ``exposed_s``   = full − compute: collective seconds still on the
      critical path (what a perfect overlap drives to 0);
    * ``hidden_s``    = collective − exposed (floored at 0): collective
      seconds the schedule ran concurrently with compute;
    * ``serialized_step_s`` = compute + collective: what the step would
      cost with the exchange fully serialized — the baseline the
      acceptance criterion compares ``exposed_s`` against.
    """
    exposed = max(full_s - compute_s, 0.0)
    hidden = max(collective_s - exposed, 0.0)
    return {
        "full_step_s": full_s,
        "compute_s": compute_s,
        "collective_s": collective_s,
        "exposed_s": exposed,
        "hidden_s": hidden,
        "serialized_step_s": compute_s + collective_s,
        "exposed_frac": (exposed / collective_s if collective_s > 0
                         else 0.0),
    }


def _copy_state(tree: PyTree) -> PyTree:
    """Device copies of every array leaf: probe steps donate their input
    state, so each timed program gets its own buffers and the caller's
    state survives the probe untouched."""
    return jax.tree.map(
        lambda x: x.copy() if hasattr(x, "copy") else x, tree)


def _blocked(out) -> Any:
    state = out[0] if isinstance(out, tuple) else out
    jax.block_until_ready(state)
    return state


def _time_step(fn, state, xs, ys, repeats: int) -> float:
    """Median wall seconds of ``fn(state, xs, ys)`` to real completion,
    threading the returned state (the programs donate their input)."""
    state = _blocked(fn(state, xs, ys))  # warmup: compile outside timing
    times = []
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        state = _blocked(fn(state, xs, ys))
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def _time_collective(fn, params, repeats: int) -> float:
    _blocked(fn(params))  # warmup
    times = []
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        _blocked(fn(params))
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def probe_engine_overlap(engine, xs, ys, sample_x=None, *, state=None,
                         repeats: int = 3) -> dict[str, Any] | None:
    """Measure the engine's exposed-vs-hidden collective split on one
    placed batch.

    Times three programs the engine builds (``build_overlap_probe_fns``
    — the explicit-collective engines implement it; engines whose
    collective is compiler-inserted return ``None`` and the probe
    reports unsupported): the real step, a collective-free twin
    (:class:`ProbeLocalCodec`), and the gradient collective alone over
    param-shaped values.  Returns the :func:`overlap_split` dict plus
    plan/codec context, or ``None`` when the engine has no probe.

    Costs two extra step compiles; callers gate it behind the overlap
    opt-in (``--grad-bucket-mb``) and run it once per process."""
    build = getattr(engine, "build_overlap_probe_fns", None)
    if build is None:
        return None
    fns = build()
    if not fns:
        return None
    if state is None:
        if sample_x is None:
            raise ValueError("probe_engine_overlap needs state= or "
                             "sample_x= (to init a throwaway state)")
        state = engine.init_state(jax.random.key(0), sample_x)
    params = _copy_state(state.params)
    full_s = _time_step(fns["full"], _copy_state(state), xs, ys, repeats)
    compute_s = _time_step(fns["compute"], _copy_state(state), xs, ys,
                           repeats)
    collective_s = _time_collective(fns["collective"], params, repeats)
    out: dict[str, Any] = overlap_split(full_s, compute_s, collective_s)
    codec = getattr(engine, "grad_codec", None)
    n_buckets = None
    if codec is not None and getattr(codec, "bucketed", False):
        n_buckets = len(codec.plan_for_tree(state.params))
    out.update({
        "grad_compression": getattr(codec, "name", "none"),
        "grad_bucket_mb": float(getattr(codec, "bucket_mb", 0.0) or 0.0),
        "n_buckets": n_buckets,
        "grad_accum": int(getattr(engine, "grad_accum", 1)),
        "repeats": int(repeats),
    })
    return out
