"""End-to-end mixed-precision training policies (Micikevicius et al.,
arXiv:1710.03740 — PAPERS.md).

``--dtype bfloat16`` (the model knob that predates this module) only casts
*activations*: params, grads and optimizer state stay float32, so memory,
HBM bandwidth and the gradient collectives never see the low-precision
win.  This module makes storage precision a POLICY wired once at the
engine base (the same pattern as ``grad_codec`` and ``enable_health``):

  ``f32``             everything float32 — the default, and a strict
                      no-op: no cast, no optimizer wrap, the compiled
                      step program is byte-identical to the pre-policy
                      one (acceptance-tested bitwise).
  ``bf16``            pure low precision: params stored bfloat16, compute
                      bfloat16, optimizer state bfloat16 (optax moments
                      inherit the param dtype).  Halves params AND
                      optimizer bytes; no master copy, so tiny updates
                      can round away in the bf16 add — the aggressive
                      mode, guarded by the health layer.
  ``bf16-f32master``  the paper's recipe: params stored/computed bfloat16
                      with a float32 MASTER copy kept inside the
                      optimizer state (``master_weights`` below).  The
                      optimizer updates the master; the bf16 params are
                      re-derived as ``cast(master)`` every step, so
                      updates below bf16 resolution still accumulate.
                      bf16 shares float32's exponent range, so no loss
                      scaling is needed.
  ``fp16-f32master``  float16 storage/compute + f32 master + DYNAMIC LOSS
                      SCALING: fp16's 5-bit exponent underflows small
                      backward intermediates, so the loss is multiplied
                      by a running scale before AD (engines thread the
                      traced scale out of ``opt_state`` into their loss —
                      ``Engine.supports_loss_scaling`` names the engines
                      that do), gradients are unscaled inside the
                      wrapper, and a non-finite gradient SKIPS the step
                      (master/optimizer untouched, params unchanged) and
                      backs the scale off; ``growth_interval`` consecutive
                      finite steps grow it back.  Skip accounting rides
                      the step metrics (``loss_scale`` / ``ls_skipped``)
                      through the scan, so the Trainer's anomaly policy
                      sees every handled overflow as a structured event
                      instead of a silent NaN trajectory.

Master-weights mechanics (why no engine step changes are needed): every
engine applies updates via ``optax.apply_updates(params, updates)``, which
computes ``p + u`` under numpy promotion and casts back to ``p.dtype``.
The wrapper emits ``u = cast_lp(master') − p`` in FLOAT32: low-precision
values are exactly representable in f32, so ``p + u == cast_lp(master')``
exactly and the engine's own apply lands the params on the downcast master
— the invariant ``params == cast(master)`` holds every step, making a
skipped step's emitted update exactly zero.

Wire composition: with bf16 param storage the gradients ARE bf16, so the
data-axis reduce moves 2 bytes/param with no codec — and the PR 3 codecs
compose without double-casting (``Bf16Codec`` passes ≤2-byte floats
through untouched; ``Int8Codec`` quantizes them like any float).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

PyTree = Any

POLICIES = ("f32", "bf16", "bf16-f32master", "fp16-f32master")

# per-step metric keys the scaling wrap adds to the trajectory
SCALE_KEYS = ("loss_scale", "ls_skipped")


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """One resolved ``--precision`` value: the four dtypes of mixed
    precision (storage, compute, grad-reduce, master) plus the dynamic
    loss-scale shape.  ``active`` False (the ``f32`` policy) means every
    hook is a python-gated no-op — the compiled programs are the
    pre-policy ones, bitwise."""

    name: str = "f32"
    param_dtype: Any = jnp.float32    # TrainState.params storage dtype
    compute_dtype: Any = jnp.float32  # model activation/matmul dtype
    master_dtype: Any = None          # f32 master copy in opt_state (None:
                                      # no master — optimizer runs on the
                                      # stored params directly)
    loss_scaling: bool = False        # dynamic loss scale (fp16 paths)
    init_scale: float = 2.0 ** 15
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 200        # consecutive finite steps per growth

    @property
    def active(self) -> bool:
        return self.name != "f32"

    @property
    def grad_reduce_dtype(self):
        """Dtype the gradient collective moves: grads share the stored
        params' dtype, so storage dtype IS the reduce dtype."""
        return self.param_dtype

    # ----------------------------------------------------------- casting
    def cast_params(self, params: PyTree) -> PyTree:
        """Float param leaves → the policy's storage dtype (identity for
        ``f32`` — python-gated, never traced into the no-op program)."""
        if not self.active:
            return params
        dt = self.param_dtype
        return jax.tree.map(
            lambda p: p.astype(dt)
            if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating) else p,
            params)

    # --------------------------------------------------------- optimizer
    def wrap_optimizer(self, tx: optax.GradientTransformation
                       ) -> optax.GradientTransformation:
        """The whole install: master weights (+ loss scaling) around the
        engine's optimizer when the policy keeps a master, the optimizer
        untouched otherwise.  Called once from ``Engine.__init__`` —
        BEFORE ``enable_health`` wraps, so the health captures see the
        raw incoming grads and the final emitted updates."""
        if self.master_dtype is None:
            return tx
        return master_weights(tx, self)


def make_policy(precision: str | PrecisionPolicy | None) -> PrecisionPolicy:
    """Resolve a ``--precision`` value (or a ready policy) — typos fail
    here with the full menu, not deep inside an engine constructor."""
    if precision is None:
        return PrecisionPolicy()
    if isinstance(precision, PrecisionPolicy):
        return precision
    if precision in ("f32", "float32"):
        return PrecisionPolicy()
    if precision == "bf16":
        return PrecisionPolicy(name="bf16", param_dtype=jnp.bfloat16,
                               compute_dtype=jnp.bfloat16)
    if precision == "bf16-f32master":
        return PrecisionPolicy(name="bf16-f32master",
                               param_dtype=jnp.bfloat16,
                               compute_dtype=jnp.bfloat16,
                               master_dtype=jnp.float32)
    if precision == "fp16-f32master":
        return PrecisionPolicy(name="fp16-f32master",
                               param_dtype=jnp.float16,
                               compute_dtype=jnp.float16,
                               master_dtype=jnp.float32,
                               loss_scaling=True)
    raise ValueError(f"unknown precision '{precision}'; "
                     f"known: {', '.join(POLICIES)}")


# ----------------------------------------------------------- master weights

class MasterWeightsState(NamedTuple):
    """Optimizer-state node of the master-weights wrapper.  ``master`` is
    the f32 copy the inner optimizer actually updates; ``inner`` its
    state (init'd ON the master, so adam moments etc. stay f32).  The
    scale fields are constants when the policy has no loss scaling."""

    master: Any            # f32 master params (sharded like the params)
    inner: Any             # inner optimizer state over the master
    loss_scale: jax.Array  # f32 scalar — the scale the NEXT step's loss
    #                        must be multiplied by (engines read it via
    #                        loss_scale_from)
    good_steps: jax.Array  # i32 consecutive finite steps since last change
    skipped: jax.Array     # i32 total non-finite (skipped) steps
    last_skipped: jax.Array  # bool: the most recent update was skipped


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def master_weights(tx: optax.GradientTransformation,
                   policy: PrecisionPolicy) -> optax.GradientTransformation:
    """f32-master optimizer wrapper (the Micikevicius recipe as a pure
    ``optax`` transformation — no engine step changes):

    * ``init(params_lp)``: master = upcast(params), inner = tx.init(master)
      — moments and schedules run full precision over the master;
    * ``update(grads, state, params_lp)``: widen grads to the master
      dtype (unscale by ``loss_scale`` when the policy scales), update
      the MASTER, and emit ``cast_lp(master') − params`` in f32 so the
      engine's ``optax.apply_updates`` lands params exactly on the
      downcast master (module docstring for why that is exact);
    * with ``loss_scaling``: a non-finite gradient skips the whole update
      (master/inner unchanged → emitted update exactly 0), multiplies the
      scale by ``backoff_factor`` and counts the skip;
      ``growth_interval`` consecutive finite steps multiply it by
      ``growth_factor``.  All inside the jit — skip accounting stacks
      through the scan like any metric.
    """
    mdt = policy.master_dtype
    scaling = policy.loss_scaling

    def init(params):
        master = jax.tree.map(
            lambda p: p.astype(mdt) if _is_float(p) else p, params)
        return MasterWeightsState(
            master=master,
            inner=tx.init(master),
            loss_scale=jnp.asarray(policy.init_scale if scaling else 1.0,
                                   jnp.float32),
            good_steps=jnp.zeros((), jnp.int32),
            skipped=jnp.zeros((), jnp.int32),
            last_skipped=jnp.zeros((), jnp.bool_))

    def update(updates, state, params=None):
        if params is None:
            raise ValueError(
                "master_weights needs tx.update(grads, opt_state, params) — "
                "every engine in this repo passes params")
        g = jax.tree.map(
            lambda u: u.astype(mdt) if _is_float(u) else u, updates)
        if scaling:
            inv = (1.0 / state.loss_scale).astype(jnp.float32)
            g = jax.tree.map(
                lambda u: u * inv.astype(u.dtype) if _is_float(u) else u, g)
            finite = jnp.array(True)
            for leaf in jax.tree.leaves(g):
                if _is_float(leaf):
                    finite = finite & jnp.all(jnp.isfinite(leaf))
        u, inner_new = tx.update(g, state.inner, state.master)
        master_new = optax.apply_updates(state.master, u)
        if scaling:
            # non-finite grads: discard the candidate update entirely —
            # master, inner state and (via the zero emitted delta below)
            # the params stay at their pre-step values
            keep = lambda new, old: jax.tree.map(  # noqa: E731
                lambda a, b: jnp.where(finite, a, b), new, old)
            master_new = keep(master_new, state.master)
            inner_new = keep(inner_new, state.inner)
            grown = jnp.where(
                state.good_steps + 1 >= policy.growth_interval,
                state.loss_scale * policy.growth_factor, state.loss_scale)
            scale_new = jnp.where(finite, grown,
                                  state.loss_scale * policy.backoff_factor)
            # keep the scale in a sane band: growth is capped where fp16's
            # own max would make every step overflow; backoff floors at 1
            scale_new = jnp.clip(scale_new, 1.0, 2.0 ** 24)
            good_new = jnp.where(
                finite & (scale_new == state.loss_scale),
                state.good_steps + 1, jnp.zeros((), jnp.int32))
            skipped_new = state.skipped + (~finite).astype(jnp.int32)
            last_skipped = ~finite
        else:
            scale_new = state.loss_scale
            good_new = state.good_steps
            skipped_new = state.skipped
            last_skipped = state.last_skipped
        # emitted in f32: p + (cast(m') − p) == cast(m') exactly (low-
        # precision values are f32-representable), so apply_updates lands
        # the params on the downcast master — and a skipped step's delta
        # is exactly zero (params == cast(master) invariant)
        emitted = jax.tree.map(
            lambda m, p: (m.astype(p.dtype).astype(jnp.float32)
                          - p.astype(jnp.float32)) if _is_float(p)
            else jnp.zeros_like(p),
            master_new, params)
        return emitted, MasterWeightsState(
            master=master_new, inner=inner_new, loss_scale=scale_new,
            good_steps=good_new, skipped=skipped_new,
            last_skipped=jnp.asarray(last_skipped, jnp.bool_))

    return optax.GradientTransformation(init, update)


# -------------------------------------------------------- opt_state readers

def _find_master(opt_state: Any) -> list[MasterWeightsState]:
    found: list[MasterWeightsState] = []

    def visit(x):
        if isinstance(x, MasterWeightsState):
            found.append(x)
        return x

    jax.tree.map(visit, opt_state,
                 is_leaf=lambda x: isinstance(x, MasterWeightsState))
    return found


def loss_scale_from(opt_state: Any) -> jax.Array:
    """The traced loss scale the CURRENT step's loss must be multiplied
    by, read out of the (possibly nested) optimizer state.  Engines with
    ``supports_loss_scaling`` call this inside their step when the
    policy scales — python-gated, so scale-free programs never trace it."""
    masters = _find_master(opt_state)
    if not masters:
        raise ValueError(
            "no MasterWeightsState in opt_state — the loss-scaling policy "
            "must wrap the optimizer before init_state()")
    # per-device-stacked states (async/gossip) carry a stacked scalar; all
    # rows are identical, reduce with max for a plain scalar
    return jnp.max(masters[0].loss_scale).astype(jnp.float32)


def scale_stats_from(opt_state: Any) -> dict[str, jax.Array]:
    """Per-step scaling metrics merged into the trajectory by the base
    engine's precision wrap: the scale in effect after the step, and
    whether the step was skipped (non-finite grads)."""
    m = _find_master(opt_state)[0]
    return {
        "loss_scale": jnp.max(m.loss_scale).astype(jnp.float32),
        "ls_skipped": jnp.max(m.last_skipped.astype(jnp.int32)),
    }


# ------------------------------------------------- f32-checkpoint adoption

def _is_master(x) -> bool:
    return isinstance(x, MasterWeightsState)


def strip_master(opt_state: Any) -> Any:
    """The f32-era rendering of a master-policy optimizer state: every
    ``MasterWeightsState`` node replaced by its ``inner`` — exactly the
    tree an ``f32``-policy run of the same optimizer/health stack
    produces (the master wrapper is the only structural delta)."""
    return jax.tree.map(lambda x: x.inner if _is_master(x) else x,
                        opt_state, is_leaf=_is_master)


def f32_template(state: Any) -> Any:
    """An f32-policy restore template derived from a master-policy
    state: params upcast to the master dtype, the master wrapper
    stripped from the optimizer state.  Used to restore a checkpoint
    WRITTEN by an f32 run into a mixed-precision run."""
    params32 = jax.tree.map(
        lambda p: p.astype(jnp.float32) if _is_float(p) else p,
        state.params)
    return state.replace(params=params32,
                         opt_state=strip_master(state.opt_state))


def adopt_f32_state(template: Any, restored32: Any,
                    policy: PrecisionPolicy) -> Any:
    """Re-render an f32-policy state under a master policy: the restored
    f32 params become the MASTER (and their downcast the stored params),
    the restored inner optimizer state nests back where the template's
    ``MasterWeightsState`` sits, and the loss-scale fields restart fresh
    (an f32 checkpoint carries none)."""
    params32 = restored32.params
    master = jax.tree.map(
        lambda p: p.astype(policy.master_dtype) if _is_float(p) else p,
        params32)
    params_lp = policy.cast_params(params32)

    def renest(t_node, r_node):
        if _is_master(t_node):
            return MasterWeightsState(
                master=master, inner=r_node,
                loss_scale=jnp.asarray(
                    policy.init_scale if policy.loss_scaling else 1.0,
                    jnp.float32),
                good_steps=jnp.zeros((), jnp.int32),
                skipped=jnp.zeros((), jnp.int32),
                last_skipped=jnp.zeros((), jnp.bool_))
        return r_node

    opt_state = jax.tree.map(renest, template.opt_state,
                             restored32.opt_state, is_leaf=_is_master)
    return restored32.replace(params=params_lp, opt_state=opt_state)


def restore_into_policy(manager, template: Any,
                        policy: PrecisionPolicy) -> Any:
    """Restore the latest checkpoint into ``template``'s layout, policy-
    aware: a checkpoint written under the SAME policy restores directly;
    a checkpoint written by an f32 run (no master in its optimizer tree)
    restores through the f32 template and is adopted — master := the
    restored f32 params, stored params := their downcast.  Raises the
    direct-restore error when neither structure matches."""
    try:
        return manager.restore(template)
    except Exception as direct_err:
        if policy.master_dtype is None:
            raise
        try:
            restored32 = manager.restore(f32_template(template))
        except Exception:
            # neither layout matches: the DIRECT error is the informative
            # one (same-policy structure/IO mismatch) — the f32-template
            # failure is just "also not that shape"
            raise direct_err
        return adopt_f32_state(template, restored32, policy)
