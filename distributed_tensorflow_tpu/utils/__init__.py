"""Cross-cutting utilities: harness, supervisor reporting, wire framing."""
