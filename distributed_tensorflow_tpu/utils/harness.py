"""L5 experiment harness: configure → train → time → evaluate → report.

Reproduces the reference's measurement window semantics: the clock runs from
"all workers ready" to "all workers finished" (start/end barriers, reference
server.py:76-79, 115-119) — here from just before the first training step to
`block_until_ready` after the last — and final accuracy is evaluated on the
full unsharded test set (reference server.py:179-180).  Compile time is
reported separately (`compile_s`): XLA traces/compiles on the first step,
which the wall-clock window includes, exactly as TF's first-batch graph
build was included in the reference's window.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import jax

from distributed_tensorflow_tpu import models as modellib
from distributed_tensorflow_tpu.data import loaders
from distributed_tensorflow_tpu.engines import create_engine
from distributed_tensorflow_tpu.engines.allreduce import Trainer
from distributed_tensorflow_tpu.parallel import mesh as meshlib
from distributed_tensorflow_tpu.utils.supervisor import ResultSink


@dataclasses.dataclass
class ExperimentConfig:
    """Everything the reference CLI configures (reference initializer.py:72-114),
    plus the TPU-native knobs."""

    engine: str = "sync"            # sync | async | allreduce | gossip
    model: str = "mlp"
    dataset: str = "mnist"
    n_devices: int | None = None    # the reference's -n, as TPU device count
    batch_size: int = 32            # global batch (reference -b is per-worker;
                                    # global = b × n, see run() docstring)
    per_worker_batch: bool = True   # interpret batch_size per device like -b
    epochs: int = 1                 # reference fixes 1 (SURVEY.md §2.4(6))
    learning_rate: float = 1e-3
    sync_every: int = 10            # async engine's averaging period
    degree: int = 1                 # gossip neighbor degree (the -d flag)
    seed: int = 0
    eval_batch: int = 100           # reference's test batch (server.py:179)
    log_every: int = 50
    result_path: str | None = None
    supervisor_address: str | None = None  # reference's -sa / port-4000 channel
    model_fn: Callable | None = None       # user plug-in override (README.md:12)
    dataset_fn: Callable | None = None
    target_accuracy: float | None = None   # e.g. 0.97 for steps-to-97%
    seq_parallel: int = 1                  # >1: shard sequences over a 'seq'
                                           # mesh axis (long-context mode)
    attention_impl: str = "ring"           # ring | ulysses (when seq_parallel>1)


@dataclasses.dataclass
class _Experiment:
    """Resolved experiment: mesh, data, model, engine, global batch."""

    mesh: Any
    n: int
    train_ds: Any
    test_ds: Any
    engine: Any
    global_batch: int


def _setup(config: ExperimentConfig) -> _Experiment:
    if config.seq_parallel > 1:
        return _setup_seq_parallel(config)
    mesh = meshlib.create_mesh(config.n_devices)
    n = mesh.shape[meshlib.DATA_AXIS]

    if config.dataset_fn is not None:
        train_ds = config.dataset_fn(config.batch_size, type="train")
        test_ds = config.dataset_fn(config.eval_batch, type="test")
    else:
        train_ds = loaders.load_dataset(config.dataset, split="train")
        test_ds = loaders.load_dataset(config.dataset, split="test")

    if config.model_fn is not None:
        model = config.model_fn()
    else:
        model = modellib.create_model(config.model, num_classes=train_ds.num_classes)

    # reference -b is the PER-WORKER batch (reference client.py:64 feeds each
    # worker's shard with batch_size b); global batch = b × n matches its
    # aggregate examples-per-round
    global_batch = config.batch_size * n if config.per_worker_batch else config.batch_size
    global_batch = max(global_batch, n)

    engine_kw: dict[str, Any] = dict(mesh=mesh, learning_rate=config.learning_rate)
    if config.engine == "async":
        engine_kw["sync_every"] = config.sync_every
    elif config.engine == "gossip":
        engine_kw["degree"] = config.degree
    engine = create_engine(config.engine, model, **engine_kw)
    return _Experiment(mesh=mesh, n=n, train_ds=train_ds, test_ds=test_ds,
                       engine=engine, global_batch=global_batch)


def _setup_seq_parallel(config: ExperimentConfig) -> _Experiment:
    """Long-context mode: 2-D (data, seq) mesh + ring/Ulysses attention.

    ``n_devices`` still plays the reference's -n role; ``seq_parallel`` of
    them shard the sequence, the rest shard the batch."""
    import jax as _jax

    from distributed_tensorflow_tpu.engines.seq_parallel import SeqParallelEngine

    if config.engine not in ("sync", "allreduce"):
        raise ValueError(
            f"seq_parallel>1 supports sync semantics only, got engine="
            f"'{config.engine}' (async/gossip + sequence sharding is not "
            f"implemented)")
    total = config.n_devices or len(_jax.devices())
    sp = config.seq_parallel
    if total % sp != 0:
        raise ValueError(f"n_devices {total} not divisible by seq_parallel {sp}")
    dp = total // sp
    mesh = meshlib.create_mesh(
        total, shape=(dp, sp), axis_names=(meshlib.DATA_AXIS, meshlib.SEQ_AXIS))

    if config.dataset_fn is not None:
        train_ds = config.dataset_fn(config.batch_size, type="train")
        test_ds = config.dataset_fn(config.eval_batch, type="test")
    else:
        train_ds = loaders.load_dataset(config.dataset, split="train")
        test_ds = loaders.load_dataset(config.dataset, split="test")
    if config.model_fn is not None:
        model = config.model_fn()
    else:
        model = modellib.create_model(
            config.model, num_classes=train_ds.num_classes,
            attention_impl=config.attention_impl)

    global_batch = max(
        config.batch_size * dp if config.per_worker_batch else config.batch_size,
        dp)
    engine = SeqParallelEngine(model, mesh=mesh,
                               learning_rate=config.learning_rate)
    return _Experiment(mesh=mesh, n=dp, train_ds=train_ds, test_ds=test_ds,
                       engine=engine, global_batch=global_batch)


def run(config: ExperimentConfig) -> dict[str, Any]:
    """Run one experiment; returns the summary dict (also emitted as JSONL)."""
    ex = _setup(config)
    n, train_ds, test_ds = ex.n, ex.train_ds, ex.test_ds
    global_batch = ex.global_batch

    sink = ResultSink(config.result_path, echo=False,
                      supervisor_address=config.supervisor_address)
    trainer = Trainer(None, engine=ex.engine, seed=config.seed)

    sink.start()
    fit = trainer.fit(train_ds, epochs=config.epochs, batch_size=global_batch,
                      log_every=config.log_every)
    sink.done(fit["elapsed"])
    ev = trainer.evaluate(test_ds, batch_size=config.eval_batch)
    sink.results(ev["accuracy"], loss=ev["loss"])

    summary = {
        "engine": config.engine if config.seq_parallel <= 1 else
                  f"seq_parallel[{config.attention_impl}]",
        "model": config.model,
        "dataset": train_ds.name,
        "synthetic_data": train_ds.synthetic,
        "n_devices": n * config.seq_parallel,
        "data_parallel": n,
        "seq_parallel": config.seq_parallel,
        "global_batch": global_batch,
        "epochs": config.epochs,
        "steps": fit["steps"],
        "elapsed_s": fit["elapsed"],
        "examples_per_sec": fit["examples_per_sec"],
        "examples_per_sec_per_device": fit["examples_per_sec"] / (n * config.seq_parallel),
        "test_accuracy": ev["accuracy"],
        "test_loss": ev["loss"],
    }
    sink.emit("summary", **summary)
    sink.close()
    return summary


def steps_to_accuracy(
    config: ExperimentConfig,
    target: float,
    max_steps: int = 10_000,
    eval_every: int = 50,
) -> dict[str, Any]:
    """Steps-to-target measurement (BASELINE.md north star: steps-to-97%).

    Counts *global* batches, the normalization BASELINE.md requires when
    comparing against the reference's sequential-apply sync PS
    (SURVEY.md §2.4(1)).  Evaluates every ``eval_every`` steps, so the
    returned step count is accurate to that resolution.
    """
    ex = _setup(config)
    eng = ex.engine
    rng = jax.random.key(config.seed)
    state = eng.init_state(rng, ex.train_ds.x[: max(1, ex.n)])

    steps = 0
    epoch = 0
    acc = 0.0
    t0 = time.perf_counter()
    while steps < max_steps:
        for bx, by, _ in ex.train_ds.batches(
                ex.global_batch, shuffle=True, seed=config.seed, epoch=epoch,
                drop_remainder=True):
            xs, ys = eng.shard_batch(bx, by)
            state, _ = eng.step(state, xs, ys)
            steps += 1
            if steps % eval_every == 0 or steps >= max_steps:
                acc = eng.evaluate(state, ex.test_ds,
                                   batch_size=config.eval_batch)["accuracy"]
                if acc >= target:
                    return {"reached": True, "steps": steps, "accuracy": acc,
                            "elapsed_s": time.perf_counter() - t0}
                if steps >= max_steps:
                    break
        epoch += 1
    return {"reached": False, "steps": steps, "accuracy": acc,
            "elapsed_s": time.perf_counter() - t0}
