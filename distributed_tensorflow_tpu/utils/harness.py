"""L5 experiment harness: configure → train → time → evaluate → report.

Reproduces the reference's measurement window semantics: the clock runs from
"all workers ready" to "all workers finished" (start/end barriers, reference
server.py:76-79, 115-119) — here from just before the first training step to
`block_until_ready` after the last — and final accuracy is evaluated on the
full unsharded test set (reference server.py:179-180).  Compile time is
reported separately (`compile_s`): XLA traces/compiles on the first step,
which the wall-clock window includes, exactly as TF's first-batch graph
build was included in the reference's window.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import Any, Callable

import jax
import numpy as np

from distributed_tensorflow_tpu import models as modellib
from distributed_tensorflow_tpu.data import loaders
from distributed_tensorflow_tpu.engines import create_engine
from distributed_tensorflow_tpu.engines.allreduce import Trainer
from distributed_tensorflow_tpu.parallel import mesh as meshlib
from distributed_tensorflow_tpu.utils.supervisor import ResultSink


@dataclasses.dataclass
class ExperimentConfig:
    """Everything the reference CLI configures (reference initializer.py:72-114),
    plus the TPU-native knobs."""

    engine: str = "sync"            # sync | async | allreduce | gossip | fsdp
    model: str = "mlp"
    dataset: str = "mnist"
    n_devices: int | None = None    # the reference's -n, as TPU device count
    batch_size: int = 32            # global batch (reference -b is per-worker;
                                    # global = b × n, see run() docstring)
    per_worker_batch: bool = True   # interpret batch_size per device like -b
    epochs: int = 1                 # reference fixes 1 (SURVEY.md §2.4(6))
    learning_rate: float = 1e-3
    lr_schedule: str = "constant"   # constant | cosine | linear (each with
                                    # optional linear warmup); horizon =
                                    # epochs × steps-per-epoch
    warmup_steps: int = 0           # linear LR warmup from 0 over this many
                                    # steps (0 disables)
    schedule_horizon_steps: int | None = None  # decay horizon override for
                                    # --lr-schedule; default = epochs ×
                                    # steps-per-epoch (steps_to_accuracy sets
                                    # it to max_steps: its loop runs far past
                                    # one epoch, and a horizon computed from
                                    # config.epochs would decay LR to 0 with
                                    # thousands of steps still to train)
    grad_accum: int = 1             # microbatches accumulated per optimizer
                                    # step (sync/allreduce engines): ~K× less
                                    # activation memory at identical math
    grad_compression: str = "none"  # cross-device gradient/parameter
                                    # exchange codec: none | bf16 | int8
                                    # (parallel/compression.py; pipeline
                                    # modes reject it)
    precision: str = "f32"          # end-to-end mixed-precision policy
                                    # (parallel/precision.py): f32 | bf16 |
                                    # bf16-f32master | fp16-f32master.
                                    # Storage + compute + grad-reduce
                                    # dtypes with an optional f32 master
                                    # copy inside the optimizer state;
                                    # 'f32' compiles the byte-identical
                                    # pre-policy programs.  Distinct from
                                    # `dtype` (the activation-only knob):
                                    # a non-f32 policy OWNS the model
                                    # dtype — see _resolve_precision
    grad_bucket_mb: float = 0.0     # >0: communication/compute overlap —
                                    # partition the grad pytree into
                                    # size-targeted buckets (reverse-
                                    # backward order, parallel/overlap.py)
                                    # whose independent collectives XLA's
                                    # latency-hiding scheduler runs behind
                                    # backward compute; the codec applies
                                    # per bucket.  0 (default): bitwise
                                    # pre-overlap programs.  ~4 is the
                                    # recommended size; pipeline modes
                                    # reject it like grad_compression
    compile_cache: str | None = None  # persistent XLA compilation cache
                                    # dir (jax_compilation_cache_dir):
                                    # repeat runs skip recompiles
    weight_decay: float = 0.0       # >0: AdamW decoupled weight decay
    clip_norm: float = 0.0          # >0: clip gradients to this global norm
                                    # before the optimizer update
    sync_every: int = 10            # async engine's averaging period
    degree: int = 1                 # gossip neighbor degree (the -d flag)
    seed: int = 0
    eval_batch: int = 100           # reference's test batch (server.py:179)
    log_every: int = 50
    steps_per_call: int | None = None  # steady-state drain chunk: steps per
                                    # jitted lax.scan dispatch (None = auto —
                                    # 8, downshifting to 1 only for
                                    # steps-to-target runs; telemetry rides
                                    # the chunk — resolve_steps_per_call)
    prefetch: int = 2               # device-prefetch depth: batches staged
                                    # on the mesh ahead of the step loop so
                                    # transfer N+1 overlaps compute N
    result_path: str | None = None
    supervisor_address: str | None = None  # reference's -sa / port-4000 channel
    model_fn: Callable | None = None       # user plug-in override (README.md:12)
    dataset_fn: Callable | None = None
    target_accuracy: float | None = None   # e.g. 0.97 for steps-to-97%
    seq_parallel: int = 1                  # >1: shard sequences over a 'seq'
                                           # mesh axis (long-context mode)
    attention_impl: str = "ring"           # ring | ring_flash | ulysses |
                                           # ulysses_flash (when
                                           # seq_parallel>1); flash (Pallas
                                           # kernel) when seq_parallel==1
    positional: str = "learned"            # GPT positions: learned | rope
    kv_heads: int | None = None            # GPT GQA: K/V heads < query heads
    remat: bool = False                    # activation checkpointing: store
                                           # block inputs only, recompute in
                                           # backward (transformer models
                                           # and the GPipe tick body)
    model_args: dict | None = None         # extra model constructor fields
                                           # (--model-arg KEY=VALUE): sizes
                                           # like hidden/layers/heads for the
                                           # registered models; applied on
                                           # the DP and model-parallel
                                           # paths (pipeline stages size via
                                           # --pipeline-hidden instead)
    tensor_parallel: int = 1               # >1: shard weights over a 'model'
                                           # mesh axis (Megatron-style TP)
    pipeline_parallel: int = 1             # >1: shard stages over a 'pipe'
                                           # mesh axis (GPipe microbatching)
    microbatches: int = 4                  # pipeline microbatches per step
    pipeline_schedule: str = "gpipe"       # gpipe | 1f1b (bounded stash)
    expert_parallel: int = 1               # >1: shard MoE experts over an
                                           # 'expert' mesh axis
    num_experts: int = 8                   # MoE expert count
    aux_weight: float = 0.01               # MoE load-balance loss weight
    router_top_k: int = 1                  # MoE routing: 1 (Switch) | 2 (GShard)
    router_z_weight: float = 0.0           # MoE router z-loss weight
    pipeline_hidden: int = 128             # pipeline stage width
    checkpoint_dir: str | None = None      # enable TrainState checkpointing
    checkpoint_every: int = 0              # steps between checkpoints (0=end only)
    async_checkpoint: bool = True          # overlap checkpoint writes with
                                           # training (AsyncCheckpointManager:
                                           # device snapshot on the training
                                           # thread, Orbax write + retention
                                           # on a background writer); False =
                                           # the synchronous blocking save
    resume: bool = False                   # restore latest checkpoint first
    elastic_restore: bool = False          # mesh-shape-independent resume
                                           # (elastic/reshard.py): restore
                                           # the latest checkpoint onto
                                           # THIS run's mesh whatever mesh
                                           # wrote it (GSPMD family), with
                                           # exactly-once data resume from
                                           # the checkpoint's data state
                                           # and preemption accounting
                                           # (preemption_lost_s /
                                           # resume_replay_steps in the
                                           # run report)
    max_steps_per_lease: int = 0           # >0: graceful lease drain
                                           # (elastic/lease.py) — stop at
                                           # the first chunk boundary at/
                                           # after N steps this run, write
                                           # the final checkpoint (data
                                           # state included) and return a
                                           # `preempted` result instead of
                                           # training on.  Checkpointed
                                           # runs also arm a SIGTERM
                                           # preemption-notice handler
                                           # that triggers the same drain
    metrics_path: str | None = None        # per-step metrics JSONL (async
                                           # crash-durable sink; rides the
                                           # chunked drain — no downshift)
    trace_path: str | None = None          # structured span/event JSONL
                                           # timeline (observability/trace)
    timeline: bool = False                 # periodic gauge sampler (queue
                                           # depth, KV blocks, replica load)
                                           # + XLA program ledger (per-
                                           # program memory_analysis,
                                           # compile wall-time).  Host-side
                                           # only; off compiles the exact
                                           # pre-timeline program set
    timeline_interval: float = 0.05        # min seconds between samples
                                           # per gauge group (throttle —
                                           # sampling happens at existing
                                           # iteration boundaries, never
                                           # on a timer thread)
    roofline: bool = False                 # analytic FLOPs/bytes cost
                                           # model + MFU/MBU attribution
                                           # (observability/roofline) on
                                           # the fit result, the serve
                                           # summary and the run report;
                                           # arms the XLA program ledger
                                           # for cost_analysis capture.
                                           # Host-side only; off keeps the
                                           # program + key sets
                                           # byte-identical (parity pin)
    profile_dir: str | None = None         # XLA profiler trace output
    dtype: str = "float32"                 # model compute dtype; 'bfloat16'
                                           # enables mixed precision (params
                                           # stay f32, activations/matmuls
                                           # run bf16 on the MXU)
    watchdog_timeout: float = 0.0          # >0: stall detector around the
                                           # step loop (utils/failure.py)
    watchdog_abort: bool = False           # on stall: report, then exit(75)
                                           # for an external relaunch with
                                           # resume (in-process recovery of
                                           # a wedged XLA runtime is not
                                           # possible)
    nan_guard: bool = True                 # divergence check at log cadence
                                           # (legacy alias: --health on
                                           # subsumes it with the per-step
                                           # anomaly policy)
    health: str = "off"                    # 'on': per-step numeric-health
                                           # stats on device inside the
                                           # scan (observability/health.py)
                                           # — zero downshift, stacked like
                                           # metrics; 'off' compiles the
                                           # exact pre-health program
    on_anomaly: str = "warn"               # health anomaly policy: 'warn'
                                           # records structured anomaly
                                           # events; 'halt' raises at the
                                           # offending step
    max_restarts: int = 0                  # >0: checkpoint-resume crash
                                           # recovery (run_with_recovery)
    sample_tokens: int = 0                 # >0: after training an LM, decode
                                           # this many tokens per prompt from
                                           # the final params (KV-cache
                                           # sampler, models/gpt.py generate)
                                           # and record them in the summary
    sample_prompt_len: int = 8             # prompt tokens taken from the
                                           # test split per sampled row
    serve_requests: int = 0                # >0: after training an LM, run a
                                           # continuous-batching serving
                                           # window of this many requests
                                           # (serving/: slot KV cache +
                                           # in-flight scheduler) and carry
                                           # its TTFT/ITL percentiles +
                                           # requests/sec/chip in the
                                           # summary and run report —
                                           # serving gets the same
                                           # trajectory and `analyze diff`
                                           # gating training has
    serve_slots: int = 4                   # KV slot table size (requests in
                                           # flight at once; shards over
                                           # the 'data' axis when it
                                           # divides)
    serve_max_new: int = 16                # tokens generated per request
    serve_prompt_len: int = 8              # prompt tokens taken from the
                                           # test split per request
    serve_kv_dtype: str | None = None      # --serve KV-table storage dtype
                                           # ('bfloat16' halves KV memory →
                                           # double the slots per chip;
                                           # 'int8' halves bf16's payload
                                           # again — int8 K/V + one f32
                                           # max-abs scale per written
                                           # vector, tolerance-based token
                                           # parity vs the bf16 oracle);
                                           # None: the model's dtype
    serve_prefill_chunk: int = 0           # >0: chunked prefill token
                                           # budget (Sarathi-Serve) — at
                                           # most one ≤N-token prompt chunk
                                           # rides each decode iteration,
                                           # so a long admission cannot
                                           # stall live slots for more
                                           # than a chunk; 0 = monolithic
                                           # (pre-round-10 programs)
    serve_prefix_cache: int = 0            # >0: prefix-cache pool capacity
                                           # in KV blocks (vLLM-style
                                           # block reuse; LRU past the
                                           # bound); admission copies the
                                           # longest cached prompt prefix
                                           # into the slot and prefills
                                           # only the uncached tail
    serve_prefix_block: int = 16           # tokens per prefix-cache block
                                           # (reuse granularity)
    serve_shared_prefix: int = 0           # >0: prepend a fixed synthetic
                                           # N-token system prompt to
                                           # every request (the shared-
                                           # prefix traffic shape;
                                           # deterministic from seed)
    serve_slo_ttft: float = 2.0            # TTFT SLO target in seconds:
                                           # a request is goodput only
                                           # when arrival→first-token
                                           # (queue wait included) meets
                                           # this AND the ITL target
    serve_slo_itl: float = 0.5             # ITL SLO target in seconds,
                                           # judged at each request's own
                                           # p99 inter-token gap
    serve_queue_cap: int = 0               # >0: bounded admission — the
                                           # arrived-but-unadmitted
                                           # backlog is capped; excess
                                           # sheds with 429 accounting
                                           # (shed_requests/
                                           # serve_shed_rate + a
                                           # structured `overload` trace
                                           # event) so overload degrades
                                           # to bounded queue wait, not
                                           # unbounded TTFT.  0 = admit
                                           # everything (PR 10 behavior)
    serve_draft_config: str | None = None  # speculative decoding: 'self'
                                           # (draft = the served model —
                                           # accept rate 1, the mechanism
                                           # check) or 'k=v,...' GPT size
                                           # overrides (hidden/layers/
                                           # heads/ffn; vocab + max_len
                                           # inherited, fresh-initialized
                                           # from --seed).  None = off:
                                           # the pre-round-14 programs,
                                           # byte-identical
    serve_draft_k: int = 4                 # draft tokens proposed per
                                           # verify round (k draft steps →
                                           # one batched k+1-position
                                           # target verify; greedy
                                           # acceptance keeps the stream
                                           # bitwise non-speculative)
    serve_replicas: int = 1                # >1: serve through a ReplicaSet
                                           # fleet (serving/fleet.py) —
                                           # N batcher replicas, each with
                                           # its own serve_slots-slot KV
                                           # table, behind a least-loaded
                                           # router with journaled
                                           # no-loss failover; the serve
                                           # section gains `serve_fleet`
                                           # + the failover gate keys
    serve_fault_spec: str | None = None    # seeded fault injection into
                                           # the fleet (FaultInjector
                                           # grammar: 'crash:replica=0,
                                           # iter=3;stall:replica=1,
                                           # iter=2,stall_s=1' ...) — the
                                           # chaos-test substrate; forces
                                           # the fleet path even at
                                           # serve_replicas == 1
    serve_watchdog_s: float = 0.0          # >0: fleet supervisor watchdog
                                           # — a replica busy with no
                                           # token progress for this many
                                           # seconds is failed over (its
                                           # zombie fenced).  Set it above
                                           # worst-case first-program XLA
                                           # compile; 0 = off (stall
                                           # faults then just sleep).
                                           # Fleet mode only
    serve_hot_swap: bool = False           # zero-downtime weight hot-swap
                                           # drill: after half the window
                                           # completes, drain + re-install
                                           # the served params replica-by-
                                           # replica (never below N-1
                                           # admitting) — swap_generations
                                           # >= 1 proves the mechanism,
                                           # greedy tokens unchanged (the
                                           # swapped-in weights are the
                                           # same trained params)
    serve_kv_layout: str = "monolithic"    # --serve-kv-layout paged: the KV
                                           # table becomes a refcounted
                                           # physical block pool + per-slot
                                           # block tables (PagedSlotKVCache
                                           # — vLLM PagedAttention): prefix
                                           # hits alias blocks zero-copy,
                                           # CoW isolates writers, decode
                                           # reads fused through the Pallas
                                           # paged kernel (tolerance-based
                                           # token parity, the int8
                                           # precedent).  'monolithic'
                                           # keeps the per-slot rows and a
                                           # byte-identical program set
    serve_paged_block: int = 0             # tokens per physical KV block
                                           # under paged (0: inherit
                                           # serve_prefix_block — the two
                                           # MUST agree when the prefix
                                           # pool is on: hits alias
                                           # physical blocks by pointer)
    serve_paged_blocks: int = 0            # physical block-pool capacity
                                           # under paged (0: auto-size so
                                           # slots*max_len + prefix pool
                                           # always fit — never exhausts);
                                           # explicit smaller pools defer
                                           # admissions when the free list
                                           # cannot cover a request's
                                           # worst-case block need
    serve_disaggregate: str | None = None  # 'P:D': disaggregated fleet —
                                           # P prefill replicas (admission
                                           # + chunked prefill, then a
                                           # serialized KV handoff) and D
                                           # decode replicas (never share
                                           # an iteration with a long
                                           # prompt).  Overrides
                                           # serve_replicas (P+D total);
                                           # handoff time is charged
                                           # inside TTFT.  Decode-side
                                           # tables carry no prefix pool
                                           # (pool warmth lives where
                                           # prefill runs).  None = the
                                           # homogeneous fleet, summary-
                                           # key-identical to round 17
    serve_routing: str = "least-loaded"    # fleet request routing:
                                           # 'least-loaded' (PR 13) or
                                           # 'affinity' — key on the
                                           # chained SHA-256 digest of the
                                           # first prefix block and land
                                           # shared-prefix traffic where
                                           # that block is already warm;
                                           # adds serve_fleet_prefix_
                                           # hit_rate to the summary
    serve_autoscale: str | None = None     # 'MIN:MAX': queue-driven
                                           # replica autoscaling — start
                                           # at MIN serving replicas,
                                           # scale toward MAX on arrived-
                                           # backlog high watermark, drain
                                           # an idle replica back down;
                                           # serve_replica_seconds becomes
                                           # the efficiency ledger.  With
                                           # serve_disaggregate the policy
                                           # drives each role pool
                                           # independently (range clamped
                                           # per pool) and the ledger
                                           # splits per role
    serve_multi_step: int | None = None    # k: fuse k decode iterations
                                           # into ONE device dispatch
                                           # (lax.scan with on-device
                                           # token feedback + EOS/budget
                                           # deactivation) and pipeline
                                           # round i+1's dispatch ahead of
                                           # round i's drain.  Greedy
                                           # streams stay bitwise equal to
                                           # k=1; admissions wait at most
                                           # k fused iterations.  Adds
                                           # serve_dispatches and
                                           # serve_host_gap_s to the
                                           # summary.  None = the legacy
                                           # per-iteration loop, program-
                                           # and key-set identical to
                                           # round 19


def enable_compile_cache(directory: str | os.PathLike) -> str:
    """Point XLA's persistent compilation cache at ``directory``
    (``--compile-cache``): repeat runs — and bench warmups — reuse the
    compiled executables of unchanged programs instead of re-tracing and
    re-compiling them.  Creates the directory, drops jax's minimum-compile-
    time/entry-size gates so even fast CPU-test compiles persist (the gates
    exist to avoid caching trivia; a user who passed a cache dir wants
    hits), and returns the resolved path.  Safe to call before or after
    backend initialization — the cache dir is read per compile."""
    import pathlib

    import jax

    path = pathlib.Path(directory)
    path.mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(path))
    for knob, value in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                        ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, value)
        except Exception:  # knob not present on this jax — cache still on
            pass
    return str(path)


# XLA knobs that let the TPU compiler actually HIDE the bucketed gradient
# collectives parallel/overlap.py makes schedulable: the latency-hiding
# scheduler plus async-collective fusion (the production TPU overlap set).
# They ride LIBTPU_INIT_ARGS — read only by libtpu, so setting them is
# inert on CPU/GPU containers (an unknown flag in XLA_FLAGS would abort
# backend init; LIBTPU_INIT_ARGS is the safe carrier).  The effective
# values are recorded in the run report's `environment` section
# (observability/report.runtime_environment) so bench trajectories stay
# attributable across containers.
OVERLAP_XLA_TPU_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
)


def enable_overlap_flags(env=None) -> str:
    """Append the communication/compute-overlap XLA flags to
    ``LIBTPU_INIT_ARGS`` (idempotent: a flag whose key is already present
    — e.g. user-overridden to false — is left alone).  Must run BEFORE
    backend initialization; ``run()`` and ``bench.py`` call it when
    ``--grad-bucket-mb`` > 0.  Returns the resulting value, which the run
    report records for reproducibility."""
    env = os.environ if env is None else env
    parts = env.get("LIBTPU_INIT_ARGS", "").split()
    have = {p.split("=", 1)[0] for p in parts}
    for flag in OVERLAP_XLA_TPU_FLAGS:
        if flag.split("=", 1)[0] not in have:
            parts.append(flag)
    env["LIBTPU_INIT_ARGS"] = " ".join(parts)
    return env["LIBTPU_INIT_ARGS"]


@dataclasses.dataclass
class _Experiment:
    """Resolved experiment: mesh, data, model, engine, global batch.

    ``name`` is the summary's engine label, set by the _setup_* function
    that chose the engine — the ONE place that knows which mode resolved
    (run() used to re-derive it from the config flags in a parallel
    if/elif ladder, which drifted: ep×sp runs were reported as
    'seq_parallel[ring]' until round 5)."""

    mesh: Any
    n: int
    train_ds: Any
    test_ds: Any
    engine: Any
    global_batch: int
    name: str


def _reject_flash_under_sp(config: ExperimentConfig) -> None:
    """Every seq-sharded mode shares this rejection so the option list
    cannot drift between modes (the seq-capable set is
    ring / ring_flash / ulysses / ulysses_flash; 'flash' is the
    single-device Pallas kernel)."""
    if config.attention_impl == "flash":
        raise ValueError(
            "--attention flash is the single-device Pallas kernel; with "
            "--seq-parallel use ring, ring_flash, ulysses or ulysses_flash")


def _is_pipeline(engine) -> bool:
    """Pipeline engines have no monolithic ``model`` — params are stacked
    per 'pipe' stage — so sampling/eval paths branch on the engine type."""
    from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine

    return isinstance(engine, PipelineEngine)


def _validate_grad_bucket(config: ExperimentConfig) -> None:
    """Reject bad --grad-bucket-mb configs.  Called from _setup AND from
    run() BEFORE enable_overlap_flags() — the overlap flags mutate
    process-global LIBTPU_INIT_ARGS, so a config that _setup would reject
    must never get to mutate the environment of later runs in the same
    process."""
    if not config.grad_bucket_mb:
        return
    if config.grad_bucket_mb < 0:
        raise ValueError(
            f"--grad-bucket-mb must be >= 0 (0 disables bucketing), "
            f"got {config.grad_bucket_mb}")
    if config.pipeline_parallel > 1:
        # same named rejection as --grad-compression: the pipeline
        # schedules own per-stage params inside a manual shard_map
        # axis — there is no single post-AD gradient tree to bucket
        raise ValueError(
            "--grad-bucket-mb is implemented for the data-parallel "
            "and GSPMD engines (sync/async/allreduce/gossip/fsdp, -tp, "
            "-sp, -ep and their composites); the pipeline schedules "
            "(-pp) are not supported — drop the flag or train "
            "without -pp")


def _resolve_precision(config: ExperimentConfig) -> ExperimentConfig:
    """Validate ``--precision`` and resolve the model dtype it implies.

    The policy owns end-to-end precision (storage + compute + grad
    reduce), so with a non-f32 policy the model's compute dtype FOLLOWS
    the policy: ``--dtype`` left at its float32 default is overridden to
    the policy's compute dtype; an explicit matching ``--dtype`` is
    fine; a CONFLICTING one is rejected (silently computing f32 over
    bf16-stored params would promote every matmul back to f32 and hand
    the user neither win).  ``--precision f32`` leaves ``--dtype``'s
    activation-only behavior exactly as before (MIGRATING.md).  Pipeline
    modes reject non-f32 policies with the same named reason as
    --grad-compression: stage params live per-'pipe' inside a manual
    shard_map axis with their own optimizer handling."""
    from distributed_tensorflow_tpu import models as modellib
    from distributed_tensorflow_tpu.parallel import precision as precisionlib

    pol = precisionlib.make_policy(config.precision)  # typo → full menu
    if not pol.active:
        return config
    if config.pipeline_parallel > 1:
        raise ValueError(
            "--precision is implemented for the data-parallel and GSPMD "
            "engines (sync/async/allreduce/gossip/fsdp, -tp, -sp, -ep and "
            "their composites); the pipeline schedules (-pp) are not "
            "supported — drop the flag or train without -pp")
    compute = modellib.resolve_dtype(pol.compute_dtype)
    asked = modellib.resolve_dtype(config.dtype)
    if asked is not modellib.resolve_dtype("float32") and asked is not compute:
        raise ValueError(
            f"--dtype {config.dtype} conflicts with --precision "
            f"{pol.name} (compute dtype {jnp_name(compute)}): a non-f32 "
            f"policy owns the model dtype — drop --dtype or make them "
            f"agree")
    return dataclasses.replace(config, dtype=str(np.dtype(compute)))


def jnp_name(dtype) -> str:
    import jax.numpy as jnp

    return jnp.dtype(dtype).name


def _setup(config: ExperimentConfig) -> _Experiment:
    config = _resolve_precision(config)
    # the z-loss is applied by the MoE-aware engines: the -ep paths, and
    # the tp×sp composite when the model carries MoE blocks
    # (--model-arg moe_experts=N)
    composite_moe = (config.tensor_parallel > 1 and config.seq_parallel > 1
                     and bool((config.model_args or {}).get("moe_experts")))
    if (config.router_z_weight and config.expert_parallel <= 1
            and not composite_moe):
        raise ValueError(
            "--router-z-weight is applied by the MoE-aware engines; "
            "without --expert-parallel > 1 (or a tp×sp composite with "
            "--model-arg moe_experts=N) it would be silently ignored")
    if config.grad_compression != "none":
        from distributed_tensorflow_tpu.parallel import compression

        # fail on typos here, not deep inside an engine constructor
        compression.make_codec(config.grad_compression)
        if config.pipeline_parallel > 1:
            # named rejection, not a silent gap: the pipeline schedules'
            # data-axis gradient reduce rides the manual (data, pipe)
            # shard_map with per-stage param ownership — there is no
            # single post-AD gradient tree to run the codec over, and
            # silently training uncompressed would misreport the wire
            # bytes the flag promises to shrink
            raise ValueError(
                "--grad-compression is implemented for the data-parallel "
                "and GSPMD engines (sync/async/allreduce/gossip/fsdp, -tp, "
                "-sp, -ep and their composites); the pipeline schedules "
                "(-pp) are not supported yet — drop the flag or train "
                "without -pp")
    _validate_grad_bucket(config)
    if config.sample_tokens:
        # pipeline runs sample too (sequential-forward decode over the
        # pipe-stacked stages, engines/pipeline.py generate); family/shape
        # specifics are checked post-setup in _validate_sampling
        if config.model_fn is None and config.model not in _LM_MODELS:
            raise ValueError(
                f"--sample decodes autoregressively and needs a causal LM "
                f"({'/'.join(_LM_MODELS)}), got --model {config.model}")
    multi = [f for f in ("seq_parallel", "tensor_parallel", "pipeline_parallel",
                         "expert_parallel")
             if getattr(config, f) > 1]
    if len(multi) > 1:
        combos = {
            frozenset({"seq_parallel", "tensor_parallel"}): _setup_composite,
            frozenset({"pipeline_parallel", "tensor_parallel"}):
                _setup_pipeline_tp,
            frozenset({"expert_parallel", "tensor_parallel"}): _setup_expert_tp,
            frozenset({"pipeline_parallel", "seq_parallel"}):
                _setup_pipeline_sp,
            frozenset({"pipeline_parallel", "tensor_parallel",
                       "seq_parallel"}): _setup_pipeline_tp_sp,
            frozenset({"expert_parallel", "seq_parallel"}): _setup_expert_sp,
            frozenset({"expert_parallel", "tensor_parallel",
                       "seq_parallel"}): _setup_expert_tp_sp,
            frozenset({"pipeline_parallel", "expert_parallel"}):
                _setup_pipeline_ep,
            frozenset({"pipeline_parallel", "expert_parallel",
                       "tensor_parallel"}): _setup_pipeline_ep_tp,
            frozenset({"pipeline_parallel", "expert_parallel",
                       "seq_parallel"}): _setup_pipeline_ep_sp,
            frozenset({"pipeline_parallel", "expert_parallel",
                       "tensor_parallel", "seq_parallel"}):
                _setup_pipeline_ep_tp_sp,
        }
        # every >= 2-factor subset of the four model-parallel axes is
        # composable (6 pairs, 4 triples, the 5-D quad) — the dict is
        # total over frozenset(multi).  The one remaining rejection,
        # pipeline × the fsdp ENGINE, is enforced where the mesh splits
        # (_split_mesh) with its reason: ZeRO shards state over 'data',
        # a manual axis in the pipeline shard_map, so the gather-per-use
        # all-gathers cannot be GSPMD-inserted mid-schedule.
        return combos[frozenset(multi)](config)
    if config.seq_parallel > 1:
        return _setup_seq_parallel(config)
    if config.tensor_parallel > 1:
        if config.engine == "fsdp":
            return _setup_fsdp_tp(config)
        return _setup_tensor_parallel(config)
    if config.pipeline_parallel > 1:
        return _setup_pipeline_parallel(config)
    if config.expert_parallel > 1:
        return _setup_expert_parallel(config)
    mesh = meshlib.create_mesh(config.n_devices)
    n = mesh.shape[meshlib.DATA_AXIS]

    train_ds, test_ds = _load_data(config)
    if config.model in _LM_MODELS and config.model_fn is None:
        # fail with the dataset hint, not a cryptic Embed trace error
        _require_token_data(train_ds, config, f"engine '{config.engine}'")
    model = _resolve_model(config, train_ds.num_classes)

    # reference -b is the PER-WORKER batch (reference client.py:64 feeds each
    # worker's shard with batch_size b); global batch = b × n matches its
    # aggregate examples-per-round
    global_batch = _global_batch(config, n)

    engine_kw: dict[str, Any] = dict(
        mesh=mesh, learning_rate=config.learning_rate,
        optimizer=_make_optimizer(config, train_ds, global_batch),
        grad_compression=config.grad_compression,
        grad_bucket_mb=config.grad_bucket_mb,
        precision=config.precision)
    if config.engine == "async":
        engine_kw["sync_every"] = config.sync_every
    elif config.engine == "gossip":
        engine_kw["degree"] = config.degree
    if config.grad_accum > 1:
        if config.engine not in ("sync", "allreduce", "fsdp"):
            raise ValueError(
                f"grad_accum is implemented by the sync/allreduce/fsdp "
                f"engines (got engine='{config.engine}')")
        if (global_batch // n) % config.grad_accum:
            raise ValueError(
                f"per-device batch {global_batch // n} not divisible by "
                f"grad_accum {config.grad_accum}")
    if config.engine in ("sync", "allreduce", "fsdp"):
        engine_kw["grad_accum"] = config.grad_accum
    engine = create_engine(config.engine, model, **engine_kw)
    return _Experiment(mesh=mesh, n=n, train_ds=train_ds, test_ds=test_ds,
                       engine=engine, global_batch=global_batch,
                       name=config.engine)


def make_lr_schedule(config: ExperimentConfig, total_steps: int):
    """Learning-rate schedule from --lr-schedule/--warmup-steps, or None for
    the default (constant, no warmup).  The decay horizon is the full run:
    ``total_steps`` = epochs × steps-per-epoch.  No reference counterpart
    (the reference's Adam runs at its constructor default forever, reference
    server.py:52-55) — schedules are table stakes for the transformer-scale
    models this framework adds."""
    import optax

    lr, warm = config.learning_rate, max(config.warmup_steps, 0)
    if config.lr_schedule not in ("constant", "cosine", "linear"):
        raise ValueError(
            f"unknown lr_schedule '{config.lr_schedule}'; "
            f"known: constant, cosine, linear")
    if config.lr_schedule == "constant" and warm == 0:
        return None
    total = max(total_steps, warm + 1)
    decay = max(total - warm, 1)
    if config.lr_schedule == "cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0 if warm else lr, peak_value=lr,
            warmup_steps=warm, decay_steps=total)
    if config.lr_schedule == "linear":
        main = optax.linear_schedule(lr, 0.0, decay)
    else:  # constant after warmup
        main = optax.constant_schedule(lr)
    if warm == 0:
        return main
    return optax.join_schedules(
        [optax.linear_schedule(0.0, lr, warm), main], [warm])


def _make_optimizer(config: ExperimentConfig, train_ds,
                    global_batch: int):
    """Adam over the run's LR schedule, or None → the engine's stock
    adam(learning_rate).

    The horizon counts GLOBAL steps: a process-sharded dataset (multi-host,
    Dataset.process_shard_of) holds 1/P of the examples but every process
    still takes the same global-batch steps over the full set — scaling by
    P keeps the decay reaching 0 at the run's true end, not P× early."""
    import optax

    if config.schedule_horizon_steps is not None:
        total = config.schedule_horizon_steps
    else:
        shard = getattr(train_ds, "process_shard", None)
        n_global = len(train_ds) * (shard[1] if shard else 1)
        total = config.epochs * max(n_global // max(global_batch, 1), 1)
    sched = make_lr_schedule(config, total)
    if sched is None and not config.weight_decay and not config.clip_norm:
        return None
    lr = sched if sched is not None else config.learning_rate
    if config.weight_decay:
        tx = optax.adamw(lr, weight_decay=config.weight_decay,
                         mask=_decay_mask)
    else:
        tx = optax.adam(lr)
    if config.clip_norm:
        tx = optax.chain(optax.clip_by_global_norm(config.clip_norm), tx)
    return tx


def _decay_mask(params):
    """Standard transformer decay mask: weight-decay matmul kernels only —
    biases and LayerNorm scales (ndim < 2) and embedding tables (flax names
    the param 'embedding') drift toward zero under decoupled decay with no
    regularization benefit, measurably hurting convergence."""
    def keep(path, p):
        names = {getattr(k, "key", None) for k in path}
        return p.ndim >= 2 and "embedding" not in names

    return jax.tree_util.tree_map_with_path(keep, params)


def _lm_model_kw(config: ExperimentConfig) -> dict[str, Any]:
    """GPT-only model kwargs (--positional/--kv-heads) — only passed when
    non-default so non-LM models never see unknown fields."""
    kw: dict[str, Any] = {}
    if config.model in _LM_MODELS:
        if config.positional != "learned":
            kw["positional"] = config.positional
        if config.kv_heads is not None:
            kw["kv_heads"] = config.kv_heads
    return kw


def _resolve_model(config: ExperimentConfig, num_classes: int):
    """Model for the data-parallel engines: plug-in ``model_fn`` wins (and
    owns its dtype — warn if --dtype would be silently ignored); registered
    models get ``dtype`` only if their Module accepts it."""
    if config.model_fn is not None:
        if (modellib.resolve_dtype(config.dtype)
                is not modellib.resolve_dtype("float32")):
            import warnings

            warnings.warn(
                f"--dtype {config.dtype} is ignored for plug-in model_fn "
                f"models; the model_fn owns its dtype", stacklevel=2)
        return config.model_fn()
    kw = dict(config.model_args or {})
    forced = _lm_model_kw(config)
    if config.remat:
        if config.model not in _SEQUENCE_MODELS:
            raise ValueError(
                f"--remat checkpoints transformer blocks; --model "
                f"{config.model} has none (sequence models: "
                f"{'/'.join(_SEQUENCE_MODELS)})")
        forced["remat"] = True
    if config.model in ("moe", "moe_mlp"):
        # router_top_k is a MODEL knob — it applies under any engine (a
        # -ep 1 run still routes).  router_z_weight is an ENGINE knob that
        # only the expert-parallel engine consumes; reject it elsewhere
        # instead of silently ignoring it (checked in _setup)
        forced["router_top_k"] = config.router_top_k
    _check_reserved_model_args(
        config, {"num_classes", "dtype", *forced},
        f"--model {config.model}")
    kw.update(forced)
    if config.model in _SEQUENCE_MODELS and config.attention_impl in (
            "flash", "ring_flash", "ulysses_flash"):
        # the Pallas kernel is valid without a seq axis (single-device
        # blockwise attention); ring_flash degrades to it honestly — the
        # user asked for the flash kernel, and at sp==1 the ring schedule
        # is a no-op around it.  Plain ring/ulysses (ring is the flag
        # default) stay ignored here because they require the seq mesh
        # the DP path doesn't build.
        kw["attention_impl"] = "flash"
    try:
        return modellib.create_model(config.model, num_classes=num_classes,
                                     dtype=config.dtype, **kw)
    except TypeError as dtype_err:
        # user-register()ed Modules may not declare a dtype field; probe by
        # retrying WITHOUT dtype but WITH the remaining kwargs — a typo'd
        # --model-arg key must still fail loudly (the probe once dropped
        # ALL kwargs, which silently trained the default-size model), and
        # if the kwarg-preserving probe also fails the original error
        # surfaces, not a misleading dtype message
        try:
            model = modellib.create_model(config.model,
                                          num_classes=num_classes, **kw)
        except TypeError:
            raise dtype_err
        if (modellib.resolve_dtype(config.dtype)
                is not modellib.resolve_dtype("float32")):
            raise ValueError(
                f"model '{config.model}' does not accept a dtype field; "
                f"drop --dtype {config.dtype} or add dtype support to the "
                f"model") from dtype_err
        return model


def _load_data(config: ExperimentConfig):
    """(train, test) datasets.  On a multi-process pod the TRAIN split is
    sharded by process (reference initializer.py:44's per-worker `.shard`,
    previously honored only in spirit): each host materializes ~1/P of the
    train set and the Trainer assembles global batches from local rows.
    Eval stays unsharded — every process computes the same full-test-set
    numbers, matching the reference's single server-side eval.  User
    ``dataset_fn`` plug-ins own their sharding: call
    ``Dataset.process_shard_of(process_count, process_index)`` (or
    `data.make_dataset_fn`'s ``shard=True, process=True``) to opt in to
    per-process global-batch assembly."""
    if config.dataset_fn is not None:
        return (config.dataset_fn(config.batch_size, type="train"),
                config.dataset_fn(config.eval_batch, type="test"))
    train = loaders.load_dataset(config.dataset, split="train")
    test = loaders.load_dataset(config.dataset, split="test")
    n_proc = jax.process_count()
    if n_proc > 1:
        train = train.process_shard_of(n_proc, jax.process_index())
    return train, test


def _global_batch(config: ExperimentConfig, dp: int) -> int:
    return max(config.batch_size * dp if config.per_worker_batch
               else config.batch_size, dp)


def _split_mesh(config: ExperimentConfig, factor: int, factor_name: str,
                second_axis: str, *more: tuple[int, str],
                engines: tuple[str, ...] = ("sync", "allreduce"),
                grad_accum_ok: bool = False):
    """(data, <second_axis>, ...) mesh: the named factors take their axes,
    the remaining devices shard data.  Shared by every model-parallel setup.

    ``engines`` names the engine semantics the mode supports (fsdp×tp passes
    ('fsdp',)); ``grad_accum_ok`` marks modes whose engine implements
    K-microbatch accumulation (the GSPMD engines — tp, fsdp)."""
    import jax as _jax

    if config.engine not in engines:
        why = ""
        if config.engine == "fsdp" and "pipeline" in factor_name:
            # named rejection, not a silent gap (VERDICT r4 #5): the
            # schedules run manual over 'pipe' with per-stage param
            # ownership; ZeRO's GSPMD gather-per-use would have to cross
            # that manual axis mid-schedule, which shard_map forbids
            why = (" (fsdp × pipeline is rejected by design: the pipeline "
                   "schedules own params per 'pipe' stage inside a manual "
                   "shard_map axis, so ZeRO's gather-per-use collectives "
                   "cannot cross it; shard the optimizer inside each stage "
                   "with --engine sync + --grad-accum instead)")
        raise ValueError(
            f"{factor_name} supports {'/'.join(engines)} semantics only, "
            f"got engine='{config.engine}'{why}")
    if config.grad_accum > 1 and not grad_accum_ok:
        raise ValueError(
            f"grad_accum composes with sync/allreduce/fsdp, tensor_parallel, "
            f"fsdp×tp, seq_parallel, expert_parallel, and the tp×sp / ep×sp "
            f"/ ep×tp×sp composites, not with {factor_name}: the pipeline "
            f"schedules already microbatch — size their chunks with "
            f"--microbatches")
    factors = [(factor, second_axis), *more]
    total = config.n_devices or len(_jax.devices())
    prod = 1
    for f, _ in factors:
        prod *= f
    if total % prod != 0:
        raise ValueError(f"n_devices {total} not divisible by {factor_name} {prod}")
    dp = total // prod
    mesh = meshlib.create_mesh(
        total, shape=(dp, *[f for f, _ in factors]),
        axis_names=(meshlib.DATA_AXIS, *[a for _, a in factors]))
    return mesh, dp


_SEQUENCE_MODELS = ("bert_tiny", "bert", "gpt", "gpt_tiny")
_LM_MODELS = ("gpt", "gpt_tiny")  # causal LMs: (B, L) next-token targets


def _setup_seq_parallel(config: ExperimentConfig) -> _Experiment:
    """Long-context mode: 2-D (data, seq) mesh + ring/Ulysses attention.

    ``n_devices`` still plays the reference's -n role; ``seq_parallel`` of
    them shard the sequence, the rest shard the batch."""
    from distributed_tensorflow_tpu.engines.seq_parallel import SeqParallelEngine

    _reject_flash_under_sp(config)
    mesh, dp = _split_mesh(config, config.seq_parallel, "seq_parallel",
                           meshlib.SEQ_AXIS, grad_accum_ok=True)
    train_ds, test_ds = _load_data(config)
    model = _sequence_model(config, train_ds, "seq_parallel",
                            attention_impl=config.attention_impl)

    # the seq engine scans K chunks of each data shard's LOCAL batch
    if config.grad_accum > 1 and (_global_batch(config, dp) // dp) % config.grad_accum:
        raise ValueError(
            f"seq_parallel: per-data-shard batch "
            f"{_global_batch(config, dp) // dp} not divisible by "
            f"grad_accum {config.grad_accum}")
    engine = SeqParallelEngine(
        model, mesh=mesh, learning_rate=config.learning_rate,
        optimizer=_make_optimizer(config, train_ds,
                                  _global_batch(config, dp)),
        grad_accum=config.grad_accum,
        grad_compression=config.grad_compression,
        grad_bucket_mb=config.grad_bucket_mb,
        precision=config.precision)
    return _Experiment(mesh=mesh, n=dp, train_ds=train_ds, test_ds=test_ds,
                       engine=engine, global_batch=_global_batch(config, dp),
                       name=f"seq_parallel[{config.attention_impl}]")


def _tp_model(config: ExperimentConfig, train_ds, mode: str):
    """Model for the ('data','model')-mesh modes (tp, fsdp×tp): the
    Megatron-annotated MLP for the reference's default model names, or a
    TP-annotated sequence model."""
    from distributed_tensorflow_tpu.engines.tensor_parallel import TPMLP

    if config.model_fn is None and config.model in ("mlp", "tp_mlp",
                                                    "mnist_mlp"):
        return TPMLP(num_classes=train_ds.num_classes,
                     dtype=modellib.resolve_dtype(config.dtype))
    return _sequence_model(config, train_ds, mode,
                           partition_model=True, attention_impl="dense")


def _check_accum_divides(config: ExperimentConfig, global_batch: int,
                         mode: str) -> None:
    if config.grad_accum > 1 and global_batch % config.grad_accum:
        raise ValueError(
            f"{mode}: global batch {global_batch} not divisible by "
            f"grad_accum {config.grad_accum}")


def _setup_tensor_parallel(config: ExperimentConfig) -> _Experiment:
    """Megatron-style TP: 2-D (data, model) mesh, weights sharded by GSPMD."""
    from distributed_tensorflow_tpu.engines.tensor_parallel import (
        TensorParallelEngine)

    mesh, dp = _split_mesh(config, config.tensor_parallel, "tensor_parallel",
                           meshlib.MODEL_AXIS, grad_accum_ok=True)
    train_ds, test_ds = _load_data(config)
    model = _tp_model(config, train_ds, "tensor_parallel")
    _check_accum_divides(config, _global_batch(config, dp), "tensor_parallel")

    engine = TensorParallelEngine(
        model, mesh=mesh, learning_rate=config.learning_rate,
        optimizer=_make_optimizer(config, train_ds,
                                  _global_batch(config, dp)),
        grad_accum=config.grad_accum,
        grad_compression=config.grad_compression,
        grad_bucket_mb=config.grad_bucket_mb,
        precision=config.precision)
    return _Experiment(mesh=mesh, n=dp, train_ds=train_ds, test_ds=test_ds,
                       engine=engine, global_batch=_global_batch(config, dp),
                       name="tensor_parallel")


def _setup_fsdp_tp(config: ExperimentConfig) -> _Experiment:
    """fsdp × tp: ('data','model') mesh — the model's Megatron annotations
    take their dims (compute sharding), then FSDP shards each leaf's
    largest free dim over 'data' (storage sharding, engines/fsdp.py
    fsdp_spec base=): per-device state bytes ~1/(dp·tp)."""
    from distributed_tensorflow_tpu.engines.fsdp import FSDPEngine

    mesh, dp = _split_mesh(config, config.tensor_parallel,
                           "fsdp×tensor_parallel", meshlib.MODEL_AXIS,
                           engines=("fsdp",), grad_accum_ok=True)
    train_ds, test_ds = _load_data(config)
    model = _tp_model(config, train_ds, "fsdp×tensor_parallel")
    _check_accum_divides(config, _global_batch(config, dp),
                         "fsdp×tensor_parallel")

    engine = FSDPEngine(
        model, mesh=mesh, learning_rate=config.learning_rate,
        optimizer=_make_optimizer(config, train_ds,
                                  _global_batch(config, dp)),
        grad_accum=config.grad_accum,
        grad_compression=config.grad_compression,
        grad_bucket_mb=config.grad_bucket_mb,
        precision=config.precision)
    return _Experiment(mesh=mesh, n=dp, train_ds=train_ds, test_ds=test_ds,
                       engine=engine, global_batch=_global_batch(config, dp),
                       name="fsdp_tp[fsdp*tp]")


def _require_token_data(train_ds, config: ExperimentConfig, mode: str) -> None:
    if not np.issubdtype(train_ds.x.dtype, np.integer):
        hint = ("lm_synth" if config.model in _LM_MODELS else "glue_synth")
        raise ValueError(
            f"{mode} with a sequence model needs a token dataset (integer "
            f"ids), got --dataset {config.dataset} with dtype "
            f"{train_ds.x.dtype}; use --dataset {hint}")
    if config.model in _LM_MODELS and train_ds.y.ndim < 2:
        raise ValueError(
            f"--model {config.model} is a causal LM and needs per-token "
            f"(B, L) targets, got labels of shape {train_ds.y.shape} from "
            f"--dataset {config.dataset}; use --dataset lm_synth")


def _sequence_model(config: ExperimentConfig, train_ds, mode: str, **kw):
    """Resolve a sequence model for a model-parallel mode: user ``model_fn``
    wins as-is; registered sequence models get the mode's sharding kwargs;
    anything else is an error (non-sequence models carry no seq/TP layout)."""
    if config.model_fn is not None:
        return config.model_fn()
    if config.model in _SEQUENCE_MODELS:
        _require_token_data(train_ds, config, mode)
        if config.remat:
            kw["remat"] = True
        _check_reserved_model_args(
            config, {"num_classes", "dtype", *kw, *_lm_model_kw(config)},
            mode)
        kw = {**(config.model_args or {}), **kw}
        kw.update(_lm_model_kw(config))
        return modellib.create_model(
            config.model, num_classes=train_ds.num_classes,
            dtype=config.dtype, **kw)
    raise ValueError(
        f"{mode} needs a sequence model ({'/'.join(_SEQUENCE_MODELS)}), got "
        f"--model {config.model}; pass model_fn for a custom model")


def _check_reserved_model_args(config: ExperimentConfig, reserved,
                               where: str) -> None:
    """--model-arg keys that a dedicated flag or the mode itself sets would
    otherwise surface as a raw ``got multiple values`` TypeError (or be
    silently overridden) when splatted into create_model (ADVICE r3).
    Reject them with the same clean style as the other CLI validations."""
    bad = sorted(set(config.model_args or {}) & set(reserved))
    if bad:
        raise ValueError(
            f"--model-arg key(s) {bad} are reserved for {where}: they are "
            f"set by a dedicated flag or by the mode itself (e.g. "
            f"--num-experts, --dtype, --kv-heads, --positional, "
            f"--attention); drop them from --model-arg")


def _reject_model_args(config: ExperimentConfig, mode: str) -> None:
    """The built-in MLP pipeline stages are sized by --pipeline-hidden, not
    --model-arg — reject rather than silently train a default-size model
    (same policy as --router-z-weight outside EP).  The BERT/GPT stage
    families DO take --model-arg (see _stage_model_args)."""
    if config.model_args:
        raise ValueError(
            f"--model-arg does not reach {mode} stage modules; size them "
            f"with --pipeline-hidden (got {sorted(config.model_args)})")


_STAGE_MODEL_ARGS = ("heads", "ffn", "layers_per_stage")
_STAGE_MOE_ARGS = ("moe_capacity_factor",)  # the overflow monitor's advised
                                            # remediation must be reachable
                                            # from the CLI on pp×ep runs


def _stage_model_args(config: ExperimentConfig, mode: str,
                      moe: bool = False) -> dict:
    """--model-arg keys the BERT/GPT pipeline-stage families accept
    (VERDICT r3 #6: an 8-head or 2-layers-per-stage pipeline should not
    require Python).  Width still comes from --pipeline-hidden; everything
    else is either a dedicated flag (--kv-heads, --positional) or not a
    per-stage knob — reject with the full picture.  MoE stages (pp×ep)
    additionally accept ``moe_capacity_factor``."""
    allowed = _STAGE_MODEL_ARGS + (_STAGE_MOE_ARGS if moe else ())
    extra = dict(config.model_args or {})
    bad = sorted(set(extra) - set(allowed))
    if bad:
        raise ValueError(
            f"--model-arg key(s) {bad} do not reach {mode} stage modules; "
            f"stages accept {'/'.join(allowed)} via --model-arg, "
            f"width via --pipeline-hidden, and K/V heads / positional "
            f"encoding via --kv-heads / --positional")
    return extra


def _pipeline_stages(config: ExperimentConfig, train_ds, test_ds, mode: str,
                     partition_model: bool = False,
                     attention_impl: str = "dense",
                     seq_axis: str | None = None,
                     moe: bool = False):
    """(embed, block, head) for the pipeline setups, by model family:
    BERT encoder (models/bert.py) or GPT decoder LM (models/gpt.py).
    ``attention_impl``/``seq_axis`` make the GPT stages sequence-parallel
    for dp×pp×sp.  ``moe=True`` (pp×ep) makes each stage block's FFN a
    routed MoE sized by ``--num-experts``/``--router-top-k``, with
    'expert'-axis partitioning annotations.  ``--model-arg
    heads/ffn/layers_per_stage`` size the stages (_stage_model_args)."""
    _require_token_data(train_ds, config, mode)
    dtype = modellib.resolve_dtype(config.dtype)
    extra = _stage_model_args(config, mode, moe=moe)
    if moe:
        extra.update(moe_experts=config.num_experts,
                     moe_top_k=config.router_top_k,
                     partition_experts=True)
    if config.model in _LM_MODELS:
        from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

        return gpt_pipeline_stages(
            vocab_size=train_ds.num_classes,
            hidden=config.pipeline_hidden,
            max_len=train_ds.x.shape[1],
            partition_model=partition_model,
            positional=config.positional,
            kv_heads=config.kv_heads,
            attention_impl=attention_impl,
            seq_axis=seq_axis,
            dtype=dtype,
            **extra)
    from distributed_tensorflow_tpu.models.bert import bert_pipeline_stages

    # vocab must cover BOTH splits: nn.Embed silently clamps out-of-range
    # ids, which would skew eval on unseen test tokens
    return bert_pipeline_stages(
        num_classes=train_ds.num_classes,
        vocab_size=int(max(train_ds.x.max(), test_ds.x.max())) + 1,
        hidden=config.pipeline_hidden,
        max_len=train_ds.x.shape[1],
        partition_model=partition_model,
        dtype=dtype,
        **extra)


def _setup_composite(config: ExperimentConfig) -> _Experiment:
    """dp×tp×sp composition: 3-D (data, model, seq) mesh, GSPMD tensor
    parallelism + manual-seq ring/Ulysses attention (engines/composite.py)."""
    from distributed_tensorflow_tpu.engines.composite import CompositeEngine

    mesh, dp = _split_mesh(config, config.tensor_parallel,
                           "tensor_parallel×seq_parallel", meshlib.MODEL_AXIS,
                           (config.seq_parallel, meshlib.SEQ_AXIS),
                           grad_accum_ok=True)
    train_ds, test_ds = _load_data(config)
    model = _sequence_model(config, train_ds, "tensor_parallel×seq_parallel",
                            partition_model=True,
                            attention_impl=config.attention_impl)
    _check_accum_divides(config, _global_batch(config, dp),
                         "tensor_parallel×seq_parallel")
    # a --model-arg moe_experts=N model makes the composite MoE-aware, so
    # the balance-loss weights must reach the engine here too (not only on
    # the -ep paths) — otherwise --aux-weight would be silently ignored
    engine = CompositeEngine(
        model, mesh=mesh, learning_rate=config.learning_rate,
        optimizer=_make_optimizer(config, train_ds,
                                  _global_batch(config, dp)),
        aux_weight=config.aux_weight,
        router_z_weight=config.router_z_weight,
        grad_accum=config.grad_accum,
        grad_compression=config.grad_compression,
        grad_bucket_mb=config.grad_bucket_mb,
        precision=config.precision)
    return _Experiment(mesh=mesh, n=dp, train_ds=train_ds, test_ds=test_ds,
                       engine=engine, global_batch=_global_batch(config, dp),
                       name=f"composite[dp*tp*sp,{config.attention_impl}]")


def _setup_pipeline_parallel(config: ExperimentConfig) -> _Experiment:
    """GPipe mode: 2-D (data, pipe) mesh.  The engine stacks stage params
    over 'pipe'; --model picks the stage family — the built-in MLP stages or
    a BERT encoder split layer-per-stage (models/bert.py
    bert_pipeline_stages)."""
    from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine

    mesh, dp = _split_mesh(config, config.pipeline_parallel,
                           "pipeline_parallel", meshlib.PIPE_AXIS)
    train_ds, test_ds = _load_data(config)
    stages = None
    if config.model in _SEQUENCE_MODELS and config.model_fn is None:
        stages = _pipeline_stages(config, train_ds, test_ds,
                                  "pipeline_parallel")
    elif config.model_fn is not None or config.model not in (
            "mlp", "mnist_mlp", "pipeline_mlp"):
        raise ValueError(
            f"pipeline_parallel ships stages for mlp and "
            f"{'/'.join(_SEQUENCE_MODELS)} (got --model {config.model}); "
            f"custom models pass stages=(embed, block, head) to "
            f"PipelineEngine directly")
    else:
        # built-in MLP stages: sized by --pipeline-hidden only
        _reject_model_args(config, "pipeline_parallel")
    if (_global_batch(config, dp) // dp) % config.microbatches:
        raise ValueError(
            f"per-data-shard batch {_global_batch(config, dp) // dp} not "
            f"divisible by microbatches {config.microbatches}")
    engine = PipelineEngine(num_classes=train_ds.num_classes,
                            hidden=config.pipeline_hidden,
                            microbatches=config.microbatches, mesh=mesh,
                            learning_rate=config.learning_rate,
                            optimizer=_make_optimizer(
                                config, train_ds,
                                _global_batch(config, dp)),
                            dtype=modellib.resolve_dtype(config.dtype),
                            stages=stages,
                            schedule=config.pipeline_schedule,
                            remat=config.remat)
    return _Experiment(mesh=mesh, n=dp, train_ds=train_ds, test_ds=test_ds,
                       engine=engine, global_batch=_global_batch(config, dp),
                       name="pipeline_parallel")


def _setup_pipeline_tp(config: ExperimentConfig) -> _Experiment:
    """dp×pp×tp: 3-D (data, pipe, model) mesh — GPipe/1F1B schedule manual
    over (data, pipe), Megatron TP inside each stage as a GSPMD auto axis
    (engines/pipeline.py).  Sequence-model stages only (BERT encoder or GPT
    decoder): the built-in MLP stages carry no Megatron annotations."""
    from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine

    mesh, dp = _split_mesh(config, config.pipeline_parallel,
                           "pipeline_parallel×tensor_parallel",
                           meshlib.PIPE_AXIS,
                           (config.tensor_parallel, meshlib.MODEL_AXIS))
    train_ds, test_ds = _load_data(config)
    if config.model not in _SEQUENCE_MODELS or config.model_fn is not None:
        raise ValueError(
            f"pipeline×tensor parallelism ships TP-annotated stages for "
            f"{'/'.join(_SEQUENCE_MODELS)} (got --model {config.model}); "
            f"custom models pass stages=(embed, block, head) with "
            f"with_partitioning('model', ...) annotations to PipelineEngine")
    stages = _pipeline_stages(config, train_ds, test_ds,
                               "pipeline_parallel×tensor_parallel",
                               partition_model=True)
    if (_global_batch(config, dp) // dp) % config.microbatches:
        raise ValueError(
            f"per-data-shard batch {_global_batch(config, dp) // dp} not "
            f"divisible by microbatches {config.microbatches}")
    engine = PipelineEngine(microbatches=config.microbatches, mesh=mesh,
                            learning_rate=config.learning_rate,
                            optimizer=_make_optimizer(
                                config, train_ds,
                                _global_batch(config, dp)),
                            stages=stages,
                            schedule=config.pipeline_schedule,
                            remat=config.remat)
    return _Experiment(mesh=mesh, n=dp, train_ds=train_ds, test_ds=test_ds,
                       engine=engine, global_batch=_global_batch(config, dp),
                       name=f"pipeline_tp[dp*pp*tp,{config.pipeline_schedule}]")


def _setup_pipeline_ep(config: ExperimentConfig, tp: int = 1,
                       sp: int = 1) -> _Experiment:
    """dp×pp×ep: 3-D (data, pipe, expert) mesh — GPipe schedule manual over
    (data, pipe), each stage block's FFN a routed MoE whose experts shard
    over 'expert' as a GSPMD auto axis (engines/pipeline.py; same
    partial-manual recipe as pp×tp's 'model' axis).  The batch shards over
    'data' only — the expert axis holds experts, not tokens, exactly as the
    'model' axis holds Megatron shards in pp×tp.  GPipe only: 1F1B's
    hand-scheduled backward carries no router aux cotangent (the engine
    rejects it with that reason).

    ``tp > 1`` adds a 'model' GSPMD axis (dp×pp×ep×tp, 4-D mesh): GShard's
    2-D expert layout inside pipeline stages — each expert's FFN is
    additionally Megatron-split, w1 sharded ('pipe','expert',·,'model').
    ``sp > 1`` adds a manual 'seq' axis (dp×pp×ep×sp): the long-context
    MoE pipeline — ring attention over seq-sharded carries while each seq
    device routes its token block to the globally-sharded experts."""
    from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine

    mode = "pipeline_parallel×expert_parallel" + (
        "×tensor_parallel" if tp > 1 else "") + (
        "×seq_parallel" if sp > 1 else "")
    lm_only = sp > 1  # a seq-sharded carry cannot serve a [CLS] head
    family = _LM_MODELS if lm_only else _SEQUENCE_MODELS
    if config.model not in family or config.model_fn is not None:
        raise ValueError(
            f"{mode} ships MoE-FFN stages for {'/'.join(family)} "
            f"(got --model {config.model}); custom models pass stages "
            f"whose block carries moe_experts/partition_experts "
            f"(models/moe.py MoELayer) to PipelineEngine")
    if sp > 1:
        _reject_flash_under_sp(config)
    if config.num_experts % config.expert_parallel:
        raise ValueError(
            f"num_experts {config.num_experts} not divisible by "
            f"expert_parallel {config.expert_parallel}")
    extra = [(config.expert_parallel, meshlib.EXPERT_AXIS)]
    if tp > 1:
        extra.append((tp, meshlib.MODEL_AXIS))
    if sp > 1:
        extra.append((sp, meshlib.SEQ_AXIS))
    mesh, dp = _split_mesh(config, config.pipeline_parallel, mode,
                           meshlib.PIPE_AXIS, *extra)
    train_ds, test_ds = _load_data(config)
    stages = _pipeline_stages(
        config, train_ds, test_ds, mode, moe=True,
        partition_model=tp > 1,
        attention_impl=config.attention_impl if sp > 1 else "dense",
        seq_axis=meshlib.SEQ_AXIS if sp > 1 else None)
    if (_global_batch(config, dp) // dp) % config.microbatches:
        raise ValueError(
            f"per-data-shard batch {_global_batch(config, dp) // dp} not "
            f"divisible by microbatches {config.microbatches}")
    engine = PipelineEngine(microbatches=config.microbatches, mesh=mesh,
                            learning_rate=config.learning_rate,
                            optimizer=_make_optimizer(
                                config, train_ds,
                                _global_batch(config, dp)),
                            stages=stages,
                            schedule=config.pipeline_schedule,
                            remat=config.remat,
                            aux_weight=config.aux_weight,
                            router_z_weight=config.router_z_weight)
    tag = (f"pipeline_ep_tp_sp[dp*pp*ep*tp*sp,{config.attention_impl}]"
           if tp > 1 and sp > 1
           else "pipeline_ep_tp[dp*pp*ep*tp]" if tp > 1
           else f"pipeline_ep_sp[dp*pp*ep*sp,{config.attention_impl}]"
           if sp > 1 else f"pipeline_ep[dp*pp*ep,{config.pipeline_schedule}]")
    return _Experiment(mesh=mesh, n=dp, train_ds=train_ds, test_ds=test_ds,
                       engine=engine, global_batch=_global_batch(config, dp),
                       name=tag)


def _setup_pipeline_ep_tp(config: ExperimentConfig) -> _Experiment:
    """dp×pp×ep×tp (4-D mesh) — see _setup_pipeline_ep(tp=...)."""
    return _setup_pipeline_ep(config, tp=config.tensor_parallel)


def _setup_pipeline_ep_sp(config: ExperimentConfig) -> _Experiment:
    """dp×pp×ep×sp (4-D mesh) — see _setup_pipeline_ep(sp=...)."""
    return _setup_pipeline_ep(config, sp=config.seq_parallel)


def _setup_pipeline_ep_tp_sp(config: ExperimentConfig) -> _Experiment:
    """dp×pp×ep×tp×sp (5-D mesh): every model-parallel axis at once — pipe
    schedule + ring attention manual over (data, pipe, seq); Megatron and
    GShard-2-D expert sharding GSPMD over ('model', 'expert').  See
    _setup_pipeline_ep(tp=..., sp=...)."""
    return _setup_pipeline_ep(config, tp=config.tensor_parallel,
                              sp=config.seq_parallel)


def _setup_expert_parallel(config: ExperimentConfig,
                           tp: int = 1) -> _Experiment:
    """MoE mode: (data, expert) mesh, experts sharded over 'expert', tokens
    over the data×expert plane (engines/expert_parallel.py).  ``tp > 1``
    adds a 'model' axis — dp×ep×tp: each expert's FFN is also
    Megatron-split (models/moe.py partition_model), still one GSPMD jit."""
    from distributed_tensorflow_tpu.engines.expert_parallel import (
        ExpertParallelEngine)

    mode = ("expert_parallel×tensor_parallel" if tp > 1
            else "expert_parallel")
    extra = [(tp, meshlib.MODEL_AXIS)] if tp > 1 else []
    mesh, dp = _split_mesh(config, config.expert_parallel, mode,
                           meshlib.EXPERT_AXIS, *extra, grad_accum_ok=True)
    train_ds, test_ds = _load_data(config)
    if config.model_fn is not None:
        model = config.model_fn()
    elif config.model in ("moe", "moe_mlp", "mlp"):
        if config.num_experts % config.expert_parallel:
            raise ValueError(
                f"num_experts {config.num_experts} not divisible by "
                f"expert_parallel {config.expert_parallel}")
        _check_reserved_model_args(
            config, {"num_classes", "num_experts", "partition_experts",
                     "partition_model", "router_top_k", "dtype"}, mode)
        model = modellib.create_model(
            "moe", num_classes=train_ds.num_classes,
            **(config.model_args or {}),
            num_experts=config.num_experts, partition_experts=True,
            partition_model=tp > 1, router_top_k=config.router_top_k,
            dtype=config.dtype)
    else:
        raise ValueError(
            f"{mode} needs the MoE model (got --model {config.model}); "
            f"custom MoEs pass model_fn with with_partitioning('expert' "
            f"{'+ ''model'' ' if tp > 1 else ''}...) annotations")

    # tokens shard over (data, expert); a model axis replicates them, so the
    # global batch scales with the token-shard count only
    n_token_shards = dp * config.expert_parallel
    _check_accum_divides(config, _global_batch(config, n_token_shards), mode)
    engine = ExpertParallelEngine(
        model, mesh=mesh, learning_rate=config.learning_rate,
        optimizer=_make_optimizer(config, train_ds,
                                  _global_batch(config, n_token_shards)),
        aux_weight=config.aux_weight,
        router_z_weight=config.router_z_weight,
        grad_accum=config.grad_accum,
        grad_compression=config.grad_compression,
        grad_bucket_mb=config.grad_bucket_mb,
        precision=config.precision)
    return _Experiment(mesh=mesh, n=dp, train_ds=train_ds, test_ds=test_ds,
                       engine=engine,
                       global_batch=_global_batch(config, n_token_shards),
                       name=("expert_tp[dp*ep*tp]" if tp > 1 else "expert_parallel"))


def _setup_pipeline_sp(config: ExperimentConfig, tp: int = 1) -> _Experiment:
    """dp×pp×sp: 3-D (data, pipe, seq) mesh — GPipe schedule manual over
    (data, pipe), ring/Ulysses attention manual over 'seq' inside each
    stage (engines/pipeline.py).  GPT decoder stages only: a seq-sharded
    carry cannot serve a [CLS] classification head, and the LM's per-token
    loss is what the schedule's drain reduces correctly.

    ``tp > 1`` adds a 'model' GSPMD axis — dp×pp×tp×sp on a 4-D mesh: the
    shard_map stays manual over (data, pipe, seq) while each stage's
    Megatron annotations drive in-stage model-axis collectives (the same
    partial-manual composition as pp×tp, engines/pipeline.py
    _wrap_pipe_step)."""
    from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine

    mode = ("pipeline_parallel×tensor_parallel×seq_parallel" if tp > 1
            else "pipeline_parallel×seq_parallel")
    if config.model not in _LM_MODELS or config.model_fn is not None:
        raise ValueError(
            f"{mode} ships GPT decoder stages only "
            f"(got --model {config.model}); custom models pass seq-aware "
            f"stages to PipelineEngine directly")
    _reject_flash_under_sp(config)
    extra = [(tp, meshlib.MODEL_AXIS)] if tp > 1 else []
    mesh, dp = _split_mesh(config, config.pipeline_parallel, mode,
                           meshlib.PIPE_AXIS,
                           (config.seq_parallel, meshlib.SEQ_AXIS), *extra)
    train_ds, test_ds = _load_data(config)
    stages = _pipeline_stages(config, train_ds, test_ds, mode,
                              attention_impl=config.attention_impl,
                              seq_axis=meshlib.SEQ_AXIS,
                              partition_model=tp > 1)
    if (_global_batch(config, dp) // dp) % config.microbatches:
        raise ValueError(
            f"per-data-shard batch {_global_batch(config, dp) // dp} not "
            f"divisible by microbatches {config.microbatches}")
    engine = PipelineEngine(microbatches=config.microbatches, mesh=mesh,
                            learning_rate=config.learning_rate,
                            optimizer=_make_optimizer(
                                config, train_ds, _global_batch(config, dp)),
                            stages=stages,
                            schedule=config.pipeline_schedule,
                            remat=config.remat)
    return _Experiment(mesh=mesh, n=dp, train_ds=train_ds, test_ds=test_ds,
                       engine=engine, global_batch=_global_batch(config, dp),
                       name=(f"pipeline_tp_sp[dp*pp*tp*sp,{config.attention_impl}]" if tp > 1
                             else f"pipeline_sp[dp*pp*sp,{config.attention_impl}]"))


def _setup_pipeline_tp_sp(config: ExperimentConfig) -> _Experiment:
    """dp×pp×tp×sp (4-D mesh) — see _setup_pipeline_sp(tp=...)."""
    return _setup_pipeline_sp(config, tp=config.tensor_parallel)


def _setup_expert_tp(config: ExperimentConfig) -> _Experiment:
    """dp×ep×tp — see _setup_expert_parallel(tp=...)."""
    return _setup_expert_parallel(config, tp=config.tensor_parallel)


def _setup_expert_sp(config: ExperimentConfig, tp: int = 1) -> _Experiment:
    """dp×ep×sp (the long-context MoE shape): ('data','expert','seq') mesh
    — GPT decoder with MoE-FFN blocks (models/gpt.py ``moe_experts``),
    ring/Ulysses attention manual over 'seq', expert dispatch GSPMD over
    'expert' (engines/composite.py).  ``tp > 1`` adds a 'model' axis
    (ep×tp×sp on a 4-D mesh): attention/embeddings Megatron-sharded and
    each expert's FFN additionally model-split (GShard 2-D experts)."""
    from distributed_tensorflow_tpu.engines.composite import CompositeEngine

    mode = ("expert_parallel×tensor_parallel×seq_parallel" if tp > 1
            else "expert_parallel×seq_parallel")
    if config.model not in _SEQUENCE_MODELS:
        raise ValueError(
            f"{mode} routes a transformer's FFN blocks (moe_experts on "
            f"models/gpt.py or models/bert.py); got --model {config.model} "
            f"— use --model gpt (--dataset lm_synth) or --model bert_tiny "
            f"(--dataset glue_synth)")
    _reject_flash_under_sp(config)
    if config.num_experts % config.expert_parallel:
        raise ValueError(
            f"num_experts {config.num_experts} not divisible by "
            f"expert_parallel {config.expert_parallel}")
    extra = [(tp, meshlib.MODEL_AXIS)] if tp > 1 else []
    mesh, dp = _split_mesh(config, config.expert_parallel, mode,
                           meshlib.EXPERT_AXIS,
                           (config.seq_parallel, meshlib.SEQ_AXIS), *extra,
                           grad_accum_ok=True)
    train_ds, test_ds = _load_data(config)
    model = _sequence_model(
        config, train_ds, mode,
        attention_impl=config.attention_impl,
        moe_experts=config.num_experts,
        moe_top_k=config.router_top_k,
        partition_experts=True,
        partition_model=tp > 1)
    _check_accum_divides(config, _global_batch(config, dp), mode)
    engine = CompositeEngine(
        model, mesh=mesh, learning_rate=config.learning_rate,
        optimizer=_make_optimizer(config, train_ds,
                                  _global_batch(config, dp)),
        aux_weight=config.aux_weight,
        router_z_weight=config.router_z_weight,
        grad_accum=config.grad_accum,
        grad_compression=config.grad_compression,
        grad_bucket_mb=config.grad_bucket_mb,
        precision=config.precision)
    return _Experiment(mesh=mesh, n=dp, train_ds=train_ds, test_ds=test_ds,
                       engine=engine, global_batch=_global_batch(config, dp),
                       name=(f"expert_tp_sp[dp*ep*tp*sp,{config.attention_impl}]" if tp > 1
                             else f"expert_sp[dp*ep*sp,{config.attention_impl}]"))


def _setup_expert_tp_sp(config: ExperimentConfig) -> _Experiment:
    """dp×ep×tp×sp (4-D mesh) — see _setup_expert_sp(tp=...)."""
    return _setup_expert_sp(config, tp=config.tensor_parallel)


def run(config: ExperimentConfig) -> dict[str, Any]:
    """Run one experiment; returns the summary dict (also emitted as JSONL).

    With ``max_restarts > 0`` the run is wrapped in checkpoint-resume crash
    recovery (utils/failure.py run_with_recovery).
    """
    if config.max_restarts > 0:
        from distributed_tensorflow_tpu.utils.failure import run_with_recovery

        return run_with_recovery(
            dataclasses.replace(config, max_restarts=0),
            max_restarts=config.max_restarts, run_fn=run)
    if config.watchdog_abort and config.watchdog_timeout <= 0:
        raise ValueError("watchdog_abort requires watchdog_timeout > 0 "
                         "(nothing would ever detect the stall)")
    if config.timeline_interval < 0:
        raise ValueError(f"--timeline-interval must be >= 0 seconds "
                         f"(0 = sample at every boundary), got "
                         f"{config.timeline_interval}")
    if config.compile_cache:
        # before any compile: the whole run's programs become cache hits
        # on the next invocation with the same cache dir
        enable_compile_cache(config.compile_cache)
    if config.grad_bucket_mb:
        # before backend init: the latency-hiding/async-collective flags
        # only take effect at compile time (recorded in the run report's
        # `environment` section either way).  Validate FIRST — a config
        # _setup would reject must not leave LIBTPU_INIT_ARGS mutated for
        # later runs in this process
        _validate_grad_bucket(config)
        enable_overlap_flags()
    ex = _setup(config)
    # numeric-health layer: must be enabled BEFORE any state init (the
    # optimizer tree gains its capture slots at tx.init) — including the
    # --resume template below
    if config.health not in ("off", "on"):
        raise ValueError(
            f"--health must be 'off' or 'on', got '{config.health}'")
    if config.health == "on":
        ex.engine.enable_health()
    n, train_ds, test_ds = ex.n, ex.train_ds, ex.test_ds
    global_batch = ex.global_batch
    if config.sample_tokens:
        _validate_sampling(config, ex, test_ds)
    if config.serve_requests:
        # like sampling: every deterministically-knowable --serve failure
        # raises BEFORE the run spends a training budget on it
        _validate_serving(config, ex, test_ds)

    # in a multi-host pod only process 0 reports — N processes each emitting
    # the start/done/results triple would corrupt an external supervisor's
    # accounting (the reference has exactly one reporting server)
    supervisor = (config.supervisor_address
                  if jax.process_index() == 0 else None)
    sink = ResultSink(config.result_path, echo=False,
                      supervisor_address=supervisor)
    trainer = Trainer(None, engine=ex.engine, seed=config.seed)

    ckpt_mgr = None
    resume_requested = config.resume or config.elastic_restore
    if config.resume and not config.checkpoint_dir:
        raise ValueError("--resume requires --checkpoint-dir")
    if config.elastic_restore and not config.checkpoint_dir:
        raise ValueError("--elastic-restore requires --checkpoint-dir")
    if config.checkpoint_every and not config.checkpoint_dir:
        raise ValueError("--checkpoint-every requires --checkpoint-dir "
                         "(no checkpoints would be written otherwise)")
    if config.max_steps_per_lease < 0:
        raise ValueError(f"--max-steps-per-lease must be >= 0, got "
                         f"{config.max_steps_per_lease}")
    if config.max_steps_per_lease and not config.checkpoint_dir:
        raise ValueError("--max-steps-per-lease requires --checkpoint-dir "
                         "(the lease drain's final checkpoint needs "
                         "somewhere to go)")
    # elastic-resume accounting, filled by the restore below and carried
    # into the run report: seconds the preemption cost (save → resume
    # wall-clock gap) and the data state the resumed fit continues from
    resume_data_state = None
    preemption_lost = None
    restored_step = None
    if config.checkpoint_dir:
        from distributed_tensorflow_tpu.utils.checkpoint import (
            AsyncCheckpointManager, CheckpointManager)

        # async (the default) takes the Orbax write off the training
        # thread; --async-checkpoint off restores the synchronous
        # blocking-save path bit-for-bit (same on-disk format either way).
        # Constructing EITHER manager sweeps any tmp_step_* left by a
        # crashed write, so --resume below only ever sees complete
        # (renamed) checkpoints.
        ckpt_mgr = (AsyncCheckpointManager(config.checkpoint_dir)
                    if config.async_checkpoint
                    else CheckpointManager(config.checkpoint_dir))
        if resume_requested:
            if ckpt_mgr.latest_step() is None:
                flag = ("--elastic-restore" if config.elastic_restore
                        else "--resume")
                print(f"warning: {flag} set but no checkpoint found under "
                      f"{config.checkpoint_dir}; training from scratch")
            else:
                rng = jax.random.key(config.seed)
                template = ex.engine.init_state(
                    rng, train_ds.x[: max(1, ex.n)])
                try:
                    if config.elastic_restore:
                        # mesh-shape-independent restore (elastic/
                        # reshard.py): policy-aware per-leaf load, then
                        # re-placement under THIS engine's spec map on
                        # THIS mesh — the checkpoint may have been
                        # written by a different device count or axis
                        # layout.  The elastic sidecar comes back with
                        # it: data state for the exactly-once resume
                        # ({} when the checkpoint predates it → replay
                        # accounting) and the save wall time the
                        # preemption_lost_s figure is measured from.
                        from distributed_tensorflow_tpu import (
                            elastic as elasticlib)

                        trainer.state, extra = elasticlib.elastic_restore(
                            ckpt_mgr, ex.engine, template)
                        resume_data_state = (
                            (extra or {}).get("data_state") or {})
                        preemption_lost = elasticlib.preemption_lost_s(
                            extra)
                    else:
                        # policy-aware restore: a checkpoint written under
                        # the SAME --precision restores directly; an
                        # f32-era checkpoint restored into a master policy
                        # is adopted (restored f32 params become the
                        # master, their downcast the stored params —
                        # precision.py)
                        from distributed_tensorflow_tpu.parallel import (
                            precision as precisionlib)

                        trainer.state = precisionlib.restore_into_policy(
                            ckpt_mgr, template, ex.engine.precision)
                except Exception as e:
                    # the most common structure mismatch here is a --health
                    # toggle across the resume boundary: enable_health
                    # grows the optimizer tree by two capture slots, so a
                    # checkpoint written under the other setting no longer
                    # matches the template — name that cause instead of
                    # surfacing the checkpoint library's raw tree error
                    raise ValueError(
                        f"--resume could not restore the checkpoint under "
                        f"{config.checkpoint_dir} into this run's state "
                        f"layout (--health {config.health}, --precision "
                        f"{config.precision}).  If the checkpointed run "
                        f"used a different --health setting, the optimizer "
                        f"tree differs (the health capture slots live in "
                        f"it) — resume with the original setting.  An f32 "
                        f"checkpoint restores into a master --precision "
                        f"policy automatically; other precision crossings "
                        f"need the original policy.  Original error: "
                        f"{type(e).__name__}: {e}") from e
                restored_step = ckpt_mgr.latest_step()
                sink.emit("resumed", step=restored_step,
                          elastic=config.elastic_restore,
                          **({"preemption_lost_s": preemption_lost}
                             if config.elastic_restore else {}))

    metrics_logger = None
    if config.metrics_path:
        from distributed_tensorflow_tpu.utils.metrics import MetricsLogger

        metrics_logger = MetricsLogger(config.metrics_path,
                                       log_every=max(1, config.log_every))

    # the tracer is always live: file-backed when --trace is set,
    # aggregate-only otherwise (the run report reads its span table and
    # measured overhead either way; the aggregate cost is two perf_counter
    # calls per chunk-level span)
    from distributed_tensorflow_tpu.observability import (
        Tracer, build_run_report)

    tracer = Tracer(path=config.trace_path,
                    process_index=jax.process_index())

    # --timeline: the sensor substrate.  One flag arms BOTH halves —
    # the gauge sampler (Timeline, sampled at boundaries the loops
    # already cross) and the XLA program ledger (ProgramLedger, riding
    # the serve path's jit sites via ledger.jit).  Off means the objects
    # are None at every call site, so the compiled program set and the
    # summary key set are byte-identical to a pre-timeline run (the
    # parity pin tests/test_timeline.py enforces).
    timeline = None
    ledger = None
    if config.timeline or config.roofline:
        # --roofline arms the ledger too: cost_analysis flops/bytes ride
        # the same AOT-compiled executables memory_analysis does, and the
        # attribution table needs them.  ledger.jit compiles the SAME
        # programs the plain path does (the round-17 discipline), so the
        # parity pin stays about flag-OFF byte-identity.
        from distributed_tensorflow_tpu.observability import ProgramLedger

        ledger = ProgramLedger()
    if config.timeline:
        from distributed_tensorflow_tpu.observability import Timeline

        timeline = Timeline(interval_s=config.timeline_interval)

    # --roofline: device peaks (honest None off-TPU) + the engine's
    # analytic cost model (None for non-GPT models — MFU then reports
    # None, never a number against an invented peak), normalized over the
    # run's total device count.  Threaded through fit, the serve window
    # and the run report below.
    roofline = None
    if config.roofline:
        from distributed_tensorflow_tpu.observability.roofline import (
            Roofline, _dtype_key, device_peaks)

        rf_devices = (n * config.seq_parallel * config.tensor_parallel
                      * config.pipeline_parallel * config.expert_parallel)
        rf_cost = (ex.engine.roofline_model()
                   if hasattr(ex.engine, "roofline_model") else None)
        roofline = Roofline(
            device_peaks(jax.local_devices()[0].device_kind),
            rf_devices, rf_cost,
            _dtype_key(getattr(getattr(ex.engine, "model", None),
                               "dtype", "float32")))

    # elastic lease + straggler detection (distributed_tensorflow_tpu/
    # elastic/): every checkpointed run arms the graceful SIGTERM drain —
    # a preemption notice finishes the in-flight chunk, writes a final
    # checkpoint with its data state and returns a structured `preempted`
    # result instead of a corpse; --max-steps-per-lease adds the step
    # budget.  The straggler detector rides the step times the Trainer
    # already measures (zero extra syncs) and emits structured
    # `straggler` trace events on outliers.
    from distributed_tensorflow_tpu.elastic import (
        LeaseManager, StragglerDetector)

    lease = None
    if config.checkpoint_dir:
        lease = LeaseManager(
            max_steps_per_lease=config.max_steps_per_lease).install()
    straggler = StragglerDetector(tracer=tracer)

    # one-time exposed-vs-hidden collective measurement (the overlap
    # opt-in pays two extra step compiles for the number BASELINE.md
    # gates): spanned/evented as `collective_overlap`, surfaced by the
    # run report as grad_collective_exposed_s / grad_collective_hidden_s
    overlap_probe = None
    if config.grad_bucket_mb:
        overlap_probe = _probe_collective_overlap(ex, global_batch, tracer)

    from distributed_tensorflow_tpu.utils.metrics import profile

    watchdog = None
    if config.watchdog_timeout > 0:
        from distributed_tensorflow_tpu.utils.failure import Watchdog

        def _on_stall(elapsed: float) -> None:
            sink.emit("stall", elapsed=elapsed)
            if config.watchdog_abort:
                # the step loop is wedged inside the XLA runtime; no Python
                # exception can reach it — exit so a supervisor relaunches
                # with --resume (EX_TEMPFAIL).  os._exit skips every
                # finally block AND kills the async sinks' daemon writer
                # threads, so drain them here first: the records leading up
                # to the stall are exactly the ones worth keeping
                if metrics_logger is not None:
                    metrics_logger.close()
                tracer.close()
                sink.close()
                os._exit(75)

        watchdog = Watchdog(timeout=config.watchdog_timeout,
                            on_stall=_on_stall)

    sink.start()
    try:  # noqa: the sink (and its supervisor socket) must close on ANY exit
        try:
            with profile(config.profile_dir, tracer=tracer):
                fit = trainer.fit(train_ds, epochs=config.epochs,
                                  batch_size=global_batch,
                                  log_every=config.log_every,
                                  checkpoint_manager=ckpt_mgr,
                                  checkpoint_every=config.checkpoint_every,
                                  metrics_logger=metrics_logger,
                                  watchdog=watchdog,
                                  nan_guard=config.nan_guard,
                                  on_anomaly=config.on_anomaly,
                                  steps_per_call=config.steps_per_call,
                                  prefetch=config.prefetch,
                                  tracer=tracer,
                                  should_stop=(lease.should_stop
                                               if lease is not None
                                               else None),
                                  data_state=resume_data_state,
                                  straggler_detector=straggler,
                                  timeline=timeline,
                                  roofline=roofline)
        finally:
            if watchdog is not None:
                watchdog.close()
            if lease is not None and not config.serve_requests:
                # restore the previous SIGTERM disposition as soon as
                # training ends: nothing after fit consults the lease on
                # a non-serving run, and a still-armed handler would
                # SWALLOW a preemption notice during eval/report.  With
                # --serve the lease stays armed through the serving
                # window (its should_stop hook drains it) and the outer
                # finally uninstalls (idempotent) afterwards.
                lease.uninstall()
        if config.grad_bucket_mb:
            # ride the fit result into the run report (None when the
            # probe was unsupported/failed — "measured 0" stays
            # distinguishable from "not measured")
            fit["collective_overlap"] = overlap_probe
        # preemption accounting (elastic/): the restore-side numbers ride
        # the fit result into the run report next to the fit-side ones
        # (preempted / resume_replay_steps / stragglers), and a drained
        # lease emits the structured `preempted` event an external
        # supervisor reads instead of finding a corpse
        if config.elastic_restore:
            fit["preemption_lost_s"] = preemption_lost
            fit["restored_step"] = restored_step
        if lease is not None:
            fit["lease"] = lease.report()
        if fit.get("preempted"):
            # the supervisor-protocol drain notice (utils/supervisor.py
            # ResultSink.preempted): an external harness sees a planned
            # ['preempted', reason, step] instead of a dead socket
            sink.preempted(fit["preempted"],
                           fit.get("start_step", 0) + fit["steps"])
        sink.done(fit["elapsed"])
        with tracer.span("eval", final=True):
            ev = trainer.evaluate(test_ds, batch_size=config.eval_batch)
        sink.results(ev["accuracy"], loss=ev["loss"])

        # the summary's engine label comes from the _setup_* function that
        # chose the engine (_Experiment.name) — re-deriving it here from
        # the config flags drifted from the dispatch table twice
        engine_name = ex.name
        total_devices = (n * config.seq_parallel * config.tensor_parallel
                         * config.pipeline_parallel * config.expert_parallel)
        model_name = config.model if config.model_fn is None else getattr(
            config.model_fn, "__name__", "custom_model_fn")
        summary = {
            "engine": engine_name,
            "model": model_name,
            "dataset": train_ds.name,
            "synthetic_data": train_ds.synthetic,
            "n_devices": total_devices,
            "data_parallel": n,
            "seq_parallel": config.seq_parallel,
            "tensor_parallel": config.tensor_parallel,
            "pipeline_parallel": config.pipeline_parallel,
            "expert_parallel": config.expert_parallel,
            "num_experts": (config.num_experts
                            if config.expert_parallel > 1 else None),
            "microbatches": (config.microbatches
                             if config.pipeline_parallel > 1 else None),
            "global_batch": global_batch,
            "epochs": config.epochs,
            "precision": fit.get("precision", config.precision),
            "steps": fit["steps"],
            # graceful-drain outcome: the lease reason when this run was
            # preempted (SIGTERM notice / --max-steps-per-lease), None on
            # a normal finish — relaunch with --elastic-restore
            "preempted": fit.get("preempted"),
            # resolved steady-state drain shape (auto may downshift to 1)
            "steps_per_call": fit.get("steps_per_call"),
            "prefetch_depth": fit.get("prefetch_depth"),
            "elapsed_s": fit["elapsed"],
            "examples_per_sec": fit["examples_per_sec"],
            "examples_per_sec_per_device": fit["examples_per_sec"] / total_devices,
            "test_accuracy": ev["accuracy"],
            "test_loss": ev["loss"],
            # next-token cross-entropy exponentiated = perplexity, the
            # standard LM quality number (reported only for LM models —
            # exp(classification loss) would be meaningless)
            **({"test_perplexity": float(np.exp(min(ev["loss"], 80.0)))}
               if config.model in _LM_MODELS else {}),
        }
        # expert-parallel runs surface the router-health watch (sustained
        # capacity overflow warns during training; the summary records it)
        monitor = getattr(ex.engine, "overflow_monitor", None)
        if monitor is not None:
            summary.update(monitor.report())
        if config.sample_tokens:
            summary.update(_sample_from_state(config, ex, trainer.state,
                                              test_ds))
        serve_sec = None
        if config.serve_requests:
            # the serve window rides the lease's SIGNAL hook only (budget
            # steps are a TRAINING budget — a budget-drained fit still
            # runs its cheap post-work, but a preemption notice drains
            # the serving loop too: stop admitting, finish in-flight,
            # flush the partial section into the report before exit)
            serve_stop = ((lambda _iters: lease.should_stop(0))
                          if lease is not None else None)
            serve_sec = _serve_from_state(config, ex, trainer.state,
                                          test_ds, tracer, total_devices,
                                          should_stop=serve_stop,
                                          timeline=timeline,
                                          ledger=ledger,
                                          roofline=roofline)
            summary["serve"] = serve_sec
            # supervisor exit policy: a serve window that lost requests
            # (unserved > 0 — lease drain, retry exhaustion, dead fleet)
            # or delivered a duplicate token must not bury it in the
            # middle of a summary — emit a structured warning event AND
            # a machine-checkable flag (0 = clean) so CI gates on it
            violations = []
            if serve_sec.get("unserved_requests"):
                violations.append(
                    f"unserved_requests="
                    f"{serve_sec['unserved_requests']}")
            if serve_sec.get("serve_duplicate_emissions"):
                violations.append(
                    f"duplicate_emissions="
                    f"{serve_sec['serve_duplicate_emissions']}")
            summary["serve_exit_policy"] = 1 if violations else 0
            if violations:
                tracer.event("serve_warning", reasons=violations,
                             preempted=serve_sec.get("preempted"))
                sink.emit("serve_warning", reasons=violations,
                          preempted=serve_sec.get("preempted"))
                print(f"warning: serve window degraded "
                      f"({', '.join(violations)}); "
                      f"serve_exit_policy=1", file=sys.stderr)
        # end-of-run report: steady-state percentiles split from compile,
        # chunk shapes actually used, watchdog/prefetch/sink health, and
        # the telemetry's own measured overhead (observability/report) —
        # emitted as its own event AND carried in the summary
        if metrics_logger is not None:
            # drain the async sink first: stats() read mid-drain would
            # report written < records, which reads as silent record loss
            metrics_logger.flush()
        if timeline is not None:
            # flush the sampled series into the trace file as bulk
            # `timeline_series` events — `analyze timeline` and the
            # Perfetto counter tracks render from the trace alone, no
            # run report needed
            timeline.emit(tracer)
        report = build_run_report(fit, watchdog=watchdog,
                                  metrics_logger=metrics_logger,
                                  tracer=tracer, serve=serve_sec,
                                  timeline=timeline, ledger=ledger,
                                  roofline=roofline)
        summary["run_report"] = report
        sink.emit("run_report", **report)
        sink.emit("summary", **summary)
        return summary
    finally:
        if lease is not None:
            # restore the previous SIGTERM disposition: a later run in
            # this process must not drain into THIS run's lease (kept
            # armed until here so the --serve window drains on it too)
            lease.uninstall()
        if ckpt_mgr is not None:
            # drain + join the checkpoint writer on ANY exit: a restart
            # (run_with_recovery) must never begin its restore with a
            # previous run's write still in flight.  reraise=False — the
            # normal path already surfaced writer errors at fit's final
            # drain, and the exception path must not mask its error.
            ckpt_mgr.close(reraise=False)
        if metrics_logger is not None:
            metrics_logger.close()  # drain + flush the async JSONL sink
        tracer.close()
        sink.close()


def _probe_collective_overlap(ex: _Experiment, global_batch: int, tracer):
    """One-time exposed-vs-hidden collective split for --grad-bucket-mb
    runs (parallel/overlap.probe_engine_overlap): spans the measurement as
    ``collective_overlap`` and emits the split as a ``collective_overlap``
    event.  Returns the split dict, or None when the engine has no probe
    (compiler-inserted collectives), the probe fails, or the job is
    multi-process (the probe's throwaway programs would have to rendezvous
    across hosts for no benefit) — a failed probe must never kill a
    training run, it only leaves the report's exposed/hidden keys None."""
    from distributed_tensorflow_tpu.parallel import overlap as overlaplib

    result = None
    error = None
    with tracer.span("collective_overlap", probe=True):
        try:
            if jax.process_count() > 1:
                error = "probe skipped on multi-process jobs"
            else:
                batch = None
                for bx, by, _bm in ex.train_ds.batches(global_batch,
                                                       shuffle=False):
                    batch = (bx, by)
                    break
                if batch is None:
                    error = "dataset yielded no probe batch"
                else:
                    xs, ys = ex.engine.shard_batch(*batch)
                    result = overlaplib.probe_engine_overlap(
                        ex.engine, xs, ys,
                        sample_x=ex.train_ds.x[: max(1, ex.n)])
                    if result is None:
                        error = ("engine has no overlap probe "
                                 "(compiler-inserted collectives)")
        except Exception as e:
            error = f"{type(e).__name__}: {e}"
    if result is None:
        tracer.event("collective_overlap", supported=False, error=error)
        return None
    tracer.event("collective_overlap", **result)
    return result


def _validate_sampling(config: ExperimentConfig, ex: _Experiment,
                       test_ds) -> None:
    """Every deterministically-knowable --sample failure is raised BEFORE
    training: a post-train ValueError would waste the whole run — and
    under --max-restarts it would be caught by run_with_recovery as a
    restartable crash and re-train up to max_restarts more times, failing
    identically after each."""
    from distributed_tensorflow_tpu.models.gpt import GPTLM, GPTPipeEmbed

    if config.sample_tokens < 0:
        raise ValueError(
            f"--sample must be positive, got {config.sample_tokens}")
    if _is_pipeline(ex.engine):
        # pipeline runs sample via the engine's sequential-forward decode
        # (engines/pipeline.py generate) — GPT stage families only
        if not isinstance(ex.engine.embed, GPTPipeEmbed):
            raise ValueError(
                f"--sample under --pipeline-parallel needs GPT decoder "
                f"stages (vocab-head output); this run's embed stage is "
                f"{type(ex.engine.embed).__name__}")
        if ex.engine.moe:
            # raised pre-train (a post-train raise would waste the run):
            # the fixed-length decode's padding-invisibility argument is a
            # causal-attention property — MoE routing's capacity-limited
            # dispatch sees the zero padding (engines/pipeline.py generate)
            raise ValueError(
                "--sample is unavailable for MoE pipeline stages "
                "(-pp with --num-experts): expert routing's capacity "
                "depends on every buffer position, so the fixed-length "
                "decode would not be the true greedy continuation — "
                "sample a dense-FFN pipeline run, or train MoE without "
                "-pp and use the KV-cache sampler")
        max_len = ex.engine.embed.max_len
    else:
        model = ex.engine.model
        if not isinstance(model, GPTLM):
            raise ValueError(
                f"--sample requires the GPT causal LM; the resolved model "
                f"is {type(model).__name__}")
        max_len = model.max_len
    plen = config.sample_prompt_len
    if plen < 1 or plen > test_ds.x.shape[1]:
        raise ValueError(
            f"--sample-prompt-len {plen} outside the test sequences' "
            f"length {test_ds.x.shape[1]}")
    if plen + config.sample_tokens > max_len:
        raise ValueError(
            f"--sample-prompt-len {plen} + --sample {config.sample_tokens} "
            f"exceeds the model's capacity max_len={max_len}")
    n_prompts = ex.mesh.shape.get(meshlib.DATA_AXIS, 1)
    if len(test_ds.x) < n_prompts:
        raise ValueError(
            f"--sample takes one prompt per data shard ({n_prompts}), but "
            f"the test split has only {len(test_ds.x)} rows")


def _sample_from_state(config: ExperimentConfig, ex: _Experiment, state,
                       test_ds) -> dict[str, Any]:
    """--sample N: greedy-decode N tokens per prompt from the trained
    params (models/gpt.py ``generate`` — KV-cache sampler; multi-device
    when the run's mesh has >1 device: batch over 'data', Megatron layout
    kept under a 'model' axis).

    Prompts are the first ``sample_prompt_len`` tokens of one test row per
    data-axis shard (divisibility with the 'data' axis by construction).
    Greedy, so the recorded continuation is a deterministic function of
    the final params — reproducible evidence of what the model learned,
    not a dice roll.  Engines whose state stacks per-device copies
    (async/gossip) are averaged first via their ``eval_params`` — the same
    consensus model their evaluation uses.  Pipeline engines decode via
    their sequential-forward ``generate`` (engines/pipeline.py) — stage
    params stay pipe-stacked; there is no KV cache to thread through the
    schedule.  Arguments were validated pre-train (_validate_sampling)."""
    from distributed_tensorflow_tpu.models.gpt import generate

    n_prompts = ex.mesh.shape.get(meshlib.DATA_AXIS, 1)
    prompts = np.asarray(test_ds.x[:n_prompts, :config.sample_prompt_len],
                         dtype=np.int32)
    if _is_pipeline(ex.engine):
        # engine.generate returns prompt+continuation; slice to the
        # continuation so 'samples' has ONE schema — (B, N) decoded
        # tokens — regardless of engine (models/gpt.py generate already
        # returns continuations only)
        full = np.asarray(ex.engine.generate(state, prompts,
                                             config.sample_tokens))
        toks = full[:, config.sample_prompt_len:]
    else:
        get_params = getattr(ex.engine, "eval_params", None)
        params = (get_params(state) if get_params is not None
                  else state.params)
        mesh = ex.mesh if ex.mesh.devices.size > 1 else None
        toks = np.asarray(generate(ex.engine.model, params, prompts,
                                   config.sample_tokens, greedy=True,
                                   mesh=mesh))
    return {
        "sample_prompts": prompts.tolist(),
        "samples": toks.tolist(),
    }


def parse_draft_config(spec: str) -> dict[str, int] | None:
    """``--serve-draft-config`` parser: the literal ``'self'`` → None
    (the draft IS the served model and shares its params — accept rate 1,
    the mechanism/parity configuration) or ``'key=int,...'`` GPT size
    overrides (hidden/layers/heads/ffn/kv_heads; vocab and max_len always
    inherit from the served model — draft proposals must be target
    tokens, and the draft mirrors every slot position)."""
    if spec == "self":
        return None
    allowed = ("ffn", "heads", "hidden", "kv_heads", "layers")
    out: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, val = part.partition("=")
        key = key.strip()
        if not eq or key not in allowed:
            raise ValueError(
                f"--serve-draft-config entries must be key=int with key "
                f"in {allowed} (or the literal 'self'); got '{part}' — "
                f"vocab/max_len inherit from the served model")
        try:
            out[key] = int(val)
        except ValueError:
            raise ValueError(
                f"--serve-draft-config value for '{key}' must be an "
                f"int, got '{val.strip()}'") from None
    if not out:
        raise ValueError(
            "--serve-draft-config needs at least one key=int override "
            "(or the literal 'self')")
    return out


def parse_disaggregate(spec: str) -> tuple[int, int]:
    """``--serve-disaggregate`` parser: ``'P:D'`` → (prefill_replicas,
    decode_replicas).  Both sides must be >= 1 — a disaggregated fleet
    needs somewhere to prefill AND somewhere to decode (the handoff has
    no same-replica fallback by design: falling back would silently
    reintroduce the prefill/decode interference the mode exists to
    remove)."""
    p_s, colon, d_s = spec.partition(":")
    try:
        if not colon:
            raise TypeError
        p, d = int(p_s), int(d_s)
    except (TypeError, ValueError):
        raise ValueError(
            f"--serve-disaggregate must be P:D (prefill:decode replica "
            f"counts, e.g. 1:2), got '{spec}'") from None
    if p < 1 or d < 1:
        raise ValueError(
            f"--serve-disaggregate needs at least one prefill and one "
            f"decode replica, got '{spec}'")
    return p, d


def _resolve_serve_kv_dtype(name: str):
    """``--serve-kv-dtype`` resolver: float dtype names via
    models.resolve_dtype, plus ``'int8'`` — the quantized slot table
    (int8 K/V + per-vector f32 scales, SlotKVCache kv_dtype)."""
    if name == "int8":
        return "int8"
    try:
        return modellib.resolve_dtype(name)
    except KeyError:
        raise ValueError(
            f"--serve-kv-dtype '{name}' unknown: float32/bfloat16/"
            f"float16 (and aliases) or int8") from None


def _validate_serving(config: ExperimentConfig, ex: _Experiment,
                      test_ds) -> None:
    """Pre-train validation of the --serve window (same contract as
    _validate_sampling: a post-train raise would waste the whole run and,
    under --max-restarts, re-train to fail identically)."""
    from distributed_tensorflow_tpu.models.gpt import GPTLM

    if config.serve_requests < 0:
        raise ValueError(
            f"--serve must be positive, got {config.serve_requests}")
    if config.serve_slots < 1:
        raise ValueError(
            f"--serve-slots must be positive, got {config.serve_slots}")
    if config.serve_max_new < 1:
        raise ValueError(
            f"--serve-max-new must be positive, got {config.serve_max_new}")
    if _is_pipeline(ex.engine):
        raise ValueError(
            "--serve needs flat GPTLM params for the slot KV cache; a "
            "pipeline engine's stage params are pipe-stacked — train "
            "without -pp (or restore the checkpoint into a non-pipeline "
            "layout) to serve")
    model = ex.engine.model
    if not isinstance(model, GPTLM):
        raise ValueError(
            f"--serve requires the GPT causal LM; the resolved model is "
            f"{type(model).__name__}")
    if config.serve_prefill_chunk < 0:
        raise ValueError(
            f"--serve-prefill-chunk must be >= 0 (0 = monolithic "
            f"prefill), got {config.serve_prefill_chunk}")
    if config.serve_prefix_cache < 0:
        raise ValueError(
            f"--serve-prefix-cache must be >= 0 (0 = off), got "
            f"{config.serve_prefix_cache}")
    if config.serve_prefix_block < 1:
        raise ValueError(
            f"--serve-prefix-block must be positive, got "
            f"{config.serve_prefix_block}")
    if config.serve_shared_prefix < 0:
        raise ValueError(
            f"--serve-shared-prefix must be >= 0, got "
            f"{config.serve_shared_prefix}")
    if config.serve_slo_ttft <= 0 or config.serve_slo_itl <= 0:
        raise ValueError(
            f"--serve-slo-ttft/--serve-slo-itl must be positive seconds, "
            f"got {config.serve_slo_ttft}/{config.serve_slo_itl}")
    if config.serve_queue_cap < 0:
        raise ValueError(
            f"--serve-queue-cap must be >= 0 (0 = unbounded admission), "
            f"got {config.serve_queue_cap}")
    if config.serve_draft_k < 1:
        raise ValueError(
            f"--serve-draft-k must be positive, got "
            f"{config.serve_draft_k}")
    if config.serve_draft_config is not None:
        # a malformed draft spec must fail BEFORE the training budget is
        # spent, like every other deterministically-knowable serve flag
        parse_draft_config(config.serve_draft_config)
    if config.serve_kv_dtype:
        _resolve_serve_kv_dtype(config.serve_kv_dtype)
    if config.serve_kv_layout not in ("monolithic", "paged"):
        raise ValueError(
            f"--serve-kv-layout must be 'monolithic' or 'paged', got "
            f"{config.serve_kv_layout!r}")
    if config.serve_paged_block < 0 or config.serve_paged_blocks < 0:
        raise ValueError(
            f"--serve-paged-block/--serve-paged-blocks must be >= 0, got "
            f"{config.serve_paged_block}/{config.serve_paged_blocks}")
    if config.serve_kv_layout != "paged" and (config.serve_paged_block
                                              or config.serve_paged_blocks):
        raise ValueError(
            "--serve-paged-block/--serve-paged-blocks need "
            "--serve-kv-layout paged")
    if config.serve_kv_layout == "paged":
        # the paged pool's fatal misconfigurations are all knowable
        # pre-train: block granularity must tile max_len, and with the
        # prefix pool on it must equal the prefix block (hits alias
        # physical blocks by pointer)
        block = config.serve_paged_block or config.serve_prefix_block
        if model.max_len % block:
            raise ValueError(
                f"--serve-paged-block {block} must divide the model's "
                f"max_len={model.max_len}")
        if (config.serve_prefix_cache and config.serve_paged_block
                and config.serve_paged_block != config.serve_prefix_block):
            raise ValueError(
                f"--serve-paged-block ({config.serve_paged_block}) must "
                f"equal --serve-prefix-block "
                f"({config.serve_prefix_block}) when the prefix pool is "
                f"on: pool hits alias physical blocks")
    if config.serve_replicas < 1:
        raise ValueError(
            f"--serve-replicas must be >= 1, got {config.serve_replicas}")
    n_fleet = max(config.serve_replicas, 1)
    if config.serve_disaggregate is not None:
        # round 18: --serve-disaggregate P:D builds a heterogeneous
        # fleet of P prefill + D decode replicas (overriding
        # --serve-replicas); the spec and its interactions are all
        # knowable pre-train
        p, d = parse_disaggregate(config.serve_disaggregate)
        n_fleet = p + d
        if config.serve_draft_config is not None:
            raise ValueError(
                "--serve-disaggregate cannot combine with "
                "--serve-draft-config: speculative decoding drafts in "
                "slot lockstep with its target table, which a KV "
                "handoff across replicas would break")
        if config.serve_hot_swap:
            raise ValueError(
                "--serve-disaggregate cannot combine with "
                "--serve-hot-swap: the swap drill drains replicas "
                "role-blind and could leave zero admitting prefill "
                "replicas")
    if config.serve_routing not in ("least-loaded", "affinity"):
        raise ValueError(
            f"--serve-routing must be 'least-loaded' or 'affinity', "
            f"got {config.serve_routing!r}")
    if config.serve_routing == "affinity" and not config.serve_prefix_cache:
        raise ValueError(
            "--serve-routing affinity keys on the prefix pool's block "
            "digests; enable --serve-prefix-cache (> 0) or use "
            "least-loaded routing")
    if config.serve_autoscale is not None:
        from distributed_tensorflow_tpu.serving.fleet import AutoscalePolicy

        # round 20: composes with --serve-disaggregate — the fleet
        # drives each role pool independently, clamping the MIN:MAX
        # range to the pool's size; only the homogeneous range is
        # checked against the whole fleet here
        policy = AutoscalePolicy.parse(config.serve_autoscale)
        n_max = policy.max_replicas or n_fleet
        if config.serve_disaggregate is None and n_max > n_fleet:
            raise ValueError(
                f"--serve-autoscale max ({n_max}) exceeds the built "
                f"fleet (--serve-replicas {n_fleet}): autoscale wakes "
                f"dormant replicas, it cannot build new ones")
    if config.serve_multi_step is not None and config.serve_multi_step < 1:
        raise ValueError(
            f"--serve-multi-step must be >= 1 fused decode iterations "
            f"per dispatch, got {config.serve_multi_step}")
    if config.serve_watchdog_s < 0:
        raise ValueError(
            f"--serve-watchdog must be >= 0 (0 = off), got "
            f"{config.serve_watchdog_s}")
    if config.serve_fault_spec:
        # fault grammar + replica bounds checked pre-train, like every
        # other deterministically-knowable serve flag
        from distributed_tensorflow_tpu.serving.fleet import FaultInjector

        for fault in FaultInjector.parse(config.serve_fault_spec):
            if fault.replica >= n_fleet:
                raise ValueError(
                    f"--serve-fault-spec targets replica {fault.replica} "
                    f"but the fleet has {n_fleet} replicas")
    plen = config.serve_prompt_len
    if plen < 1 or plen > test_ds.x.shape[1]:
        raise ValueError(
            f"--serve-prompt-len {plen} outside the test sequences' "
            f"length {test_ds.x.shape[1]}")
    total_prompt = plen + config.serve_shared_prefix
    if total_prompt + config.serve_max_new > model.max_len:
        raise ValueError(
            f"--serve-shared-prefix {config.serve_shared_prefix} + "
            f"--serve-prompt-len {plen} + --serve-max-new "
            f"{config.serve_max_new} exceeds the model's capacity "
            f"max_len={model.max_len}")


def _serve_from_state(config: ExperimentConfig, ex: _Experiment, state,
                      test_ds, tracer, total_devices: int,
                      should_stop=None, timeline=None,
                      ledger=None, roofline=None) -> dict[str, Any]:
    """--serve N: run a continuous-batching serving window over the
    trained params (serving/SlotKVCache + ContinuousBatcher) and return
    the run report's ``serve`` section.

    Prompts are test-split rows (``--serve-prompt-len`` tokens each,
    wrapping when N exceeds the split); arrivals are all-at-zero under the
    wall clock, so with N > slots the queue drains continuously as slots
    free — admission, eviction and queue wait are all exercised without
    sleeping, and TTFT percentiles include the queue time (BASELINE.md
    rule).  The slot table rides the run's mesh when its axes are the
    GSPMD serving set ({data, model}) and the slot count divides the data
    axis; otherwise it serves replicated.  Greedy decode: like --sample,
    the recorded window is a deterministic function of the final params.
    Engines whose state stacks per-device copies (async/gossip) serve
    their consensus ``eval_params``, same as evaluation and sampling.

    SLO observability (round 13): every window runs under an SLOMonitor
    (``--serve-slo-ttft``/``--serve-slo-itl``, p99 ITL per request) so the
    serve section always carries ``serve_goodput_under_slo`` and the
    p50/p95/p99 phase percentiles; ``--serve-queue-cap`` arms the
    bounded-admission overload mode.  ``should_stop`` is the lease-drain
    hook: a SIGTERM'd serve window stops admitting, finishes in-flight
    requests, and its partial section still flushes into the report."""
    from distributed_tensorflow_tpu.observability import (
        SLOMonitor, serve_section)
    from distributed_tensorflow_tpu.serving import (
        ContinuousBatcher, Request, SlotKVCache)

    get_params = getattr(ex.engine, "eval_params", None)
    params = get_params(state) if get_params is not None else state.params
    mesh = None
    if (ex.mesh.devices.size > 1
            and set(ex.mesh.axis_names) <= {meshlib.DATA_AXIS,
                                            meshlib.MODEL_AXIS}
            and config.serve_slots
            % ex.mesh.shape.get(meshlib.DATA_AXIS, 1) == 0):
        mesh = ex.mesh
    kv_dtype = None
    if config.serve_kv_dtype:
        # --serve-kv-dtype bfloat16: store the KV slot table in bf16 —
        # half the KV memory per slot (double the slots per chip at equal
        # HBM); greedy tokens stay oracle-exact on the shipped models
        # (tests/test_serving.py), the attention math still runs at the
        # model's compute dtype via promotion.  int8 halves bf16's
        # payload again (int8 K/V + per-vector f32 scales); token parity
        # vs the bf16 oracle is tolerance-based, not bitwise.
        kv_dtype = _resolve_serve_kv_dtype(config.serve_kv_dtype)
    # fleet mode (--serve-replicas / --serve-fault-spec / --serve-hot-
    # swap): N independent slot tables behind the ReplicaSet supervisor —
    # a fault spec or a hot-swap drill forces the fleet path even at one
    # replica, so the supervision/journal machinery is what gets tested.
    # Round 18's heterogeneous flags (--serve-disaggregate P:D roles,
    # --serve-routing affinity, --serve-autoscale MIN:MAX) are fleet
    # concepts, so any of them forces the fleet path too.
    roles = None
    if config.serve_disaggregate is not None:
        n_prefill, n_decode = parse_disaggregate(config.serve_disaggregate)
        roles = ["prefill"] * n_prefill + ["decode"] * n_decode
        n_replicas = n_prefill + n_decode
    else:
        n_replicas = max(config.serve_replicas, 1)
    fleet = (n_replicas > 1 or bool(config.serve_fault_spec)
             or config.serve_hot_swap or roles is not None
             or config.serve_routing != "least-loaded"
             or config.serve_autoscale is not None)
    kv_kwargs: dict[str, Any] = dict(
        mesh=mesh, kv_dtype=kv_dtype,
        prefix_cache_blocks=config.serve_prefix_cache,
        prefix_block=config.serve_prefix_block)
    if ledger is not None:
        # conditional-kwarg pattern (same as the paged block below): the
        # flag-off construction stays byte-identical, and with the ledger
        # on every kv jit site routes through ledger.jit — observed
        # compiles, memory_analysis captured, same executable dispatched
        kv_kwargs.update(ledger=ledger)
    if config.serve_kv_layout == "paged":
        # --serve-kv-layout paged: SlotKVCache's __new__ dispatches to
        # PagedSlotKVCache — refcounted block pool, zero-copy prefix
        # aliasing, fused Pallas decode attention.  The kwargs are only
        # passed under paged so the monolithic construction stays
        # byte-identical (program-set pin).
        kv_kwargs.update(kv_layout="paged",
                         paged_blocks=config.serve_paged_blocks,
                         paged_block=config.serve_paged_block)
    kv = SlotKVCache(ex.engine.model, params, config.serve_slots,
                     **kv_kwargs)
    # --roofline serve half: rebuild the cost model FROM THE KV TABLE so
    # the byte accounting reflects the layout actually serving (storage
    # dtype, paged blocks, measured param bytes) — the train-side model
    # knows none of that.  Device peaks / device count carry over.
    serve_roofline = None
    if roofline is not None:
        from distributed_tensorflow_tpu.observability.roofline import (
            Roofline)

        serve_roofline = Roofline.for_kv(
            kv, roofline.peaks.device_kind if roofline.peaks else None,
            total_devices)
    draft_kv = None
    if config.serve_draft_config:
        # --serve-draft-config: speculative decoding — the draft runs its
        # own full-precision SlotKVCache in slot lockstep with the target
        # table.  'self' shares the served model AND params (zero extra
        # param memory; the mechanism/parity configuration); a size spec
        # builds a fresh GPT at those dims (vocab/max_len inherited) from
        # the run seed — production use restores a trained draft here.
        import jax.numpy as jnp

        overrides = parse_draft_config(config.serve_draft_config)
        model = ex.engine.model
        if overrides is None:
            draft_model, draft_params = model, params
        else:
            draft_model = modellib.create_model(
                "gpt", num_classes=int(model.vocab_size),
                max_len=int(model.max_len), dropout_rate=0.0,
                dtype=model.dtype, **overrides)
            dummy = jnp.zeros((1, min(8, int(model.max_len))), jnp.int32)
            draft_params = jax.jit(
                lambda k: draft_model.init(k, dummy, train=False)
            )(jax.random.key(config.seed))["params"]
        draft_kv = SlotKVCache(draft_model, draft_params,
                               config.serve_slots, mesh=mesh)
    rows = np.asarray(test_ds.x, np.int32)
    plen = config.serve_prompt_len
    # --serve-shared-prefix: a fixed synthetic system prompt every request
    # shares (deterministic from the run seed) — the traffic shape the
    # prefix pool exists for; with the pool on, every admission after the
    # first reuses the shared blocks instead of recomputing them
    shared = np.zeros(0, np.int32)
    if config.serve_shared_prefix:
        vocab = int(ex.engine.model.vocab_size)
        shared = np.random.default_rng(config.seed).integers(
            0, vocab, config.serve_shared_prefix).astype(np.int32)
    requests = [
        Request(rid=i,
                prompt=np.concatenate([shared, rows[i % len(rows), :plen]]),
                max_new_tokens=config.serve_max_new, arrival_s=0.0)
        for i in range(config.serve_requests)]
    slo = SLOMonitor(config.serve_slo_ttft, config.serve_slo_itl)
    if fleet:
        from distributed_tensorflow_tpu.serving.fleet import (
            FaultInjector, ReplicaSet, build_replica_kvs)

        if roles is None:
            kvs = [kv] + build_replica_kvs(
                ex.engine.model, params, n_replicas - 1,
                config.serve_slots, **kv_kwargs)
        else:
            # disaggregated fleets keep the prefix pool prefill-side
            # only: decode replicas receive finished KV via handoff and
            # never prefill, so a warm pool there would be dead memory —
            # and the affinity router's hit accounting should reflect
            # where reuse can actually happen.  Replica 0 (the ``kv``
            # built above, pool included) is always a prefill replica
            # because roles lists prefills first.
            decode_kwargs = dict(kv_kwargs)
            decode_kwargs["prefix_cache_blocks"] = 0
            kvs = [kv]
            for role in roles[1:]:
                kvs += build_replica_kvs(
                    ex.engine.model, params, 1, config.serve_slots,
                    **(kv_kwargs if role == "prefill" else decode_kwargs))
        draft_kvs = None
        if draft_kv is not None:
            draft_kvs = [draft_kv] + build_replica_kvs(
                draft_model, draft_params, n_replicas - 1,
                config.serve_slots, mesh=mesh)
        injector = (FaultInjector(config.serve_fault_spec,
                                  seed=config.seed)
                    if config.serve_fault_spec else None)
        fleet_kwargs: dict[str, Any] = {}
        if roles is not None:
            # conditional-kwarg pattern (same as the paged block above):
            # the round-17 fleet construction stays byte-identical when
            # the round-18 flags are off
            fleet_kwargs.update(roles=roles)
        if config.serve_routing != "least-loaded":
            fleet_kwargs.update(routing=config.serve_routing)
        if config.serve_autoscale is not None:
            fleet_kwargs.update(autoscale=config.serve_autoscale)
        if config.serve_multi_step is not None:
            fleet_kwargs.update(multi_step=config.serve_multi_step)
        replica_set = ReplicaSet(
            kvs, tracer=tracer,
            prefill_chunk=config.serve_prefill_chunk,
            queue_cap=config.serve_queue_cap, slo=slo,
            draft_kvs=draft_kvs, draft_k=config.serve_draft_k,
            watchdog_timeout_s=config.serve_watchdog_s,
            fault_injector=injector, timeline=timeline,
            roofline=serve_roofline, **fleet_kwargs)
        if config.serve_hot_swap:
            # the drill: re-install the SAME trained params after half
            # the window — proves drain + swap_generations + N-1
            # availability with greedy tokens unchanged; a real rollout
            # passes new checkpoint params here
            replica_set.schedule_swap(
                params, after_completions=max(config.serve_requests // 2,
                                              1))
        with tracer.span("serve", requests=config.serve_requests,
                         slots=config.serve_slots, replicas=n_replicas):
            try:
                summary = replica_set.run(requests,
                                          should_stop=should_stop)
            finally:
                replica_set.close()
        return serve_section(summary, total_devices, tracer=tracer)
    batcher_kwargs: dict[str, Any] = {}
    if config.serve_multi_step is not None:
        # conditional-kwarg pattern: the round-19 batcher construction
        # stays byte-identical with the flag off
        batcher_kwargs.update(multi_step=config.serve_multi_step)
    with tracer.span("serve", requests=config.serve_requests,
                     slots=config.serve_slots):
        summary = ContinuousBatcher(
            kv, tracer=tracer,
            prefill_chunk=config.serve_prefill_chunk,
            slo=slo,
            queue_cap=config.serve_queue_cap,
            should_stop=should_stop,
            draft_kv=draft_kv, draft_k=config.serve_draft_k,
            timeline=timeline,
            roofline=serve_roofline, **batcher_kwargs).run(requests)
    return serve_section(summary, total_devices, tracer=tracer)


def steps_to_accuracy(
    config: ExperimentConfig,
    target: float,
    max_steps: int = 10_000,
    eval_every: int = 50,
) -> dict[str, Any]:
    """Steps-to-target measurement (BASELINE.md north star: steps-to-97%).

    Counts *global* batches, the normalization BASELINE.md requires when
    comparing against the reference's sequential-apply sync PS
    (SURVEY.md §2.4(1)).  Runs through ``Trainer.fit`` — ONE training loop
    in the codebase, so the measured path gets the hardened loop's
    throttling/nan-guard for free — with adaptive eval cadence: every
    ``eval_every`` steps far from the target, every ≤10 steps once within
    0.05 of it, so the returned step count has ≤10-step resolution.
    """
    import math

    from distributed_tensorflow_tpu.engines.allreduce import Trainer

    if config.schedule_horizon_steps is None:
        # this loop runs up to max_steps, far past config.epochs — an
        # epochs-derived LR horizon would decay to 0 almost immediately and
        # the target would silently never be reached
        config = dataclasses.replace(config,
                                     schedule_horizon_steps=max_steps)
    ex = _setup(config)
    trainer = Trainer(None, engine=ex.engine, seed=config.seed)
    steps_per_epoch = max(len(ex.train_ds) // ex.global_batch, 1)
    epochs = math.ceil(max_steps / steps_per_epoch) + 1

    t0 = time.perf_counter()
    fit = trainer.fit(
        ex.train_ds, epochs=epochs, batch_size=ex.global_batch, log_every=0,
        max_steps=max_steps, eval_ds=ex.test_ds, target_accuracy=target,
        eval_every=eval_every, eval_batch=config.eval_batch,
        # steps_per_call auto-downshifts to 1 under target_accuracy (the
        # steps-to-target resolution IS the per-step cadence); an explicit
        # config value still passes through for chunk-boundary eval
        steps_per_call=config.steps_per_call, prefetch=config.prefetch)
    return {
        "reached": bool(fit["reached_target"]),
        "steps": fit["steps"],
        "accuracy": fit["eval_accuracy"],
        "elapsed_s": time.perf_counter() - t0,
        # measured, not assumed: the gap between the crossing eval and the
        # one before it (a >0.05 jump between coarse evals is resolved at
        # eval_every, not 10)
        "step_resolution": fit["eval_resolution"],
        "synthetic": bool(getattr(ex.train_ds, "synthetic", False)),
    }
