"""Length-prefixed socket framing — control-plane parity with the reference.

The reference frames every message as a 4-byte big-endian length plus a
pickled payload (reference centralized/network.py:4-28) and uses it both for
the gradient/weight wire and the out-of-band supervisor channel.  In this
framework tensors NEVER travel over sockets (XLA collectives own the data
plane); this module exists only for the supervisor/benchmark-harness channel
(reference server.py:121-124, 182-187; dist_keras.py:34-58) and for any
external tool speaking the reference's protocol.

Payloads are JSON by default.  Pickle decode of *incoming* data is opt-in
(``allow_pickle=True``) because unpickling untrusted bytes executes code;
pickle *encode* is provided for compatibility with reference-style listeners.
"""

from __future__ import annotations

import ctypes
import json
import pickle
import socket
import struct
from typing import Any

_LEN = struct.Struct(">I")  # 4-byte big-endian length, reference network.py:6


def _native_for(sock: socket.socket):
    """Native transport lib, when usable for this socket.

    Python sockets with a timeout set their fd non-blocking, which the C
    blocking send/recv loops don't handle — those sockets stay on the
    Python path.  The framing bytes are identical either way.
    """
    if sock.gettimeout() is not None:
        return None
    from distributed_tensorflow_tpu import native

    return native.load()


def send_bytes(sock: socket.socket, payload: bytes) -> None:
    lib = _native_for(sock)
    if lib is not None:
        if lib.dtw_send_frame(sock.fileno(), payload, len(payload)) != 0:
            raise ConnectionError("native send_frame failed")
        return
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_bytes(sock: socket.socket) -> bytes | None:
    lib = _native_for(sock)
    if lib is not None:
        n = lib.dtw_recv_header(sock.fileno())
        if n == -1:  # orderly close (DTW_CLOSED), reference recvall None
            return None
        if n < 0:
            raise ConnectionError("native recv_header failed")
        buf = ctypes.create_string_buffer(max(int(n), 1))
        rc = lib.dtw_recv_body(sock.fileno(), buf, int(n))
        if rc == -1:  # closed mid-payload
            return None
        if rc < 0:
            raise ConnectionError("native recv_body failed")
        return buf.raw[: int(n)]
    header = recvall(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    return recvall(sock, n)


def recvall(sock: socket.socket, n: int) -> bytes | None:
    """Blocking read of exactly n bytes (reference network.py:20-28)."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def send_msg(sock: socket.socket, obj: Any, *, use_pickle: bool = False) -> None:
    data = pickle.dumps(obj, -1) if use_pickle else json.dumps(obj).encode()
    send_bytes(sock, data)


def recv_msg(sock: socket.socket, *, allow_pickle: bool = False) -> Any | None:
    data = recv_bytes(sock)
    if data is None:
        return None
    if allow_pickle:
        try:
            return json.loads(data)
        except (UnicodeDecodeError, json.JSONDecodeError):
            return pickle.loads(data)
    return json.loads(data)
