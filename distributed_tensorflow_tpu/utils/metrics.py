"""Metrics, step timing, and profiling.

The reference's observability is print() plus one wall-clock window
(SURVEY.md §5: server.py:72-119 prints; logging actively disabled in
dist_keras.py:67-68).  Here: structured per-step metric records behind an
async crash-durable JSONL sink (observability/sink.py), step-time
percentiles for the benchmark harness with compile split out, and an XLA
profiler hook (`jax.profiler.trace`) whose window shares a name with the
structured span timeline (observability/trace.py).
"""

from __future__ import annotations

import contextlib
import time
from pathlib import Path
from typing import Any, Iterator

from distributed_tensorflow_tpu.observability.sink import (
    SCHEMA_VERSION, AsyncJsonlSink)


class StepTimer:
    """Wall-clock per-step timing with percentile summary.

    The reference times one global window between barriers (reference
    server.py:76-79, 115-119); per-step percentiles additionally separate
    compile from steady state.  ``compile_steps`` is how many leading
    entries carry the first-call XLA compile — 1 for a single-step loop,
    the first chunk's length for the scanned drain (its compile is smeared
    over its k per-step averages); the Trainer sets it as it dispatches.
    """

    def __init__(self):
        self.times: list[float] = []
        self.compile_steps = 1
        self._t0: float | None = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.times.append(time.perf_counter() - self._t0)
        self._t0 = None
        return False

    def summary(self) -> dict[str, float | None]:
        if not self.times:
            return {}
        xs = sorted(self.times)
        n = len(xs)
        pick = lambda q, s: s[min(len(s) - 1, int(q * len(s)))]  # noqa: E731
        c = min(max(self.compile_steps, 1), n)
        # a run that never left the compile chunk has NO steady state —
        # the steady_* keys go None rather than silently reporting
        # compile-smeared entries as steady percentiles (compile_s
        # already carries those seconds)
        steady = self.times[c:]
        steady_sorted = sorted(steady)
        return {
            "steps": n,
            "total_s": sum(self.times),
            "first_step_s": self.times[0],   # includes XLA compile
            "compile_s": sum(self.times[:c]),  # the whole compile-smeared
                                               # prefix (c = first chunk)
            "steady_mean_s": (sum(steady) / len(steady)) if steady else None,
            "steady_p50_s": pick(0.50, steady_sorted) if steady else None,
            "steady_p95_s": pick(0.95, steady_sorted) if steady else None,
            "p50_s": pick(0.50, xs),
            "p90_s": pick(0.90, xs),
            "p95_s": pick(0.95, xs),
            "p99_s": pick(0.99, xs),
        }


class MetricsLogger:
    """Per-step metrics sink (compose with utils.supervisor.ResultSink for
    run-level events).

    Records are kept in memory (``records``) and — when ``path`` is given —
    written as JSONL through an :class:`AsyncJsonlSink`: one bounded-queue
    put per record on the caller's thread, a background thread doing the
    line-buffered I/O.  Because emission never blocks, the Trainer keeps
    its ``steps_per_call`` chunking with a metrics logger attached (no
    downshift): per-step records ride the scan's stacked trajectory and
    are logged at chunk flush, step-exact and bitwise identical to k=1
    (tests/test_steady_state.py).

    Crash durability: every line is complete-or-absent (the sink writes
    one flushed line per record), every record carries ``schema_version``,
    and ``close()`` drains and flushes.  ``overhead_s`` accumulates this
    logger's own host cost for the run report's telemetry budget.
    """

    def __init__(self, path: str | Path | None = None, log_every: int = 1,
                 queue_size: int = 8192):
        self.path = Path(path) if path else None
        self.log_every = log_every
        self.records: list[dict] = []
        self.overhead_s = 0.0
        self._sink = (AsyncJsonlSink(self.path, maxsize=queue_size)
                      if self.path else None)

    def should_log(self, step: int) -> bool:
        """Single home of the throttle policy — callers that must avoid even
        *computing* metric values (host-device sync) check this first."""
        return not self.log_every or step % self.log_every == 0

    def log(self, step: int, **metrics: Any) -> None:
        """Record one step's metrics.  ``time`` is the wall clock AT LOG
        TIME: under a chunked drain (``steps_per_call=k``) a chunk's k
        records are logged in one burst at chunk flush, so ``time`` marks
        the flush, not the step — derive per-step timing from the run
        report's step_time percentiles (or the trace spans), never from
        gaps between metric records.  The metric VALUES are step-exact
        and k-invariant (tests/test_steady_state.py parity)."""
        if not self.should_log(step):
            return
        t0 = time.perf_counter()
        rec = {"schema_version": SCHEMA_VERSION, "step": step,
               "time": time.time(),
               **{k: float(v) for k, v in metrics.items()}}
        self.records.append(rec)
        if self._sink is not None:
            self._sink.write(rec)
        self.overhead_s += time.perf_counter() - t0

    @property
    def dropped(self) -> int:
        """Records the bounded queue had to drop (0 without a file sink)."""
        return self._sink.dropped if self._sink is not None else 0

    def stats(self) -> dict[str, int]:
        out = {"records": len(self.records), "dropped": self.dropped}
        if self._sink is not None:
            out["written"] = self._sink.written
        return out

    def flush(self) -> None:
        if self._sink is not None:
            self._sink.flush()

    def close(self) -> None:
        """Drain + flush the async sink (flush-on-close contract)."""
        if self._sink is not None:
            self._sink.close()

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


@contextlib.contextmanager
def profile(trace_dir: str | Path | None, tracer=None) -> Iterator[None]:
    """XLA profiler window; view with TensorBoard's profile plugin / XProf.
    No-op when trace_dir is None.  ``tracer``, when given, records the
    window as an ``xprof`` span, so the span timeline and the XProf trace
    cover the same region under the same name (the tracer's spans inside
    the window additionally appear in XProf via TraceAnnotation)."""
    if trace_dir is None:
        yield
        return
    import jax

    from distributed_tensorflow_tpu.observability.trace import NULL_TRACER

    t = tracer if tracer is not None else NULL_TRACER
    with t.span("xprof", trace_dir=str(trace_dir)):
        with jax.profiler.trace(str(trace_dir)):
            yield
