"""Metrics, step timing, and profiling.

The reference's observability is print() plus one wall-clock window
(SURVEY.md §5: server.py:72-119 prints; logging actively disabled in
dist_keras.py:67-68).  Here: structured per-step metric records, step-time
percentiles for the benchmark harness, and an XLA profiler hook
(`jax.profiler.trace`) whose output loads in TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import json
import time
from pathlib import Path
from typing import Any, Iterator


class StepTimer:
    """Wall-clock per-step timing with percentile summary.

    The reference times one global window between barriers (reference
    server.py:76-79, 115-119); per-step percentiles additionally separate
    compile (first step) from steady state."""

    def __init__(self):
        self.times: list[float] = []
        self._t0: float | None = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.times.append(time.perf_counter() - self._t0)
        self._t0 = None
        return False

    def summary(self) -> dict[str, float]:
        if not self.times:
            return {}
        xs = sorted(self.times)
        n = len(xs)
        pick = lambda q: xs[min(n - 1, int(q * n))]  # noqa: E731
        steady = xs[1:] if n > 1 else xs  # drop the compile step
        return {
            "steps": n,
            "total_s": sum(self.times),
            "first_step_s": self.times[0],  # includes XLA compile
            "steady_mean_s": sum(steady) / len(steady),
            "p50_s": pick(0.50),
            "p90_s": pick(0.90),
            "p99_s": pick(0.99),
        }


class MetricsLogger:
    """JSONL per-step metrics sink (compose with utils.supervisor.ResultSink
    for run-level events)."""

    def __init__(self, path: str | Path | None = None, log_every: int = 1):
        self.path = Path(path) if path else None
        self.log_every = log_every
        self.records: list[dict] = []

    def should_log(self, step: int) -> bool:
        """Single home of the throttle policy — callers that must avoid even
        *computing* metric values (host-device sync) check this first."""
        return not self.log_every or step % self.log_every == 0

    def log(self, step: int, **metrics: Any) -> None:
        if not self.should_log(step):
            return
        rec = {"step": step, "time": time.time(),
               **{k: float(v) for k, v in metrics.items()}}
        self.records.append(rec)
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")


@contextlib.contextmanager
def profile(trace_dir: str | Path | None) -> Iterator[None]:
    """XLA profiler window; view with TensorBoard's profile plugin / XProf.
    No-op when trace_dir is None."""
    if trace_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(str(trace_dir)):
        yield
