"""Checkpoint / resume.

The reference has NO persistence: weights live only in process memory and
cross the wire as pickle, never touching disk (SURVEY.md §5 — reference
server.py:81, :104); any crash loses the run.  Here full TrainState
(params + optimizer state + step + rng) checkpoints atomically via Orbax,
with retention and resume — including per-device-stacked states from the
async/gossip engines (Orbax gathers sharded arrays transparently).

Two write disciplines share one on-disk format:

* :class:`CheckpointManager` — synchronous: ``save`` blocks the caller
  for the full device→host transfer + Orbax write + retention sweep.
* :class:`AsyncCheckpointManager` — ``save`` snapshots the TrainState off
  the live (donated) device buffers, starts a non-blocking device→host
  transfer, and hands the snapshot to a single background writer thread;
  the caller dispatches its next chunk immediately.  At most one save is
  in flight (a second ``save`` waits on the previous write — bounded host
  memory); writer errors re-raise at the next ``save``/``wait``/
  ``close``; ``restore`` begins with a drain barrier so resume never
  races a pending write.

Both write atomically: Orbax writes into ``tmp_step_N``, the directory is
fsynced, then renamed to ``step_N`` — a crash mid-write leaves only a
``tmp_`` directory (invisible to ``steps()``/``restore`` and cleaned on
the next manager start), never a half-written visible checkpoint.
"""

from __future__ import annotations

import contextlib
import json
import os
import queue
import re
import shutil
import threading
import time
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

_STEP_DIR = re.compile(r"^step_(\d+)$")
_TMP_DIR = re.compile(r"^tmp_step_(\d+)$")

# JSON sidecar riding INSIDE each step directory (Orbax ignores files it
# did not write): the elastic payload — data-iterator state + save wall
# time — that makes a checkpoint a resumable, exactly-once object
# (elastic/data_state.py).  Written into tmp_step_N BEFORE the fsync +
# rename, so the payload is atomic with the checkpoint itself: a visible
# step_N either carries its sidecar or was written by an older build
# (restore then degrades to replay accounting, never to a torn read).
_EXTRA_FILE = "elastic.json"


def _is_key(x) -> bool:
    return hasattr(x, "dtype") and jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key)


def _unkey(tree):
    """Typed PRNG keys aren't serializable — store their raw uint32 data."""
    return jax.tree.map(
        lambda x: jax.random.key_data(x) if _is_key(x) else x, tree)


def _rekey(template, tree):
    return jax.tree.map(
        lambda t, r: jax.random.wrap_key_data(jax.numpy.asarray(r))
        if _is_key(t) else r,
        template, tree)


def _host_template(template):
    """Host-side restore template (structure + shape + dtype).  Single
    process: the real values via device_get.  Multi-process: shape/dtype
    zeros — device_get cannot read non-addressable shards, and Orbax only
    needs the structure to restore into."""
    t = _unkey(template)
    if jax.process_count() == 1:
        return jax.device_get(t)
    import numpy as np

    return jax.tree.map(
        lambda a: np.zeros(a.shape, a.dtype) if hasattr(a, "shape") else a, t)


def _fsync_dir(path: Path) -> None:
    """Best-effort directory fsync: make the tmp→final rename (and the
    entries under it) durable before the checkpoint becomes visible."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _fsync_tree(path: Path) -> None:
    """fsync every file (then directory) under ``path``: the rename must
    never become durable before the bytes it points at — a power loss
    after a data-less rename would persist a visible ``step_N`` whose
    array files are still page-cache-only, the exact torn state the
    tmp/rename discipline exists to rule out."""
    for p in sorted(path.rglob("*")):
        if p.is_dir():
            _fsync_dir(p)
            continue
        try:
            fd = os.open(p, os.O_RDONLY)
        except OSError:
            continue
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)
    _fsync_dir(path)


def _snapshot(state: Any) -> Any:
    """Decouple a TrainState from its live device buffers.

    Every engine's step donates its input state (``donate_argnums=0``), so
    a background writer cannot read the trainer's arrays once the next
    chunk is dispatched.  The copy is an on-device op (async dispatch —
    XLA orders it after the producing chunk and before the donated
    reuse), and ``copy_to_host_async`` starts the device→host transfer on
    the stream without blocking, so by the time the writer calls
    ``device_get`` the bytes are typically already on the host."""
    def snap(x):
        if isinstance(x, jax.Array):
            c = x.copy()
            with contextlib.suppress(Exception):  # transfer hint only —
                c.copy_to_host_async()            # device_get still works
            return c
        return x

    return jax.tree.map(snap, _unkey(state))


class AsyncCheckpointError(RuntimeError):
    """A background checkpoint write failed; re-raised on the training
    thread at the next ``save``/``wait``/``close``."""


class CheckpointManager:
    """Step-numbered checkpoints under ``directory`` with retention."""

    asynchronous = False

    def __init__(self, directory: str | Path, max_to_keep: int = 3):
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._ckptr = ocp.PyTreeCheckpointer()
        self._clean_tmp()

    def _clean_tmp(self) -> None:
        """A ``tmp_step_N`` left by a crashed write is garbage by
        definition (the rename never happened): sweep it on start —
        under EITHER discipline, a torn tmp dir holds a full TrainState
        of dead disk."""
        for p in self.directory.iterdir():
            if _TMP_DIR.match(p.name) and p.is_dir():
                shutil.rmtree(p, ignore_errors=True)

    # ------------------------------------------------------------------ save
    def _resolve_step(self, state: Any, step: int | None) -> int:
        if step is not None:
            return int(step)
        s = state.step
        if getattr(s, "is_fully_addressable", True):
            return int(jax.device_get(s).max())
        # device_get rejects non-addressable shards (stacked async state on
        # multi-process meshes); all rows carry the same step, so local
        # shards suffice
        import numpy as np

        return int(max(np.asarray(sh.data).max()
                       for sh in s.addressable_shards))

    def _write(self, step: int, host_state: Any,
               extra: dict | None = None) -> None:
        """Atomic visible write: Orbax into ``tmp_step_N``, fsync, rename
        to ``step_N``.  A crash anywhere before the rename leaves only the
        ``tmp_`` directory — never a half-written ``step_N``.  ``extra``
        (the elastic sidecar) is written into the tmp directory, so it
        becomes visible atomically with the checkpoint."""
        tmp = self.directory / f"tmp_step_{step}"
        final = self.directory / f"step_{step}"
        if tmp.exists():
            shutil.rmtree(tmp)
        self._ckptr.save(tmp, host_state, force=True)
        if extra is not None:
            (tmp / _EXTRA_FILE).write_text(json.dumps(extra))
        _fsync_tree(tmp)
        if final.exists():
            shutil.rmtree(final)
        os.replace(tmp, final)
        _fsync_dir(self.directory)

    def save(self, state: Any, step: int | None = None,
             extra: dict | None = None) -> Path:
        step = self._resolve_step(state, step)
        path = self.directory / f"step_{step}"
        state = _unkey(state)
        if jax.process_count() > 1:
            # device_get cannot read non-addressable shards (tp/pp/ep state
            # on multi-process meshes): gather full host copies everywhere,
            # then let exactly one process write the shared directory
            from jax.experimental import multihost_utils

            host_state = multihost_utils.process_allgather(state)
            if jax.process_index() == 0:
                self._write(step, host_state, extra)
                self._retain()
            multihost_utils.sync_global_devices(f"ckpt_save_{step}")
        else:
            self._write(step, jax.device_get(state), extra)
            self._retain()
        return path

    def _retain(self) -> None:
        steps = sorted(self.steps())
        for s in steps[: -self.max_to_keep] if self.max_to_keep else []:
            shutil.rmtree(self.directory / f"step_{s}", ignore_errors=True)

    # ------------------------------------------------------- async interface
    # no-ops on the synchronous manager, so the Trainer/harness treat both
    # disciplines uniformly (drain barriers cost nothing here)
    def wait(self, reraise: bool = True) -> None:
        """No save is ever in flight on the synchronous manager."""

    def close(self, reraise: bool = True) -> None:
        """Nothing to join on the synchronous manager."""

    # --------------------------------------------------------------- restore
    def steps(self) -> list[int]:
        out = []
        for p in self.directory.iterdir():
            m = _STEP_DIR.match(p.name)
            if m and p.is_dir():
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def load_extra(self, step: int | None = None) -> dict | None:
        """The elastic sidecar saved with ``step`` (latest when None):
        data-iterator state + save wall time (elastic/data_state.py).
        ``None`` when the checkpoint predates the sidecar (older builds) —
        callers then fall back to replay accounting — or when the step
        does not exist."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        path = self.directory / f"step_{step}" / _EXTRA_FILE
        try:
            payload = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            return None
        return payload if isinstance(payload, dict) else None

    def restore(self, template: Any, step: int | None = None) -> Any:
        """Restore into the structure/shardings of ``template`` (a freshly
        initialized TrainState — engine.init_state output)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        restored = self._ckptr.restore(
            self.directory / f"step_{step}",
            item=_host_template(template))
        restored = _rekey(template, restored)
        if jax.process_count() > 1:
            # device_put rejects non-addressable shardings; the jit-identity
            # placement (mesh.state_to_global) reshards host-replicated
            # values onto the global mesh instead
            from distributed_tensorflow_tpu.parallel import mesh as meshlib

            shardings = jax.tree.map(
                lambda t: t.sharding if hasattr(t, "sharding") else None,
                template)
            return meshlib.state_to_global(restored, shardings)
        # re-place on device with the template's shardings
        return jax.tree.map(
            lambda t, r: jax.device_put(r, t.sharding)
            if hasattr(t, "sharding") else r,
            template, restored)


class AsyncCheckpointManager(CheckpointManager):
    """Checkpointing off the training critical path (see module docstring).

    ``save`` costs the training thread a device snapshot (+ any wait for a
    still-running previous write — the at-most-one-in-flight backpressure
    that bounds host memory to one extra TrainState); the device→host
    transfer, Orbax write, fsync-rename and retention sweep run on one
    background writer thread.  Training-thread seconds spent blocked
    accumulate in ``wait_s``; writer seconds that ran GENUINELY
    concurrently with training accumulate in ``overlapped_s`` (write
    wall time the trainer stood blocked on is counted once, in
    ``wait_s`` — never double-booked as overlap) — the split the run
    report and ``bench.py --checkpoint-every`` surface.

    ``tracer``, when set (the Trainer wires its own in), gets a
    ``ckpt_write`` span per background write, the overlapped twin of the
    training thread's ``ckpt_snapshot`` span.

    Multi-process meshes fall back to the synchronous path per save: the
    pod save is a collective (process_allgather + barrier) and cannot
    leave the training thread without racing training's own collectives.
    """

    asynchronous = True

    def __init__(self, directory: str | Path, max_to_keep: int = 3):
        super().__init__(directory, max_to_keep)
        self.tracer = None          # optional observability.Tracer
        self.wait_s = 0.0           # training-thread seconds blocked here
        # writer seconds GENUINELY concurrent with training: the writer
        # tallies its wall time, minus any of it the trainer spent
        # blocked waiting on that same write (see _blocked)
        self.overlapped_s = 0.0
        self.saves = 0
        self._idle = threading.Event()
        self._idle.set()
        self._error: BaseException | None = None
        self._queue: queue.SimpleQueue = queue.SimpleQueue()
        self._thread: threading.Thread | None = None
        self._acct_lock = threading.Lock()

    # ----------------------------------------------------------- writer side
    def _ensure_writer(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer_loop, name="ckpt-writer", daemon=True)
            self._thread.start()

    def _writer_loop(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            step, snapshot, extra = job
            t0 = time.perf_counter()
            try:
                span = (self.tracer.span("ckpt_write", step=step)
                        if self.tracer is not None
                        else contextlib.nullcontext())
                with span:
                    # the transfer was started by copy_to_host_async at
                    # snapshot time; device_get here mostly just collects
                    self._write(step, jax.device_get(snapshot), extra)
                    self._retain()
            except BaseException as e:  # noqa: BLE001 — surfaced on the
                self._error = e         # training thread at the next sync
            finally:
                with self._acct_lock:
                    self.overlapped_s += time.perf_counter() - t0
                self._idle.set()

    def _reraise(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise AsyncCheckpointError(
                f"background checkpoint write under {self.directory} "
                f"failed: {type(err).__name__}: {err}") from err

    # --------------------------------------------------------- training side
    def _blocked(self, seconds: float) -> None:
        """Account training-thread seconds spent waiting on an in-flight
        write.  They go into ``wait_s`` AND come back out of
        ``overlapped_s``: the writer tallies its full wall time, but time
        the trainer stood blocked on it was not overlap — the two windows
        nest (the wait ends when the write's ``_idle.set`` fires, after
        the writer's own tally), so the difference is the genuinely
        concurrent share.  Clamped at 0 against enqueue→dequeue jitter."""
        self.wait_s += seconds
        with self._acct_lock:
            self.overlapped_s = max(0.0, self.overlapped_s - seconds)

    def save(self, state: Any, step: int | None = None,
             extra: dict | None = None) -> Path:
        if jax.process_count() > 1:
            return super().save(state, step, extra)  # pod saves stay collective
        step = self._resolve_step(state, step)
        t0 = time.perf_counter()
        self._idle.wait()  # backpressure: at most ONE save in flight
        self._blocked(time.perf_counter() - t0)
        self._reraise()
        snapshot = _snapshot(state)
        self._idle.clear()
        self._ensure_writer()
        self._queue.put((step, snapshot, extra))
        self.saves += 1
        return self.directory / f"step_{step}"

    def wait(self, reraise: bool = True) -> None:
        """Drain barrier: block until no write is in flight; surface any
        writer error (unless ``reraise=False`` — exception-path cleanup
        must not mask the original failure)."""
        t0 = time.perf_counter()
        self._idle.wait()
        self._blocked(time.perf_counter() - t0)
        if reraise:
            self._reraise()

    def close(self, reraise: bool = True) -> None:
        """Drain, stop the writer thread, surface any pending error."""
        self.wait(reraise=False)
        if self._thread is not None and self._thread.is_alive():
            self._queue.put(None)
            self._thread.join(timeout=60)
        self._thread = None
        if reraise:
            self._reraise()

    def restore(self, template: Any, step: int | None = None) -> Any:
        self.wait()  # resume must never read a directory mid-write
        return super().restore(template, step)

    def latest_step(self) -> int | None:
        self.wait()  # an in-flight write IS the latest step once visible
        return super().latest_step()

    def load_extra(self, step: int | None = None) -> dict | None:
        self.wait()  # the sidecar lands with the write it rides
        return super().load_extra(step)

    def stats(self) -> dict[str, Any]:
        return {"saves": self.saves, "wait_s": self.wait_s,
                "overlapped_s": self.overlapped_s}
