"""Checkpoint / resume.

The reference has NO persistence: weights live only in process memory and
cross the wire as pickle, never touching disk (SURVEY.md §5 — reference
server.py:81, :104); any crash loses the run.  Here full TrainState
(params + optimizer state + step + rng) checkpoints atomically via Orbax,
with retention and resume — including per-device-stacked states from the
async/gossip engines (Orbax gathers sharded arrays transparently).
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

_STEP_DIR = re.compile(r"^step_(\d+)$")


def _is_key(x) -> bool:
    return hasattr(x, "dtype") and jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key)


def _unkey(tree):
    """Typed PRNG keys aren't serializable — store their raw uint32 data."""
    return jax.tree.map(
        lambda x: jax.random.key_data(x) if _is_key(x) else x, tree)


def _rekey(template, tree):
    return jax.tree.map(
        lambda t, r: jax.random.wrap_key_data(jax.numpy.asarray(r))
        if _is_key(t) else r,
        template, tree)


def _host_template(template):
    """Host-side restore template (structure + shape + dtype).  Single
    process: the real values via device_get.  Multi-process: shape/dtype
    zeros — device_get cannot read non-addressable shards, and Orbax only
    needs the structure to restore into."""
    t = _unkey(template)
    if jax.process_count() == 1:
        return jax.device_get(t)
    import numpy as np

    return jax.tree.map(
        lambda a: np.zeros(a.shape, a.dtype) if hasattr(a, "shape") else a, t)


class CheckpointManager:
    """Step-numbered checkpoints under ``directory`` with retention."""

    def __init__(self, directory: str | Path, max_to_keep: int = 3):
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._ckptr = ocp.PyTreeCheckpointer()

    # ------------------------------------------------------------------ save
    def save(self, state: Any, step: int | None = None) -> Path:
        if step is None:
            s = state.step
            if getattr(s, "is_fully_addressable", True):
                step = int(jax.device_get(s).max())
            else:
                # device_get rejects non-addressable shards (stacked async
                # state on multi-process meshes); all rows carry the same
                # step, so local shards suffice
                import numpy as np

                step = int(max(np.asarray(sh.data).max()
                               for sh in s.addressable_shards))
        path = self.directory / f"step_{step}"
        state = _unkey(state)
        if jax.process_count() > 1:
            # device_get cannot read non-addressable shards (tp/pp/ep state
            # on multi-process meshes): gather full host copies everywhere,
            # then let exactly one process write the shared directory
            from jax.experimental import multihost_utils

            host_state = multihost_utils.process_allgather(state)
            if jax.process_index() == 0:
                self._ckptr.save(path, host_state, force=True)
                self._retain()
            multihost_utils.sync_global_devices(f"ckpt_save_{step}")
        else:
            self._ckptr.save(path, jax.device_get(state), force=True)
            self._retain()
        return path

    def _retain(self) -> None:
        steps = sorted(self.steps())
        for s in steps[: -self.max_to_keep] if self.max_to_keep else []:
            import shutil

            shutil.rmtree(self.directory / f"step_{s}", ignore_errors=True)

    # --------------------------------------------------------------- restore
    def steps(self) -> list[int]:
        out = []
        for p in self.directory.iterdir():
            m = _STEP_DIR.match(p.name)
            if m and p.is_dir():
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, template: Any, step: int | None = None) -> Any:
        """Restore into the structure/shardings of ``template`` (a freshly
        initialized TrainState — engine.init_state output)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        restored = self._ckptr.restore(
            self.directory / f"step_{step}",
            item=_host_template(template))
        restored = _rekey(template, restored)
        if jax.process_count() > 1:
            # device_put rejects non-addressable shardings; the jit-identity
            # placement (mesh.state_to_global) reshards host-replicated
            # values onto the global mesh instead
            from distributed_tensorflow_tpu.parallel import mesh as meshlib

            shardings = jax.tree.map(
                lambda t: t.sharding if hasattr(t, "sharding") else None,
                template)
            return meshlib.state_to_global(restored, shardings)
        # re-place on device with the template's shardings
        return jax.tree.map(
            lambda t, r: jax.device_put(r, t.sharding)
            if hasattr(t, "sharding") else r,
            template, restored)
