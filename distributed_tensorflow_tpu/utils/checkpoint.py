"""Checkpoint / resume.

The reference has NO persistence: weights live only in process memory and
cross the wire as pickle, never touching disk (SURVEY.md §5 — reference
server.py:81, :104); any crash loses the run.  Here full TrainState
(params + optimizer state + step + rng) checkpoints atomically via Orbax,
with retention and resume — including per-device-stacked states from the
async/gossip engines (Orbax gathers sharded arrays transparently).
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

_STEP_DIR = re.compile(r"^step_(\d+)$")


def _is_key(x) -> bool:
    return hasattr(x, "dtype") and jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key)


def _unkey(tree):
    """Typed PRNG keys aren't serializable — store their raw uint32 data."""
    return jax.tree.map(
        lambda x: jax.random.key_data(x) if _is_key(x) else x, tree)


def _rekey(template, tree):
    return jax.tree.map(
        lambda t, r: jax.random.wrap_key_data(jax.numpy.asarray(r))
        if _is_key(t) else r,
        template, tree)


class CheckpointManager:
    """Step-numbered checkpoints under ``directory`` with retention."""

    def __init__(self, directory: str | Path, max_to_keep: int = 3):
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._ckptr = ocp.PyTreeCheckpointer()

    # ------------------------------------------------------------------ save
    def save(self, state: Any, step: int | None = None) -> Path:
        if step is None:
            step = int(jax.device_get(state.step).max())
        path = self.directory / f"step_{step}"
        self._ckptr.save(path, jax.device_get(_unkey(state)), force=True)
        self._retain()
        return path

    def _retain(self) -> None:
        steps = sorted(self.steps())
        for s in steps[: -self.max_to_keep] if self.max_to_keep else []:
            import shutil

            shutil.rmtree(self.directory / f"step_{s}", ignore_errors=True)

    # --------------------------------------------------------------- restore
    def steps(self) -> list[int]:
        out = []
        for p in self.directory.iterdir():
            m = _STEP_DIR.match(p.name)
            if m and p.is_dir():
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, template: Any, step: int | None = None) -> Any:
        """Restore into the structure/shardings of ``template`` (a freshly
        initialized TrainState — engine.init_state output)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        restored = self._ckptr.restore(
            self.directory / f"step_{step}",
            item=jax.device_get(_unkey(template)))
        restored = _rekey(template, restored)
        # re-place on device with the template's shardings
        return jax.tree.map(
            lambda t, r: jax.device_put(r, t.sharding)
            if hasattr(t, "sharding") else r,
            template, restored)
