"""Failure detection and crash recovery.

The reference has NO failure handling — a dead worker deadlocks the rest at
the fixed-size barrier and the server thread spins forever on a closed
connection (reference server.py:132-134, 151; SURVEY.md §5 "Failure
detection: NO (and buggy)").  The only resilience is the client's
connect-retry loop (reference client.py:56-62).

TPU-native failure handling is different in kind: there are no per-worker
sockets to watch — a training process is a single SPMD program, so the
failure modes are (a) the numeric kind, a diverged/NaN loss; (b) the stall
kind, a step that never completes (hung collective, wedged runtime); and
(c) the crash kind, the process dying.  This module covers all three:

  check_finite   — divergence detection on materialized metrics
  Watchdog       — wall-clock stall detector around the step loop
  run_with_recovery — restart-from-latest-checkpoint crash recovery loop
                   (pairs with utils/checkpoint.py, the durable-state story
                   the reference lacks entirely)
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable


class TrainingDiverged(RuntimeError):
    """Loss/metric became NaN or infinite."""


class AnomalyDetected(TrainingDiverged):
    """The health layer's ``--on-anomaly halt`` policy fired: a per-step
    health stat (observability/health.py) crossed its threshold or went
    non-finite.  Subclasses TrainingDiverged so ``run_with_recovery``
    refuses to restart into the same divergence."""


class StallDetected(RuntimeError):
    """No step completed within the watchdog timeout."""


def check_finite(metrics: dict[str, float], step: int | None = None) -> None:
    """Raise TrainingDiverged if any materialized metric is NaN/inf.

    Call sites pass metrics that are already host floats (the Trainer only
    materializes on its logging cadence), so this adds no device sync.
    """
    for k, v in metrics.items():
        if not math.isfinite(v):
            at = f" at step {step}" if step is not None else ""
            raise TrainingDiverged(f"metric '{k}' is {v}{at}")


class Watchdog:
    """Detects a stalled training loop: ``beat()`` as the loop makes
    progress; if no beat arrives within ``timeout`` seconds the
    ``on_stall`` callback fires from the monitor thread (once per stall
    episode — it re-arms when beats resume, so a transient pause that
    recovers does not poison the rest of the run).

    ``check()`` raises StallDetected from the calling thread only while a
    stall is CURRENTLY in progress (beat age > timeout at call time); a
    recovered episode never raises.  A training thread wedged inside a hung
    collective can't raise for itself — for that case the on_stall callback
    (e.g. the harness's 'stall' event emission) is the detection signal.

    Contrast: the reference cannot detect a stall at all — a single dead
    worker leaves every other thread waiting in Barrier.wait forever
    (reference server.py:151, 90-96).
    """

    def __init__(self, timeout: float = 120.0,
                 on_stall: Callable[[float], Any] | None = None,
                 poll_interval: float | None = None,
                 arm_on_first_beat: bool = True):
        self.timeout = timeout
        self.base_timeout = timeout   # per-step budget (rescale() reference)
        self.on_stall = on_stall
        self.stalled = False          # live view: currently in a stall?
        self.stall_episodes = 0
        self.stall_elapsed = 0.0      # beat age when the episode fired
        self.beats = 0                # heartbeat count (run-report gauge)
        # arm_on_first_beat: don't count the window before the first beat —
        # the first training step's blocking XLA compile routinely exceeds
        # any sane stall timeout and would fire a false episode.  Tradeoff:
        # a hang during the very first compile goes undetected.
        self._last = None if arm_on_first_beat else time.monotonic()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._poll = poll_interval if poll_interval is not None \
            else max(0.05, timeout / 10.0)
        self._thread = threading.Thread(target=self._monitor, daemon=True)
        self._thread.start()

    def beat(self) -> None:
        with self._lock:
            self._last = time.monotonic()
            self.beats += 1

    def rescale(self, steps_per_beat: int) -> None:
        """Adapt the stall budget to a chunked step loop: with
        ``steps_per_call = k`` the Trainer beats once per CHUNK dispatch
        and once per chunk flush (the host sync), so the per-step
        ``base_timeout`` becomes a per-beat budget of ``k × base_timeout``
        (dispatches are bounded by the in-flight window, so a hung device
        still stops the beats within it).  This is what lets the
        watchdog ride the multi-step scan drain instead of forcing
        ``steps_per_call`` down to 1 — stall detection resolution coarsens
        k×, which is the honest price of k× fewer host syncs."""
        if steps_per_beat < 1:
            raise ValueError(
                f"steps_per_beat must be >= 1, got {steps_per_beat}")
        with self._lock:
            self.timeout = self.base_timeout * steps_per_beat

    def _beat_age(self) -> float:
        with self._lock:
            if self._last is None:  # not armed yet (no first beat)
                return 0.0
            return time.monotonic() - self._last

    def check(self) -> None:
        """Raise StallDetected if a stall is in progress right now."""
        age = self._beat_age()
        if age > self.timeout:
            raise StallDetected(
                f"no progress beat for {age:.1f}s (timeout {self.timeout}s)")

    def _monitor(self) -> None:
        while not self._stop.wait(self._poll):
            elapsed = self._beat_age()
            if elapsed > self.timeout:
                if not self.stalled:  # fire once per episode
                    self.stalled = True
                    self.stall_episodes += 1
                    self.stall_elapsed = elapsed
                    if self.on_stall is not None:
                        self.on_stall(elapsed)
            else:
                self.stalled = False  # beats resumed: re-arm

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def run_with_recovery(config, max_restarts: int = 2,
                      run_fn: Callable | None = None,
                      on_restart: Callable[[int, BaseException], Any] | None = None,
                      ) -> dict[str, Any]:
    """Run an experiment, restarting from the latest checkpoint on crash.

    Requires ``config.checkpoint_dir`` (with ``checkpoint_every`` for
    intra-run durability).  After a failure the config is re-run with
    ``resume=True`` AND ``elastic_restore=True``: the restart goes through
    the elastic restore (distributed_tensorflow_tpu/elastic/) rather than
    a cold ``restore()`` — resharding-tolerant (the relaunch may land on a
    different device count), continuing the exact batch sequence from the
    checkpoint's data state (exactly-once over the dataset), with the
    crash's cost reported as ``preemption_lost_s``/``resume_replay_steps``
    in the resumed run's report.  Up to ``max_restarts`` retries, then the
    last exception propagates.  Divergence (TrainingDiverged) is NOT
    retried — restarting into the same NaN is not recovery.

    ``run_fn`` is injectable for tests; defaults to harness.run.
    """
    import dataclasses

    if run_fn is None:
        from distributed_tensorflow_tpu.utils.harness import run as run_fn
    if max_restarts > 0 and not config.checkpoint_dir:
        raise ValueError("run_with_recovery needs config.checkpoint_dir to "
                         "have anything to recover from")
    attempt = 0
    while True:
        try:
            summary = run_fn(config)
            if attempt:
                summary = dict(summary)
                summary["restarts"] = attempt
            return summary
        except TrainingDiverged:
            raise
        except Exception as e:  # noqa: BLE001 — any crash is restartable
            attempt += 1
            if attempt > max_restarts:
                raise
            if on_restart is not None:
                on_restart(attempt, e)
            config = dataclasses.replace(config, resume=True,
                                         elastic_restore=True)
