"""Supervisor / result reporting.

The reference reports run results over an out-of-band TCP channel on port
4000 with exactly three message shapes: ``'start'``, ``('done', elapsed)``,
``('results', accuracy)`` (reference server.py:121-124, 182-187;
dist_keras.py:34-39, 45-47, 56-58); its only other observability is print().

Here the primary sink is structured JSON-lines (file and/or stdout) — the
"metrics callback / JSON-lines result sink" of SURVEY.md §2.3 — plus an
optional socket client emitting the reference's exact event sequence for
external harnesses, and a listener used in tests.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from pathlib import Path
from typing import Any

from distributed_tensorflow_tpu.utils import wire


class ResultSink:
    """JSONL event sink; every event gets a wall timestamp."""

    def __init__(self, path: str | Path | None = None, echo: bool = False,
                 supervisor_address: str | None = None,
                 supervisor_port: int = 4000):
        self.path = Path(path) if path else None
        self.echo = echo
        self._events: list[dict] = []
        self._sock: socket.socket | None = None
        if supervisor_address:
            # 'host' or 'host:port' — bare host keeps the reference's port
            # 4000 default (reference server.py:121)
            host, _, port = supervisor_address.partition(":")
            self._sock = socket.create_connection(
                (host, int(port) if port else supervisor_port), timeout=10)

    def emit(self, event: str, **fields: Any) -> dict:
        rec = {"event": event, "time": time.time(), **fields}
        self._events.append(rec)
        line = json.dumps(rec)
        if self.path:
            with open(self.path, "a") as f:
                f.write(line + "\n")
        if self.echo:
            print(line)
        return rec

    # reference-protocol event triple ------------------------------------
    def start(self) -> None:
        self.emit("start")
        if self._sock:
            wire.send_msg(self._sock, "start")

    def done(self, elapsed: float) -> None:
        self.emit("done", elapsed=elapsed)
        if self._sock:
            wire.send_msg(self._sock, ["done", elapsed])

    def results(self, accuracy: float, **extra: Any) -> None:
        self.emit("results", accuracy=accuracy, **extra)
        if self._sock:
            wire.send_msg(self._sock, ["results", accuracy])

    def preempted(self, reason: str, step: int,
                  **extra: Any) -> None:
        """Graceful-drain notice (elastic/lease.py): the run ended its
        lease — SIGTERM preemption notice or ``--max-steps-per-lease``
        budget — after writing a final checkpoint, and a relaunch with
        ``--elastic-restore`` continues it from ``step``.  Extends the
        reference's event triple with a fourth shape,
        ``['preempted', reason, step]``, so an external supervisor
        distinguishes a planned drain (relaunch me) from a corpse
        (investigate me); JSONL consumers get the same fields as a
        structured ``preempted`` event."""
        self.emit("preempted", reason=reason, step=step, **extra)
        if self._sock:
            wire.send_msg(self._sock, ["preempted", reason, step])

    def close(self) -> None:
        if self._sock:
            self._sock.close()
            self._sock = None

    @property
    def events(self) -> list[dict]:
        return list(self._events)


class SupervisorListener:
    """Test/benchmark-side listener accepting one reporter connection —
    the counterpart the reference assumes exists on port 4000 but never
    ships (SURVEY.md §4)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self.messages: list[Any] = []
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        try:
            conn, _ = self._srv.accept()
            while True:
                msg = wire.recv_msg(conn)
                if msg is None:
                    break
                self.messages.append(msg)
            conn.close()
        except OSError:
            pass

    def close(self) -> None:
        self._srv.close()
        self._thread.join(timeout=2)
