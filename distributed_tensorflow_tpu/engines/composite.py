"""Composed parallelism: dp × tp × sp on one 3-D mesh.

No reference counterpart (the reference's only axis is data parallelism over
worker processes, SURVEY.md §2.2) — this is the TPU-native "pick a mesh,
annotate shardings, let XLA insert collectives" recipe applied across three
axes at once:

* ``data``  — batch sharding, GSPMD (compiler inserts the gradient
  all-reduce exactly as in engines/tensor_parallel.py).
* ``model`` — Megatron tensor parallelism via the model's
  ``with_partitioning`` annotations (models/bert.py ``partition_model``),
  also GSPMD.
* ``seq``   — ring/Ulysses context parallelism via **partial-manual**
  ``jax.shard_map`` (``axis_names={'seq'}``): the step body is manual over
  ``seq`` — so ring attention's explicit ``ppermute`` schedule rides ICI
  neighbor links — while ``data``/``model`` stay in GSPMD's hands inside the
  same program.  With ``seq`` size 1 (or dense attention) the step is a
  plain jit and the mesh degenerates to the tensor-parallel engine's.

Gradient bookkeeping under the manual ``seq`` axis: parameters enter the
shard_map seq-invariant (``P()``), every seq device computes the global-mean
loss through its token block, and shard_map's AD transpose psums the partial
parameter cotangents over ``seq`` at the invariant boundary — no explicit
gradient collectives, same argument as engines/seq_parallel.py but with the
``data`` mean handled by GSPMD instead of a manual pmean.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.engines.base import (
    Engine, TrainState, cross_entropy, cross_entropy_onehot, token_weights)
from distributed_tensorflow_tpu.parallel import collectives as coll
from distributed_tensorflow_tpu.parallel import compression
from distributed_tensorflow_tpu.parallel import mesh as meshlib


class CompositeEngine(Engine):
    """Sync training over a ('data', 'model', 'seq'[, 'expert']) mesh.

    Any axis may have size 1; ``seq`` > 1 requires a model whose
    ``attention_impl`` is 'ring', 'ring_flash', 'ulysses' or 'ulysses_flash'
    (dense attention on seq-sharded activations would attend within local
    blocks only).

    An ``expert`` axis (ep×sp — the long-context MoE shape) requires a
    model with MoE blocks carrying ``with_partitioning('expert', ...)``
    annotations (models/gpt.py ``moe_experts`` + ``partition_experts``):
    the expert dispatch einsums stay GSPMD over 'expert' — each manual-seq
    token block routes to the globally-sharded experts — while the router's
    aux/z losses join the objective exactly as in
    engines/expert_parallel.py (same _OverflowMonitor on the overflow
    diagnostic)."""

    seq_axis = meshlib.SEQ_AXIS

    def __init__(self, model, optimizer=None, mesh=None, learning_rate=1e-3,
                 aux_weight: float = 0.01, router_z_weight: float = 0.0,
                 overflow_warn_threshold: float = 0.25,
                 overflow_window: int = 50, grad_accum: int = 1,
                 grad_compression: str = "none",
                 grad_bucket_mb: float = 0.0, precision: str = "f32"):
        from distributed_tensorflow_tpu.engines.expert_parallel import (
            _OverflowMonitor)

        if mesh is None or meshlib.DATA_AXIS not in mesh.axis_names:
            raise ValueError("CompositeEngine requires a mesh with a 'data' "
                             "axis (plus optional 'model'/'seq'/'expert')")
        extra = set(mesh.axis_names) - {meshlib.DATA_AXIS, meshlib.MODEL_AXIS,
                                        meshlib.SEQ_AXIS, meshlib.EXPERT_AXIS}
        if extra:
            raise ValueError(f"unsupported mesh axes {sorted(extra)}; "
                             f"CompositeEngine composes data×model×seq×expert")
        self.moe = getattr(model, "moe_experts", 0) > 0
        self.ep_n = mesh.shape.get(meshlib.EXPERT_AXIS, 1)
        if self.ep_n > 1:
            if not self.moe:
                raise ValueError(
                    "mesh has an 'expert' axis but the model has no MoE "
                    "blocks (moe_experts == 0); experts would silently "
                    "replicate")
            if not getattr(model, "partition_experts", False):
                raise ValueError(
                    "an 'expert' mesh axis needs partition_experts=True on "
                    "the model — without the with_partitioning('expert') "
                    "annotations the expert weights replicate and no "
                    "expert parallelism happens")
            if getattr(model, "moe_experts", 0) % self.ep_n:
                raise ValueError(
                    f"moe_experts {model.moe_experts} not divisible by "
                    f"expert axis size {self.ep_n}")
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        self.grad_accum = grad_accum
        self.aux_weight = aux_weight
        self.router_z_weight = router_z_weight
        self.overflow_monitor = _OverflowMonitor(overflow_warn_threshold,
                                                 overflow_window)
        # bf16 precision policies apply (storage cast + master weights ride
        # the base init/optimizer hooks); fp16-f32master is rejected by the
        # base — this engine's MoE-aux loss does not thread the loss scale
        super().__init__(model, optimizer, mesh, learning_rate,
                         grad_compression=grad_compression,
                         grad_bucket_mb=grad_bucket_mb,
                         precision=precision)
        self.seq_n = mesh.shape.get(meshlib.SEQ_AXIS, 1)
        self.tp_n = mesh.shape.get(meshlib.MODEL_AXIS, 1)
        impl = getattr(model, "attention_impl", "dense")
        if self.seq_n > 1 and impl not in ("ring", "ring_flash", "ulysses",
                                           "ulysses_flash"):
            raise ValueError(
                f"seq axis size {self.seq_n} needs attention_impl 'ring', "
                f"'ring_flash', 'ulysses' or 'ulysses_flash', got "
                f"'{impl}'")
        if self.seq_n == 1 and impl in ("ring", "ring_flash", "ulysses",
                                        "ulysses_flash"):
            # degenerate seq axis: the manual collectives would reference an
            # unbound axis in the plain-jit path — swap in the dense twin
            # (identical params/math on an unsharded sequence)
            self.model = model.clone(attention_impl="dense")
        self._manual_seq = self.seq_n > 1
        # causal LMs (models/gpt.py): (B, L) per-token labels shard over
        # 'seq' with the inputs, and per-device logits VARY over 'seq' (no
        # [CLS] broadcast) — the step/eval below branch on this, mirroring
        # engines/seq_parallel.py
        self.lm = bool(getattr(self.model, "causal_lm", False))

    # ------------------------------------------------------------------ init
    def init_state(self, rng, sample_x) -> TrainState:
        """Init via a dense-attention twin (ring/Ulysses collectives cannot
        trace outside shard_map; param structure is identical) with GSPMD
        shardings read from the model's partitioning annotations."""
        twin = self.model
        if getattr(twin, "attention_impl", "dense") in ("ring", "ring_flash",
                                                        "ulysses",
                                                        "ulysses_flash"):
            twin = twin.clone(attention_impl="dense")
        return self._init_partitioned_state(rng, sample_x, init_model=twin)

    # --------------------------------------------------------------- batches
    def shard_batch(self, x, y, mask=None, process_local=False):
        if self._manual_seq:
            if x.ndim < 2:
                raise ValueError("seq sharding needs (batch, seq, ...) input")
            if x.shape[1] % self.seq_n:
                raise ValueError(f"sequence length {x.shape[1]} not divisible "
                                 f"by seq axis size {self.seq_n}")
        xspec = (P(self.axis, self.seq_axis) if self._manual_seq
                 else P(self.axis, *([None] * (x.ndim - 1))))
        xs = self._place(x, NamedSharding(self.mesh, xspec), process_local)
        # LM targets are per-token (B, L): under manual seq they shard with
        # the inputs so each seq device scores its own token block
        yspec = (P(self.axis, self.seq_axis)
                 if self.lm and self._manual_seq and y.ndim >= 2
                 else P(self.axis))
        ys = self._place(y, NamedSharding(self.mesh, yspec), process_local)
        if mask is None:
            return xs, ys
        ms = self._place(mask, NamedSharding(self.mesh, P(self.axis)),
                         process_local)
        return xs, ys, ms

    # ------------------------------------------------------------------ step
    def step(self, state, x, y):
        state, metrics = super().step(state, x, y)
        if self.moe:
            self.overflow_monitor.observe(metrics["overflow"])
        return state, metrics

    def _build_step(self):
        from distributed_tensorflow_tpu.engines.base import gspmd_grad_accum
        from distributed_tensorflow_tpu.engines.expert_parallel import (
            router_losses)

        apply_fn = self.model.apply
        tx, K = self.tx, self.grad_accum
        seq_axis, manual = self.seq_axis, self._manual_seq
        lm, sp = self.lm, self.seq_n
        moe = self.moe
        aux_weight, z_weight = self.aux_weight, self.router_z_weight

        def loss_fn(params, x, y, rng):
            if moe:
                # routed blocks sow aux_loss/z_loss/overflow; under
                # manual seq each device's router stats cover its own
                # token block.  LM path: aux stays per-block (varying) and
                # the same 1/sp scaling as the task loss makes the
                # transpose psum the mean-over-blocks aux gradient.
                # Classification path: the task loss is seq-INVARIANT
                # (the [CLS] broadcast), and adding a seq-VARYING aux —
                # even 0.0 × aux — would flip the objective's vma type to
                # varying, which turns the broadcast-psum transpose from
                # one replicated seed into sp summed seeds: every gradient
                # upstream of the [CLS] broadcast comes out sp× too large.
                # pmean makes aux invariant AND is the objective we want
                # (mean over block routers); its transpose hands each
                # block d/d aux_block = w/sp, the correct mean gradient.
                logits, col = apply_fn(
                    {"params": params}, x, train=True,
                    rngs={"dropout": rng}, mutable=["intermediates"])
                aux, z, overflow = router_losses(col["intermediates"])
                if manual and not lm:
                    aux = jax.lax.pmean(aux, seq_axis)
                    z = jax.lax.pmean(z, seq_axis)
                    overflow = jax.lax.pmean(overflow, seq_axis)
            else:
                logits = apply_fn({"params": params}, x, train=True,
                                  rngs={"dropout": rng})
                aux = z = overflow = jnp.zeros((), jnp.float32)
            # global-batch mean: 'data' is a GSPMD axis in both paths, so
            # the mean is global as written.  Over 'seq': classification
            # logits are invariant ([CLS] broadcast) and the loss needs
            # no scale; LM logits VARY (each device scores its token
            # block), so the local mean covers 1/sp of the tokens — the
            # 1/sp scale makes the seq psum of partial cotangents the
            # global-mean gradient (same argument as seq_parallel.py).
            ce = cross_entropy_onehot if (manual and lm) else cross_entropy
            loss = ce(logits, y).mean()
            acc = (logits.argmax(-1) == y).mean()
            total = loss + aux_weight * aux + z_weight * z
            scale = sp if (manual and lm) else 1
            return total / scale, (loss, acc, total, overflow)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def accum_manual(params, x, y, rng):
            """K-microbatch scan inside the manual-'seq' shard_map: the
            batch dim is GSPMD-global here, so the reshape/scan is the
            plain accumulation; scan carries must be pcast to the
            varying-over-'seq' types the per-chunk values have (see the
            vz flags below)."""
            b = x.shape[0]
            if b % K:
                raise ValueError(
                    f"global batch {b} not divisible by grad_accum {K}")
            xm = x.reshape((K, b // K) + x.shape[1:])
            ym = y.reshape((K, b // K) + y.shape[1:])

            def micro(carry, chunk):
                g_acc, a_acc, i = carry
                xc, yc = chunk
                (_, aux_c), g = grad_fn(params, xc, yc,
                                        jax.random.fold_in(rng, i))
                return (jax.tree.map(jnp.add, g_acc, g),
                        jax.tree.map(jnp.add, a_acc, aux_c), i + 1), None

            def vz(varying: bool):
                z = jnp.zeros((), jnp.float32)
                return (jax.lax.pcast(z, (seq_axis,), to="varying")
                        if varying else z)

            # carry vma types mirror the per-chunk values: loss/acc/total
            # vary iff LM (classification is [CLS]-invariant, and the moe
            # branch pmean's its aux terms invariant there); overflow
            # varies only for LM MoE (classification pmean's it, non-moe
            # is a constant zero)
            init = (jax.tree.map(jnp.zeros_like, params),
                    (vz(lm), vz(lm), vz(lm), vz(lm and moe)),
                    jnp.zeros((), jnp.int32))
            (g_sum, a_sum, _), _ = jax.lax.scan(micro, init, (xm, ym))
            return (jax.tree.map(lambda t: t / K, g_sum),
                    jax.tree.map(lambda t: t / K, a_sum))

        codec = self.grad_codec

        def train_step(state: TrainState, x, y):
            rng = jax.random.fold_in(state.rng, state.step)
            # the codec's rounding key must be derived BEFORE the per-seq-
            # device fold below: the combined gradient is seq-INVARIANT
            # (params enter the shard_map at P()), so a per-device key
            # would quantize each seq replica differently and silently
            # diverge the parameter copies
            codec_key = compression.codec_rng(rng)
            if manual:
                # per-seq-device dropout masks: activations are token blocks,
                # a shared mask would drop the same local offsets everywhere
                rng = jax.random.fold_in(rng, coll.axis_index(seq_axis))

            if K == 1:
                ((_, (loss, acc, total, overflow)),
                 grads) = grad_fn(state.params, x, y, rng)
            elif manual:
                grads, (loss, acc, total, overflow) = accum_manual(
                    state.params, x, y, rng)
            else:
                # pure-GSPMD path: the shared accumulator (aux pytree)
                grads, _, (loss, acc, total, overflow) = gspmd_grad_accum(
                    grad_fn, state.params, x, y, rng, K, mesh=self.mesh)
            if codec.name != "none":
                # the data-axis gradient reduce is GSPMD-inserted (and the
                # seq-axis contribution arrives via the AD-transpose psum),
                # so the codec applies as a quantize→dequantize roundtrip
                # with a seq-invariant key (see codec_key above)
                grads = codec.roundtrip(grads, rng=codec_key)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            if manual and lm:  # per-seq-block values → report global means
                loss = jax.lax.pmean(loss, seq_axis)
                acc = jax.lax.pmean(acc, seq_axis)
                if moe:  # router stats are per-seq-block too
                    total = jax.lax.pmean(total, seq_axis)
                    overflow = jax.lax.pmean(overflow, seq_axis)
            metrics = {"loss": loss, "accuracy": acc}
            if moe:
                metrics["total_loss"] = total
                metrics["overflow"] = overflow
            return state.replace(step=state.step + 1, params=params,
                                 opt_state=opt_state), metrics

        if not manual:
            return jax.jit(train_step, donate_argnums=0)
        y_spec = P(None, seq_axis) if lm else P()
        smapped = jax.shard_map(
            train_step, mesh=self.mesh, axis_names={seq_axis},
            in_specs=(P(), P(None, seq_axis), y_spec),
            out_specs=(P(), P()),
        )
        return jax.jit(smapped, donate_argnums=0)

    # ------------------------------------------------------------------ eval
    def _build_eval(self):
        apply_fn = self.model.apply
        seq_axis, manual = self.seq_axis, self._manual_seq
        lm = self.lm

        if not manual:  # pure-GSPMD path: the shared masked eval
            return self._build_eval_gspmd(
                lambda params, x: apply_fn({"params": params}, x,
                                           train=False))

        def eval_step(params, x, y, mask):
            logits = apply_fn({"params": params}, x, train=False)
            w = token_weights(mask, y)
            ce = cross_entropy_onehot if lm else cross_entropy
            correct = ((logits.argmax(-1) == y) * w).sum()
            loss_sum = (ce(logits, y) * w).sum()
            count = w.sum()
            if lm:  # every seq device scored its own token block
                out = jax.lax.psum(jnp.stack([correct, loss_sum, count]),
                                   seq_axis)
                return out[0], out[1], out[2]
            # classification: logits seq-invariant, sums already global
            return correct, loss_sum, count

        y_spec = P(None, seq_axis) if lm else P()
        smapped = jax.shard_map(
            eval_step, mesh=self.mesh, axis_names={seq_axis},
            in_specs=(P(), P(None, seq_axis), y_spec, P()),
            out_specs=(P(), P(), P()),
        )
        return jax.jit(smapped)
