"""FSDP engine: ZeRO-style fully-sharded data parallelism via GSPMD.

The reference's core design insight is that the optimizer lives in exactly
one place — the server owns the single model and optimizer and workers hold
only transient replicas (reference server.py:52-55, 148-155; client.py:72).
The TPU-first rendering of "parameters and optimizer state are not
replicated" is ZeRO/FSDP: every parameter AND its optimizer moments are
*sharded over the data axis*, all-gathered just-in-time for each layer's
compute, with gradients reduce-scattered back to their owning shard.  Per
device that is ~1/n of the replicated memory — the only DP mode whose model
size can exceed a single chip's HBM.

Compiler-driven like the TP engine (engines/tensor_parallel.py): we place
each state leaf with a `NamedSharding` that splits its largest
n-divisible dimension over ``data``, run the whole step under one
`jax.jit`, and XLA GSPMD inserts the all-gather-on-use /
reduce-scatter-on-grad collectives — the scaling-book recipe, no manual
collectives.  Unlike the TP engine the shardings are derived from leaf
*shapes*, not model annotations, so ANY registered model works unmodified.

Math is identical to the sync engine (same global-batch-mean loss, same
optimizer applied to the same gradients — just sharded), verified by the
parity test in tests/test_fsdp.py.
"""

from __future__ import annotations

import jax
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.engines.base import (
    Engine, TrainState, gspmd_value_and_grad, make_loss_fn)
from distributed_tensorflow_tpu.parallel import mesh as meshlib
from distributed_tensorflow_tpu.parallel import compression
from distributed_tensorflow_tpu.parallel import precision as precisionlib


def fsdp_spec(shape: tuple[int, ...], n: int,
              axis: str = meshlib.DATA_AXIS,
              base: P | None = None) -> P:
    """PartitionSpec sharding the largest free ``n``-divisible dim over
    ``axis``, on top of an optional ``base`` spec (the model's Megatron
    annotations under fsdp×tp — dims the annotations already shard over
    'model' are skipped, so each leaf ends up sharded over BOTH axes when
    it has two eligible dims, or data-sharded on its largest free dim
    otherwise).

    Leaves with no divisible free dimension (odd-sized biases, scalars,
    PRNG keys) keep ``base`` — they are a negligible fraction of model
    bytes."""
    spec: list = list(base) if base is not None else []
    spec += [None] * (len(shape) - len(spec))
    best = None
    for i, d in enumerate(shape):
        if spec[i] is None and d % n == 0 and d > 0 and (
                best is None or d > shape[best]):
            best = i
    if best is None:
        return P(*spec) if any(s is not None for s in spec) else P()
    spec[best] = axis
    return P(*spec)


class FSDPEngine(Engine):
    """Fully-sharded sync data parallelism on a ('data',) mesh — or
    fsdp × tp on a ('data', 'model') mesh.

    Same step semantics as SyncEngine; different state layout: params and
    optimizer state are sharded over ``data`` (ZeRO-3), so per-device state
    bytes shrink ~1/n while the training math stays bit-comparable.

    With a 'model' mesh axis, the model's Megatron ``with_partitioning``
    annotations take their dims first (tensor parallelism — the compute
    sharding) and the FSDP pass then shards each leaf's largest FREE dim
    over 'data' (the storage sharding): a (in, hidden) TP kernel column-
    sharded over 'model' additionally splits its 'in' dim over 'data',
    giving per-device bytes ~1/(dp·tp).  XLA all-gathers the data dim
    just-in-time per layer exactly as in plain FSDP; the 'model' dim stays
    sharded through the compute (Megatron).

    ``grad_accum`` K > 1 accumulates K microbatch gradients per optimizer
    step (base.gspmd_grad_accum): identical math, ~K× less activation
    memory — and the accumulator is itself FSDP-sharded.

    ``precision`` (parallel/precision.py): params — and a master policy's
    f32 copy inside the optimizer state — materialize low-precision AND
    FSDP-sharded (the spec_fn below maps over every state leaf, master
    included), so per-device bytes compound both wins: ~1/n of half the
    param bytes.  fp16-f32master's loss scale threads through the shared
    ``gspmd_value_and_grad`` hook (``supports_loss_scaling``).
    """

    supports_loss_scaling = True

    def __init__(self, model, optimizer=None, mesh=None, learning_rate=1e-3,
                 grad_accum: int = 1, grad_compression: str = "none",
                 grad_bucket_mb: float = 0.0, precision: str = "f32"):
        if mesh is not None:
            extra = set(mesh.axis_names) - {meshlib.DATA_AXIS,
                                            meshlib.MODEL_AXIS}
            if meshlib.DATA_AXIS not in mesh.axis_names or extra:
                raise ValueError(
                    f"FSDPEngine requires a ('data',) or ('data','model') "
                    f"mesh, got axes {mesh.axis_names}")
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        super().__init__(model, optimizer, mesh, learning_rate,
                         grad_compression=grad_compression,
                         grad_bucket_mb=grad_bucket_mb,
                         precision=precision)
        self.grad_accum = grad_accum
        self.tp_n = self.mesh.shape.get(meshlib.MODEL_AXIS, 1)
        self._state_shardings = None

    # ---------------------------------------------------------------- init
    def init_state(self, rng: jax.Array, sample_x) -> TrainState:
        """Materialize the state already sharded (never replicated first):
        the base GSPMD init scaffolding with specs derived from leaf shapes
        (any model works unmodified), merged over the model's TP
        annotations when the mesh carries a 'model' axis."""
        n = self.n_devices
        state = self._init_partitioned_state(
            rng, sample_x,
            spec_fn=lambda abstract, ann: jax.tree.map(
                lambda leaf, spec: fsdp_spec(
                    leaf.shape, n,
                    base=spec if self.tp_n > 1 else None),
                abstract, ann))
        self._state_shardings = self._init_shardings
        return state

    # ---------------------------------------------------------------- step
    def _build_step(self):
        loss_fn = make_loss_fn(self.model.apply)
        tx, K = self.tx, self.grad_accum
        codec = self.grad_codec

        scaling = self.precision.loss_scaling

        def train_step(state: TrainState, x, y):
            rng = jax.random.fold_in(state.rng, state.step)
            # fp16-f32master: the dynamic loss scale rides the entering
            # opt_state into the shared GSPMD loss-scaling hook (python
            # gate — scale-free policies compile the untouched program)
            ls = (precisionlib.loss_scale_from(state.opt_state)
                  if scaling else None)
            # jit semantics are global: `loss` is the global batch mean.
            # XLA all-gathers each param for its layer's compute and
            # reduce-scatters the grad back to the owning shard; the
            # optimizer update below then runs fully sharded (ZeRO).
            grads, loss, acc = gspmd_value_and_grad(
                loss_fn, state.params, x, y, rng, K, mesh=self.mesh,
                loss_scale=ls)
            if codec.name != "none":
                # GSPMD owns the reduce-scatter, so the codec applies as a
                # quantize→dequantize on the gradient (the numerics of a
                # compressed exchange; parallel/compression.py module
                # docstring) — 'none' skips the gate entirely, keeping the
                # default program bitwise identical.  With --grad-bucket-mb
                # the roundtrip runs per BUCKET (overlap.BucketedCodec) —
                # one int8 scale per ~bucket instead of per leaf; the gate
                # deliberately stays on the INNER codec name, so
                # bucketed-'none' also compiles the untouched program
                # (on GSPMD engines the per-microbatch reduces of
                # gspmd_grad_accum are already scheduler-overlappable;
                # bucketing only changes codec granularity + accounting)
                grads = codec.roundtrip(
                    grads, rng=compression.codec_rng(rng))
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(step=state.step + 1, params=params,
                                 opt_state=opt_state), \
                {"loss": loss, "accuracy": acc}

        # pin the output state to the FSDP layout: without the constraint
        # GSPMD is free to re-layout (e.g. replicate small leaves), which
        # would silently grow per-device memory step over step
        compiled = {}

        def step_fn(state, x, y):
            if "fn" not in compiled:
                shardings = (self._state_shardings
                             if self._state_shardings is not None
                             else jax.tree.map(lambda l: l.sharding, state))
                metric_sh = NamedSharding(self.mesh, P())
                compiled["fn"] = jax.jit(
                    train_step, donate_argnums=0,
                    out_shardings=(shardings,
                                   {"loss": metric_sh, "accuracy": metric_sh}))
            return compiled["fn"](state, x, y)

        return step_fn

    # ---------------------------------------------------------------- eval
    def _build_eval(self):
        """GSPMD eval (params stay sharded, gathered per layer) — the base
        class's shard_map eval would re-replicate the whole param tree."""
        apply_fn = self.model.apply
        return self._build_eval_gspmd(
            lambda params, x: apply_fn({"params": params}, x, train=False))

    # ------------------------------------------------------------- helpers
    def state_bytes_per_device(self, state: TrainState) -> tuple[int, int]:
        """(bytes on one local device, bytes if fully replicated) for params
        + optimizer state — the FSDP memory claim, asserted in tests.  Uses
        the first *addressable* device so the count is real on every host
        of a multi-process mesh (mesh.devices.flat[0] belongs to host 0)."""
        dev = jax.local_devices()[0]
        per_dev = 0
        total = 0
        for leaf in jax.tree.leaves((state.params, state.opt_state)):
            total += leaf.nbytes
            for shard in leaf.addressable_shards:
                if shard.device == dev:
                    per_dev += shard.data.nbytes
        return per_dev, total
