"""FSDP engine: ZeRO-style fully-sharded data parallelism via GSPMD.

The reference's core design insight is that the optimizer lives in exactly
one place — the server owns the single model and optimizer and workers hold
only transient replicas (reference server.py:52-55, 148-155; client.py:72).
The TPU-first rendering of "parameters and optimizer state are not
replicated" is ZeRO/FSDP: every parameter AND its optimizer moments are
*sharded over the data axis*, all-gathered just-in-time for each layer's
compute, with gradients reduce-scattered back to their owning shard.  Per
device that is ~1/n of the replicated memory — the only DP mode whose model
size can exceed a single chip's HBM.

Compiler-driven like the TP engine (engines/tensor_parallel.py): we place
each state leaf with a `NamedSharding` that splits its largest
n-divisible dimension over ``data``, run the whole step under one
`jax.jit`, and XLA GSPMD inserts the all-gather-on-use /
reduce-scatter-on-grad collectives — the scaling-book recipe, no manual
collectives.  Unlike the TP engine the shardings are derived from leaf
*shapes*, not model annotations, so ANY registered model works unmodified.

Math is identical to the sync engine (same global-batch-mean loss, same
optimizer applied to the same gradients — just sharded), verified by the
parity test in tests/test_fsdp.py.
"""

from __future__ import annotations

import jax
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.engines.base import (
    Engine, TrainState, cross_entropy)
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def fsdp_spec(shape: tuple[int, ...], n: int,
              axis: str = meshlib.DATA_AXIS) -> P:
    """PartitionSpec sharding the largest ``n``-divisible dim over ``axis``.

    Leaves with no divisible dimension (odd-sized biases, scalars, PRNG
    keys) replicate — they are a negligible fraction of model bytes."""
    best = None
    for i, d in enumerate(shape):
        if d % n == 0 and d > 0 and (best is None or d > shape[best]):
            best = i
    if best is None:
        return P()
    spec: list[str | None] = [None] * len(shape)
    spec[best] = axis
    return P(*spec)


class FSDPEngine(Engine):
    """Fully-sharded sync data parallelism on a 1-D ('data',) mesh.

    Same step semantics as SyncEngine; different state layout: params and
    optimizer state are sharded over ``data`` (ZeRO-3), so per-device state
    bytes shrink ~1/n while the training math stays bit-comparable."""

    def __init__(self, model, optimizer=None, mesh=None, learning_rate=1e-3):
        super().__init__(model, optimizer, mesh, learning_rate)
        self._state_shardings = None

    # ---------------------------------------------------------------- init
    def init_state(self, rng: jax.Array, sample_x) -> TrainState:
        """Materialize the state already sharded (never replicated first):
        the base GSPMD init scaffolding with specs derived from leaf SHAPES
        instead of model annotations (any model works unmodified)."""
        n = self.n_devices
        state = self._init_partitioned_state(
            rng, sample_x,
            spec_fn=lambda abstract: jax.tree.map(
                lambda leaf: fsdp_spec(leaf.shape, n), abstract))
        self._state_shardings = self._init_shardings
        return state

    # ---------------------------------------------------------------- step
    def _build_step(self):
        apply_fn = self.model.apply
        tx = self.tx

        def train_step(state: TrainState, x, y):
            rng = jax.random.fold_in(state.rng, state.step)

            def loss_fn(params):
                logits = apply_fn({"params": params}, x, train=True,
                                  rngs={"dropout": rng})
                loss = cross_entropy(logits, y).mean()
                acc = (logits.argmax(-1) == y).mean()
                return loss, acc

            # jit semantics are global: `loss` is the global batch mean.
            # XLA all-gathers each param for its layer's compute and
            # reduce-scatters the grad back to the owning shard; the
            # optimizer update below then runs fully sharded (ZeRO).
            (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params)
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(step=state.step + 1, params=params,
                                 opt_state=opt_state), \
                {"loss": loss, "accuracy": acc}

        # pin the output state to the FSDP layout: without the constraint
        # GSPMD is free to re-layout (e.g. replicate small leaves), which
        # would silently grow per-device memory step over step
        compiled = {}

        def step_fn(state, x, y):
            if "fn" not in compiled:
                shardings = (self._state_shardings
                             if self._state_shardings is not None
                             else jax.tree.map(lambda l: l.sharding, state))
                metric_sh = NamedSharding(self.mesh, P())
                compiled["fn"] = jax.jit(
                    train_step, donate_argnums=0,
                    out_shardings=(shardings,
                                   {"loss": metric_sh, "accuracy": metric_sh}))
            return compiled["fn"](state, x, y)

        return step_fn

    # ---------------------------------------------------------------- eval
    def _build_eval(self):
        """GSPMD eval (params stay sharded, gathered per layer) — the base
        class's shard_map eval would re-replicate the whole param tree."""
        apply_fn = self.model.apply
        return self._build_eval_gspmd(
            lambda params, x: apply_fn({"params": params}, x, train=False))

    # ------------------------------------------------------------- helpers
    def state_bytes_per_device(self, state: TrainState) -> tuple[int, int]:
        """(bytes on one local device, bytes if fully replicated) for params
        + optimizer state — the FSDP memory claim, asserted in tests.  Uses
        the first *addressable* device so the count is real on every host
        of a multi-process mesh (mesh.devices.flat[0] belongs to host 0)."""
        dev = jax.local_devices()[0]
        per_dev = 0
        total = 0
        for leaf in jax.tree.leaves((state.params, state.opt_state)):
            total += leaf.nbytes
            for shard in leaf.addressable_shards:
                if shard.device == dev:
                    per_dev += shard.data.nbytes
        return per_dev, total
