"""Gossip / graph-topology data parallelism over `ppermute`.

Implements for real what the reference only declares: the 'graph' and
'custom' decentralized strategies raise NotImplementedError (reference
initializer.py:175-181), and a `-d` node-degree flag sits commented out
(reference initializer.py:90-92).  Each device trains locally and, every
``mix_every`` steps, averages parameters with its ``degree`` nearest ring
neighbors on each side — a doubly-stochastic gossip mix that provably
preserves the parameter mean (tested in tests/test_collectives.py) and rides
ICI neighbor links, the cheapest traffic pattern on a TPU torus.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.engines.async_local import AsyncLocalEngine
from distributed_tensorflow_tpu.engines.base import TrainState, make_loss_fn
from distributed_tensorflow_tpu.parallel import collectives as coll
from distributed_tensorflow_tpu.parallel import compression


class GossipEngine(AsyncLocalEngine):
    def __init__(self, *args, degree: int = 1, mix_every: int = 1, **kw):
        kw.setdefault("sync_every", 1 << 30)  # no global sync; gossip only
        super().__init__(*args, **kw)
        self.degree = degree
        self.mix_every = mix_every

    def _build_step(self):
        loss_fn = make_loss_fn(self.model.apply)
        tx, axis = self.tx, self.axis
        degree, mix_every = self.degree, self.mix_every
        codec = self.grad_codec

        def device_step(state_1: TrainState, x, y):
            s = jax.tree.map(lambda a: a[0], state_1)
            rng = self._per_device_rng(s.rng, s.step)
            # per-device rounding key: each device quantizes its own copy
            # once, neighbors receive the compressed rendering
            codec_key = compression.codec_rng(rng)
            (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                s.params, x, y, rng)
            updates, opt_state = tx.update(grads, s.opt_state, s.params)
            params = optax.apply_updates(s.params, updates)
            step = s.step + 1
            do_mix = (step % mix_every) == 0
            # the gossip mix through the compression codec: the ppermute
            # hops carry the codec's wire dtype ('none' is the plain
            # neighbor_mean)
            params = jax.lax.cond(
                do_mix,
                lambda p: codec.neighbor_mean(p, axis, degree,
                                              rng=codec_key),
                lambda p: p,
                params,
            )
            metrics = coll.all_reduce_mean({"loss": loss, "accuracy": acc}, axis)
            new_s = s.replace(step=step, params=params, opt_state=opt_state)
            return jax.tree.map(lambda a: a[None], new_s), metrics

        smapped = jax.shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis), P(self.axis)),
            out_specs=(P(self.axis), P()),
            check_vma=False,
        )
        return jax.jit(smapped, donate_argnums=0)
