"""Sequence-parallel (context-parallel) training engine over a 2-D mesh.

New TPU-native capability with no reference counterpart (the reference's
models have no sequence axis, SURVEY.md §2.2 — this is the "long-context is
first-class" requirement): token sequences are sharded over a ``seq`` mesh
axis *in addition to* batch sharding over ``data``, so sequences longer than
one device's memory train with ring or Ulysses attention
(parallel/ring_attention.py).

Gradient bookkeeping: parameters are replicated everywhere.  The model runs
inside shard_map with tokens sharded (B/'data', L/'seq').  Every seq device
computes the same logits (the [CLS] readout is broadcast from seq-device 0,
models/bert.py), so the per-device loss is scaled by 1/seq_n; gradients are
then `psum` over 'seq' (each seq device holds a *partial* grad through its
token block) and `pmean` over 'data' (each data shard holds the mean over
its examples).  The broadcast/ppermute transposes deliver exactly the right
cross-device cotangents — verified against single-device dense training in
tests/test_seq_parallel.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.engines.base import (
    Engine, TrainState, cross_entropy, token_weights)
from distributed_tensorflow_tpu.parallel import collectives as coll
from distributed_tensorflow_tpu.parallel import compression
from distributed_tensorflow_tpu.parallel import mesh as meshlib


class SeqParallelEngine(Engine):
    """Data×sequence parallel sync training.

    ``mesh`` must have axes ('data', 'seq'); the model's ``attention_impl``
    must be 'ring', 'ring_flash', 'ulysses' or 'ulysses_flash' with
    ``seq_axis='seq'``.
    """

    seq_axis = meshlib.SEQ_AXIS

    def __init__(self, model, optimizer=None, mesh=None, learning_rate=1e-3,
                 grad_accum: int = 1, grad_compression: str = "none",
                 grad_bucket_mb: float = 0.0, precision: str = "f32"):
        if mesh is None:
            raise ValueError("SeqParallelEngine requires an explicit "
                             "('data','seq') mesh")
        if set(mesh.axis_names) != {meshlib.DATA_AXIS, meshlib.SEQ_AXIS}:
            raise ValueError(f"mesh axes must be (data, seq), got {mesh.axis_names}")
        if getattr(model, "attention_impl", None) not in (
                "ring", "ring_flash", "ulysses", "ulysses_flash"):
            raise ValueError(
                "SeqParallelEngine needs a model with attention_impl "
                "'ring', 'ring_flash', 'ulysses' or 'ulysses_flash' — "
                "dense attention on sequence-sharded activations would "
                "silently attend within local blocks only")
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        self.grad_accum = grad_accum
        # bf16 policies ride the base hooks; fp16-f32master is rejected by
        # the base (no loss-scale thread through the seq-sharded loss)
        super().__init__(model, optimizer, mesh, learning_rate,
                         grad_compression=grad_compression,
                         grad_bucket_mb=grad_bucket_mb,
                         precision=precision)
        self.seq_n = mesh.shape[self.seq_axis]
        # causal LMs (models/gpt.py) have (B, L) per-token labels that shard
        # over (data, seq) WITH the inputs, and per-device logits that VARY
        # over 'seq' (no [CLS] broadcast) — both loss paths below branch on
        # this marker
        self.lm = bool(getattr(model, "causal_lm", False))

    # Params are initialized OUTSIDE shard_map: the ring/broadcast collectives
    # can't trace there, so init uses a dense-attention twin (identical param
    # structure — only the attention *algorithm* differs), on a local-length
    # sequence slice (param shapes don't depend on seq length).
    def init_state(self, rng, sample_x) -> TrainState:
        lq = sample_x.shape[1] // self.seq_n
        twin = self.model
        if getattr(twin, "attention_impl", "dense") != "dense":
            twin = twin.clone(attention_impl="dense")
        params = twin.init(rng, jnp.asarray(sample_x[:1, :lq]),
                           train=False)["params"]
        opt_state = self.tx.init(params)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           opt_state=opt_state, rng=rng)
        return meshlib.state_to_global(state, meshlib.replicated(self.mesh))

    def shard_batch(self, x, y, mask=None, process_local=False):
        xs = self._place(x, NamedSharding(
            self.mesh, P(meshlib.DATA_AXIS, meshlib.SEQ_AXIS)), process_local)
        # LM targets are per-token (B, L): they shard with the inputs so each
        # seq device scores its own token block locally
        y_spec = (P(meshlib.DATA_AXIS, meshlib.SEQ_AXIS)
                  if self.lm and y.ndim >= 2 else P(meshlib.DATA_AXIS))
        ys = self._place(y, NamedSharding(self.mesh, y_spec), process_local)
        if mask is None:
            return xs, ys
        ms = self._place(
            mask, NamedSharding(self.mesh, P(meshlib.DATA_AXIS)),
            process_local)
        return xs, ys, ms

    def _build_step(self):
        apply_fn = self.model.apply
        tx, K = self.tx, self.grad_accum
        data_axis, seq_axis = self.axis, self.seq_axis
        lm = self.lm
        codec = self.grad_codec

        def device_step(state: TrainState, x, y):
            rng = jax.random.fold_in(state.rng, state.step)
            # codec rounding key derived BEFORE the per-device folds: the
            # combined gradient is invariant over BOTH axes (the AD
            # transpose psums it global), so every device must quantize it
            # identically or the replicated params silently diverge
            codec_key = compression.codec_rng(rng)
            rng = jax.random.fold_in(rng, coll.axis_index(data_axis))
            # fold over seq too: every dropout op in the model acts on
            # seq-sharded activations (token blocks), so per-seq-device masks
            # must be independent — a shared mask would drop the same local
            # offsets in every block (structured, weaker regularization)
            rng = jax.random.fold_in(rng, coll.axis_index(seq_axis))
            dp = lax.axis_size(data_axis)
            sp = lax.axis_size(seq_axis)

            def scaled_loss(params, x, y, rng):
                logits = apply_fn({"params": params}, x, train=True,
                                  rngs={"dropout": rng})
                loss = cross_entropy(logits, y).mean()
                acc = (logits.argmax(-1) == y).mean()
                # Classification: the loss is varying over 'data' (per-shard
                # batches) and INVARIANT over 'seq' (logits come from the
                # [CLS] broadcast, identical on every seq device).
                # shard_map's AD transpose psums param-cotangents over BOTH
                # axes at the varying→invariant boundaries (incl. through
                # the ring's ppermutes), so with the 1/dp scaling the
                # returned grads are exactly the global-batch mean gradient
                # — no explicit grad collectives (verified against
                # single-device dense training in tests/test_seq_parallel.py,
                # with SGD so scaling can't hide behind Adam's scale
                # invariance).
                #
                # LM: per-token logits VARY over 'seq' too — each device's
                # local mean covers 1/(dp·sp) of the global tokens, so the
                # scale is 1/(dp·sp); the psum over both axes then sums the
                # per-device partials into the global-mean gradient (same
                # oracle test, tests/test_gpt.py).
                return loss / (dp * sp if lm else dp), (loss, acc)

            grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)
            if K == 1:
                (_, (loss, acc)), grads = grad_fn(state.params, x, y, rng)
            else:
                # K-microbatch accumulation on the LOCAL batch shard: the
                # per-chunk grads are already globally correct (each chunk's
                # AD transpose psums its partial cotangents over data+seq),
                # so the scan just sums K of them and divides — identical
                # math to K=1 with ~K× less activation memory.  Dropout
                # folds the chunk index (independent masks per microbatch).
                b = x.shape[0]
                # local per-data-shard batch must split into K chunks; the
                # harness validates the global batch, this guards direct use
                if b % K:
                    raise ValueError(
                        f"local batch {b} not divisible by grad_accum {K}")
                xm = x.reshape((K, b // K) + x.shape[1:])
                ym = y.reshape((K, b // K) + y.shape[1:])

                def micro(carry, chunk):
                    g_acc, l_acc, a_acc, i = carry
                    xc, yc = chunk
                    (_, (l, a)), g = grad_fn(
                        state.params, xc, yc, jax.random.fold_in(rng, i))
                    return (jax.tree.map(jnp.add, g_acc, g),
                            l_acc + l, a_acc + a, i + 1), None

                # scan carries must match the body's varying-manual-axes
                # types: the per-device loss/acc VARY over 'data' (and
                # 'seq' for LMs), so the zero init must be cast varying
                # (grads transpose back to invariant at the P() param
                # boundary, so they stay plain zeros)
                vaxes = (data_axis, seq_axis) if lm else (data_axis,)
                zero = jax.lax.pcast(jnp.zeros((), jnp.float32), vaxes,
                                     to="varying")
                init = (jax.tree.map(jnp.zeros_like, state.params),
                        zero, zero, jnp.zeros((), jnp.int32))
                (g_sum, l_sum, a_sum, _), _ = lax.scan(micro, init, (xm, ym))
                grads = jax.tree.map(lambda t: t / K, g_sum)
                loss, acc = l_sum / K, a_sum / K
            if codec.name != "none":
                # the gradient collective here is the implicit AD-transpose
                # psum over (data, seq) — the codec applies as a
                # quantize→dequantize roundtrip with an all-axes-invariant
                # key (see codec_key above)
                grads = codec.roundtrip(grads, rng=codec_key)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            axes = (data_axis, seq_axis) if lm else data_axis
            metrics = {
                "loss": lax.pmean(loss, axes),
                "accuracy": lax.pmean(acc, axes),
            }
            new_state = state.replace(
                step=state.step + 1, params=params, opt_state=opt_state)
            return new_state, metrics

        y_spec = P(data_axis, seq_axis) if lm else P(data_axis)
        smapped = jax.shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P(), P(data_axis, seq_axis), y_spec),
            out_specs=(P(), P()),
        )
        return jax.jit(smapped, donate_argnums=0)

    def _build_eval(self):
        apply_fn = self.model.apply
        data_axis, seq_axis = self.axis, self.seq_axis
        lm = self.lm

        def device_eval(params, x, y, mask):
            logits = apply_fn({"params": params}, x, train=False)
            w = token_weights(mask, y)
            correct = ((logits.argmax(-1) == y) * w).sum()
            loss_sum = (cross_entropy(logits, y) * w).sum()
            count = w.sum()
            # classification: logits identical across seq (invariant), only
            # the data axis reduces.  LM: every seq device scored its own
            # token block — reduce both.
            axes = (data_axis, seq_axis) if lm else data_axis
            out = lax.psum(jnp.stack([correct, loss_sum, count]), axes)
            return out[0], out[1], out[2]

        y_spec = P(data_axis, seq_axis) if lm else P(data_axis)
        smapped = jax.shard_map(
            device_eval, mesh=self.mesh,
            in_specs=(P(), P(data_axis, seq_axis), y_spec, P(data_axis)),
            out_specs=(P(), P(), P()),
        )
        return jax.jit(smapped)
        # Engine.evaluate is inherited: self.n_devices is the data-axis size,
        # and shard_batch/_build_eval above handle the 2-D placement.
