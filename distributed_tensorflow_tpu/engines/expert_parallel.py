"""Expert parallelism via GSPMD auto-sharding.

No reference counterpart (SURVEY.md §2.2: no MoE anywhere); TPU-native new
capability.  Compiler-driven like the tensor-parallel engine: expert weights
carry ``with_partitioning('expert', ...)`` annotations (models/moe.py), the
batch is sharded over BOTH mesh axes (every device holds a token shard), and
XLA GSPMD lowers the dispatch/combine einsums to the all-to-alls that carry
token slots to their expert's device over ICI.

Loss = task cross-entropy + ``aux_weight`` × the Switch load-balancing
auxiliary loss the model sows into ``intermediates`` — without it top-1
routing collapses onto a few experts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.engines.base import (
    Engine, TrainState, cross_entropy)
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def _sum_aux(intermediates) -> jax.Array:
    """Total of every sown aux_loss (one per MoE layer)."""
    leaves = jax.tree.leaves(intermediates)
    return sum(leaves, jnp.zeros((), jnp.float32))


class ExpertParallelEngine(Engine):
    """data × expert parallel sync training under one jit (GSPMD).

    ``mesh`` must have axes ('data', 'expert'); tokens shard over the whole
    mesh, stacked expert weights over 'expert' only (replicated over 'data').
    """

    def __init__(self, model, optimizer=None, mesh=None, learning_rate=1e-3,
                 aux_weight: float = 0.01):
        if mesh is None or set(mesh.axis_names) != {meshlib.DATA_AXIS,
                                                    meshlib.EXPERT_AXIS}:
            raise ValueError(
                "ExpertParallelEngine requires a ('data','expert') mesh")
        self.aux_weight = aux_weight
        super().__init__(model, optimizer, mesh, learning_rate)
        # tokens shard over the WHOLE mesh (see shard_batch), so batch
        # divisibility is against every device, not just the data axis
        self.n_devices = (mesh.shape[meshlib.DATA_AXIS]
                          * mesh.shape[meshlib.EXPERT_AXIS])

    # every device holds a token shard: batch split over both mesh axes
    def _batch_sharding(self, ndim: int) -> NamedSharding:
        spec = P((meshlib.DATA_AXIS, meshlib.EXPERT_AXIS),
                 *([None] * (ndim - 1)))
        return NamedSharding(self.mesh, spec)

    def shard_batch(self, x, y, mask=None, process_local=False):
        xs = self._place(x, self._batch_sharding(x.ndim), process_local)
        ys = self._place(y, self._batch_sharding(y.ndim), process_local)
        if mask is None:
            return xs, ys
        ms = self._place(mask, self._batch_sharding(mask.ndim), process_local)
        return xs, ys, ms

    def init_state(self, rng, sample_x) -> TrainState:
        return self._init_partitioned_state(rng, sample_x)

    def _build_step(self):
        apply_fn = self.model.apply
        tx, aux_weight = self.tx, self.aux_weight

        def train_step(state: TrainState, x, y):
            rng = jax.random.fold_in(state.rng, state.step)

            def loss_fn(params):
                logits, col = apply_fn(
                    {"params": params}, x, train=True,
                    rngs={"dropout": rng}, mutable=["intermediates"])
                task = cross_entropy(logits, y).mean()
                aux = _sum_aux(col["intermediates"])
                acc = (logits.argmax(-1) == y).mean()
                return task + aux_weight * aux, (task, acc)

            (loss, (task, acc)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(step=state.step + 1, params=params,
                                 opt_state=opt_state), \
                {"loss": task, "accuracy": acc, "total_loss": loss}

        # jit semantics are global; GSPMD inserts the expert all-to-alls
        return jax.jit(train_step, donate_argnums=0)

    def _build_eval(self):
        apply_fn = self.model.apply

        def eval_step(params, x, y, mask):
            logits = apply_fn({"params": params}, x, train=False)
            correct = ((logits.argmax(-1) == y) * mask).sum()
            loss_sum = (cross_entropy(logits, y) * mask).sum()
            return correct, loss_sum, mask.sum()

        return jax.jit(eval_step)
