"""Expert parallelism via GSPMD auto-sharding.

No reference counterpart (SURVEY.md §2.2: no MoE anywhere); TPU-native new
capability.  Compiler-driven like the tensor-parallel engine: expert weights
carry ``with_partitioning('expert', ...)`` annotations (models/moe.py), the
batch is sharded over BOTH mesh axes (every device holds a token shard), and
XLA GSPMD lowers the dispatch/combine einsums to the all-to-alls that carry
token slots to their expert's device over ICI.

Loss = task cross-entropy + ``aux_weight`` × the Switch load-balancing
auxiliary loss + ``router_z_weight`` × the router z-loss, both sown by the
model into ``intermediates`` — without the balance loss top-1 routing
collapses onto a few experts.  The per-step metrics carry ``overflow``
(fraction of routing assignments dropped at capacity), so router collapse
is observable directly instead of as silent accuracy loss — and sustained
high overflow additionally WARNS (an _OverflowMonitor watches a rolling
window; VERDICT r3 #10: a collapsed router must be loud, not a metric
someone has to be watching).
"""

from __future__ import annotations

import collections
import warnings

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.engines.base import (
    Engine, TrainState, cross_entropy)
from distributed_tensorflow_tpu.parallel import mesh as meshlib
from distributed_tensorflow_tpu.parallel import compression


class _OverflowMonitor:
    """Rolling watch over the per-step expert-overflow fraction.

    Buffers the (device-scalar) metric and evaluates the window mean every
    ``window`` observations — fetching only at that cadence keeps the step
    loop's async dispatch intact.  When the mean exceeds ``threshold`` it
    emits ONE ``warnings.warn`` per sustained-high episode (re-arming after
    the mean drops back under), and keeps totals for the run summary."""

    def __init__(self, threshold: float = 0.25, window: int = 50):
        self.threshold = threshold
        self.window = max(int(window), 1)
        self._buf: collections.deque = collections.deque(maxlen=self.window)
        self._count = 0
        self._armed = True
        self.last_window_mean: float | None = None
        self.warning_count = 0

    def observe(self, overflow) -> None:
        self._buf.append(overflow)
        self._count += 1
        if self._count % self.window:
            return
        mean = float(sum(float(v) for v in self._buf) / len(self._buf))
        self.last_window_mean = mean
        if mean > self.threshold:
            if self._armed:
                self._armed = False
                self.warning_count += 1
                warnings.warn(
                    f"MoE expert overflow averaged {mean:.1%} over the last "
                    f"{len(self._buf)} steps (threshold "
                    f"{self.threshold:.0%}): tokens are being dropped at "
                    f"expert capacity — the router may have collapsed; "
                    f"raise capacity_factor or aux_weight",
                    stacklevel=3)
        else:
            self._armed = True

    def report(self) -> dict:
        """Summary fields for the harness run() output."""
        return {
            "expert_overflow_window_mean": self.last_window_mean,
            "expert_overflow_warnings": self.warning_count,
        }


def _collect(intermediates, name: str) -> list[jax.Array]:
    """Leaves sown under ``name`` (one per MoE layer) — the layers sow
    several diagnostics (aux_loss, z_loss, overflow), so summing ALL
    leaves would silently mix them."""
    out = []

    def visit(path, leaf):
        if any(isinstance(k, jax.tree_util.DictKey) and k.key == name
               for k in path):
            out.append(leaf)

    jax.tree_util.tree_map_with_path(visit, intermediates)
    return out


def _sum_named(intermediates, name: str) -> jax.Array:
    return sum(_collect(intermediates, name), jnp.zeros((), jnp.float32))


def _mean_named(intermediates, name: str) -> jax.Array:
    leaves = _collect(intermediates, name)
    return (_sum_named(intermediates, name) / max(len(leaves), 1))


def router_losses(intermediates):
    """(aux_loss_sum, z_loss_sum, overflow_mean) from the sown router
    diagnostics — the single definition of MoE loss extraction, shared by
    this engine and the ep×sp composite (engines/composite.py) so the two
    cannot silently diverge.  Overflow is stop-gradiented: it is a
    diagnostic (fraction of routing assignments dropped at capacity), not
    a loss term."""
    aux = _sum_named(intermediates, "aux_loss")
    z = _sum_named(intermediates, "z_loss")
    overflow = jax.lax.stop_gradient(_mean_named(intermediates, "overflow"))
    return aux, z, overflow


class ExpertParallelEngine(Engine):
    """data × expert parallel sync training under one jit (GSPMD).

    ``mesh`` must have axes ('data', 'expert'); tokens shard over the whole
    mesh, stacked expert weights over 'expert' only (replicated over 'data').
    """

    def __init__(self, model, optimizer=None, mesh=None, learning_rate=1e-3,
                 aux_weight: float = 0.01, router_z_weight: float = 0.0,
                 overflow_warn_threshold: float = 0.25,
                 overflow_window: int = 50, grad_accum: int = 1,
                 grad_compression: str = "none",
                 grad_bucket_mb: float = 0.0, precision: str = "f32"):
        # (data, expert) base mesh; an optional 'model' axis composes ep×tp
        # — each expert's FFN Megatron-split over it (models/moe.py
        # partition_model), still one GSPMD jit
        valid = ({meshlib.DATA_AXIS, meshlib.EXPERT_AXIS},
                 {meshlib.DATA_AXIS, meshlib.EXPERT_AXIS, meshlib.MODEL_AXIS})
        if mesh is None or set(mesh.axis_names) not in valid:
            raise ValueError(
                "ExpertParallelEngine requires a ('data','expert'[,'model']) "
                "mesh")
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        self.aux_weight = aux_weight
        self.router_z_weight = router_z_weight
        self.grad_accum = grad_accum
        self.overflow_monitor = _OverflowMonitor(overflow_warn_threshold,
                                                 overflow_window)
        # bf16 policies ride the base hooks; fp16-f32master is rejected by
        # the base (the router-aux loss does not thread the loss scale)
        super().__init__(model, optimizer, mesh, learning_rate,
                         grad_compression=grad_compression,
                         grad_bucket_mb=grad_bucket_mb,
                         precision=precision)
        # tokens shard over the WHOLE mesh (see shard_batch), so batch
        # divisibility is against every device, not just the data axis
        self.n_devices = (mesh.shape[meshlib.DATA_AXIS]
                          * mesh.shape[meshlib.EXPERT_AXIS])

    # every device holds a token shard: batch split over both mesh axes
    def _batch_sharding(self, ndim: int) -> NamedSharding:
        spec = P((meshlib.DATA_AXIS, meshlib.EXPERT_AXIS),
                 *([None] * (ndim - 1)))
        return NamedSharding(self.mesh, spec)

    def shard_batch(self, x, y, mask=None, process_local=False):
        xs = self._place(x, self._batch_sharding(x.ndim), process_local)
        ys = self._place(y, self._batch_sharding(y.ndim), process_local)
        if mask is None:
            return xs, ys
        ms = self._place(mask, self._batch_sharding(mask.ndim), process_local)
        return xs, ys, ms

    def init_state(self, rng, sample_x) -> TrainState:
        return self._init_partitioned_state(rng, sample_x)

    def step(self, state, x, y):
        state, metrics = super().step(state, x, y)
        self.overflow_monitor.observe(metrics["overflow"])
        return state, metrics

    def _build_step(self):
        from distributed_tensorflow_tpu.engines.base import gspmd_grad_accum

        apply_fn = self.model.apply
        tx, K = self.tx, self.grad_accum
        aux_weight, z_weight = self.aux_weight, self.router_z_weight

        def loss_fn(params, x, y, rng):
            logits, col = apply_fn(
                {"params": params}, x, train=True,
                rngs={"dropout": rng}, mutable=["intermediates"])
            task = cross_entropy(logits, y).mean()
            # a collapsed router is visible in the overflow metric instead
            # of as silent accuracy loss
            aux, z, overflow = router_losses(col["intermediates"])
            acc = (logits.argmax(-1) == y).mean()
            return (task + aux_weight * aux + z_weight * z,
                    (task, acc, overflow))

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        codec = self.grad_codec

        def train_step(state: TrainState, x, y):
            rng = jax.random.fold_in(state.rng, state.step)
            if K == 1:
                ((loss, (task, acc, overflow)),
                 grads) = grad_fn(state.params, x, y, rng)
            else:
                # K-microbatch accumulation (base.gspmd_grad_accum — the
                # aux pytree (task, acc, overflow) is summed then /K):
                # each microbatch runs its own expert all-to-alls, so the
                # dispatch/combine memory drops ~K× like the activations
                grads, loss, (task, acc, overflow) = gspmd_grad_accum(
                    grad_fn, state.params, x, y, rng, K, mesh=self.mesh,
                    batch_axes=(meshlib.DATA_AXIS, meshlib.EXPERT_AXIS))
            if codec.name != "none":
                # GSPMD owns the data-axis gradient all-reduce — the codec
                # applies as a quantize→dequantize roundtrip (compressed-
                # exchange numerics; parallel/compression.py)
                grads = codec.roundtrip(
                    grads, rng=compression.codec_rng(rng))
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(step=state.step + 1, params=params,
                                 opt_state=opt_state), \
                {"loss": task, "accuracy": acc, "total_loss": loss,
                 "overflow": overflow}

        # jit semantics are global; GSPMD inserts the expert all-to-alls
        return jax.jit(train_step, donate_argnums=0)

    def _build_eval(self):
        apply_fn = self.model.apply
        return self._build_eval_gspmd(
            lambda params, x: apply_fn({"params": params}, x, train=False))
