"""Keras-fit-like Trainer — the dist_keras replacement.

The reference's decentralized 'keras' mode wraps training in
`strategy.scope(); model.compile(); model.fit(epochs=1); model.evaluate()`
(reference dist_keras.py:22-58).  This Trainer offers the same ergonomics
over any engine (default: SyncEngine, whose `pmean` *is* the RING allreduce,
reference dist_keras.py:77-78), with the timing window around fit() matching
the reference's elapsed metric (reference dist_keras.py:41-43).
"""

from __future__ import annotations

import math
import time
from typing import Callable

import jax
import numpy as np

from distributed_tensorflow_tpu.engines.sync import SyncEngine
from distributed_tensorflow_tpu.utils.metrics import StepTimer


class Trainer:
    def __init__(self, model, engine=None, mesh=None, learning_rate: float = 1e-3,
                 seed: int = 0, max_in_flight: int = 4, **engine_kw):
        self.engine = engine if engine is not None else SyncEngine(
            model, mesh=mesh, learning_rate=learning_rate, **engine_kw)
        self.model = self.engine.model
        self.seed = seed
        # Bound async dispatch: without a sync point the host enqueues the
        # whole epoch; on oversubscribed hosts (1-core CI with an 8-device
        # fake mesh) queued partitions can miss XLA's 40s collective
        # rendezvous timeout.  Costs nothing on real TPUs.
        self.max_in_flight = max_in_flight
        self.state = None
        self.history: list[dict] = []

    def fit(self, train_ds, epochs: int = 1, batch_size: int | None = None,
            log_every: int = 50, log_fn: Callable[[str], None] = print,
            checkpoint_manager=None, checkpoint_every: int = 0,
            metrics_logger=None, watchdog=None, nan_guard: bool = True,
            max_steps: int | None = None, eval_ds=None,
            target_accuracy: float | None = None, eval_every: int = 50,
            eval_batch: int = 100) -> dict:
        """Train; returns {'elapsed': seconds_around_fit, 'steps': n, ...} —
        the reference's only training metrics (reference dist_keras.py:41-49).

        ``checkpoint_manager``/``checkpoint_every``: periodic TrainState
        checkpoints (+ one final); ``metrics_logger``: per-step JSONL sink.
        ``watchdog``: a utils.failure.Watchdog — beaten once per loop
        iteration (the throttle keeps the loop within max_in_flight of
        device progress, so a hung device stops the beats within that
        window and the watchdog's on_stall callback fires).
        ``nan_guard``: divergence check on metrics already materialized at
        the logging cadence (no extra device syncs; utils/failure.py).
        ``max_steps``: hard step cap across epochs.  ``target_accuracy``
        (with ``eval_ds``): early-stop when test accuracy reaches the
        target — evaluated every ``eval_every`` steps far from the target
        and every ≤10 steps once within 0.05 of it, so the steps-to-target
        figure (BASELINE.md north star) has ≤10-step resolution without
        paying full-eval cost on every step.  The result then carries
        ``reached_target`` and ``eval_accuracy``.
        """
        from distributed_tensorflow_tpu.utils.failure import check_finite
        if target_accuracy is not None and eval_ds is None:
            raise ValueError("target_accuracy requires eval_ds (nothing "
                             "would ever be evaluated against the target)")
        eng = self.engine
        bs = batch_size or train_ds.batch_size or 32
        bs = max(bs, eng.n_devices)
        bs = (bs // eng.n_devices) * eng.n_devices
        # process-sharded input (multi-host): this process's dataset holds
        # 1/P of the examples, so it iterates LOCAL batches of bs/P rows and
        # each step's global batch is assembled from every process's rows
        # (Engine.shard_batch process_local).  Shards are even (.shard
        # even=True), so all processes run the same number of steps — a
        # batch-count mismatch would wedge the collectives.
        shard = getattr(train_ds, "process_shard", None)
        n_procs = shard[1] if shard else 1
        if n_procs > 1:
            if n_procs != jax.process_count():
                # a mismatched shard count would feed
                # make_array_from_process_local_data wrongly-sized rows
                # (multi-process) or silently shrink the global batch to
                # one shard (single-process)
                raise ValueError(
                    f"dataset is sharded {n_procs} ways but this job has "
                    f"{jax.process_count()} process(es); shard with "
                    f"n_shards == process_count (Dataset.process_shard_of)")
            if bs % n_procs:
                # keep BOTH divisibilities: round to a multiple of
                # lcm(n_devices, n_procs) so per-device sharding survives
                unit = math.lcm(eng.n_devices, n_procs)
                bs = max((bs // unit) * unit, unit)
            local_bs = bs // n_procs
        else:
            local_bs = bs
        if self.state is None:
            rng = jax.random.key(self.seed)
            sample = train_ds.x[: max(1, eng.n_devices)]
            self.state = eng.init_state(rng, sample)
        # global step offset: nonzero after a checkpoint --resume, so metric
        # records and checkpoint cadence continue the original numbering
        # instead of restarting at 1
        # (.reshape(-1)[0]: async engine's step is per-device, one per shard)
        start_step = int(np.asarray(jax.device_get(self.state.step)).reshape(-1)[0])
        timer = StepTimer()
        t0 = time.perf_counter()
        steps = 0
        examples = 0
        last_metrics = {}
        in_flight: list = []
        eval_acc = 0.0
        reached = False
        stop = False
        prev_eval_step = 0   # step of the eval BEFORE the current one —
        eval_gap = None      # the honest resolution of a reached target
        for epoch in range(epochs):
            if stop:
                break
            for bx, by, _ in train_ds.batches(
                    local_bs, shuffle=True, seed=self.seed, epoch=epoch,
                    drop_remainder=True):
                with timer:  # amortized dispatch+throttle time (see result)
                    xs, ys = self.engine.shard_batch(
                        bx, by, process_local=n_procs > 1)
                    self.state, metrics = eng.step(self.state, xs, ys)
                    in_flight.append(metrics)
                    if len(in_flight) > self.max_in_flight:
                        jax.block_until_ready(in_flight.pop(0))
                if watchdog is not None:
                    # beat AFTER dispatch+throttle: the first beat arms the
                    # clock past the first-step XLA compile, and throttling
                    # bounds how far this loop runs ahead of the device, so
                    # a hung collective stops the beats within the window
                    watchdog.beat()
                steps += 1
                gstep = start_step + steps
                examples += len(bx) * n_procs  # global examples per step
                if metrics_logger is not None and metrics_logger.should_log(gstep):
                    # throttle-check BEFORE float(): forcing device values
                    # every step would sync the host into the pipeline that
                    # max_in_flight deliberately keeps async
                    floats = {k: float(v) for k, v in metrics.items()}
                    # log first: the diverging step's NaN record must reach
                    # the sink before check_finite raises
                    metrics_logger.log(gstep, **floats)
                    if nan_guard:
                        check_finite(floats, gstep)
                if checkpoint_manager is not None and checkpoint_every and \
                        gstep % checkpoint_every == 0:
                    jax.block_until_ready(self.state)
                    checkpoint_manager.save(self.state)
                if log_every and steps % log_every == 0:
                    m = {k: float(v) for k, v in metrics.items()}
                    if nan_guard:
                        check_finite(m, gstep)
                    last_metrics = m
                    # progress heartbeat — parity with reference client.py:92-94
                    log_fn(f"step {gstep}  loss {m['loss']:.4f}  acc {m['accuracy']:.4f}")
                at_cap = max_steps is not None and steps >= max_steps
                if target_accuracy is not None and eval_ds is not None:
                    # fine cadence when the answer could be near: the first
                    # window (fast-saturating tasks cross before a coarse
                    # first eval) and once accuracy is within 0.05 of the
                    # target; coarse in between.  Always evaluate on the
                    # final step so hitting max_steps can't return a stale
                    # (or never-computed) accuracy.
                    near = (eval_acc >= target_accuracy - 0.05
                            or steps <= eval_every)
                    cadence = min(eval_every, 10) if near else eval_every
                    if steps % max(cadence, 1) == 0 or at_cap:
                        gap = steps - prev_eval_step
                        prev_eval_step = steps
                        eval_acc = self.evaluate(
                            eval_ds, batch_size=eval_batch)["accuracy"]
                        if eval_acc >= target_accuracy:
                            # the crossing lies somewhere in the gap since
                            # the previous eval — report THAT as resolution
                            eval_gap = gap
                            reached = stop = True
                            break
                if at_cap:
                    stop = True
                    break
        if (target_accuracy is not None and eval_ds is not None
                and not reached and steps and prev_eval_step != steps):
            # loop ended by exhausting epochs (not the cap): still finish
            # with a real eval so eval_accuracy is never stale/uncomputed
            eval_gap = steps - prev_eval_step
            eval_acc = self.evaluate(eval_ds, batch_size=eval_batch)["accuracy"]
            reached = eval_acc >= target_accuracy
            if not reached:
                eval_gap = None
        jax.block_until_ready(self.state)
        if nan_guard and steps:
            final = {k: float(v) for k, v in metrics.items()}
            check_finite(final, start_step + steps)
            last_metrics = last_metrics or final
        elapsed = time.perf_counter() - t0
        if checkpoint_manager is not None:
            checkpoint_manager.save(self.state)
        result = {
            "elapsed": elapsed, "steps": steps, "epochs": epochs,
            "start_step": start_step, "examples": examples,
            "examples_per_sec": examples / elapsed if elapsed > 0 else 0.0,
            **({"reached_target": reached, "eval_accuracy": eval_acc,
                "eval_resolution": eval_gap}
               if target_accuracy is not None else {}),
            # per-step wall times: first_step_s isolates XLA compile; steady
            # percentiles measure dispatch pace (device-throughput-bound once
            # the max_in_flight window fills)
            "step_time": timer.summary(),
            **{f"final_{k}": v for k, v in last_metrics.items()},
        }
        self.history.append(result)
        return result

    def evaluate(self, test_ds, batch_size: int = 100) -> dict:
        """Full-test-set eval (reference parity: server.py:179-180)."""
        return self.engine.evaluate(self.state, test_ds, batch_size)
