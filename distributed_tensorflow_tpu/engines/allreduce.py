"""Keras-fit-like Trainer — the dist_keras replacement.

The reference's decentralized 'keras' mode wraps training in
`strategy.scope(); model.compile(); model.fit(epochs=1); model.evaluate()`
(reference dist_keras.py:22-58).  This Trainer offers the same ergonomics
over any engine (default: SyncEngine, whose `pmean` *is* the RING allreduce,
reference dist_keras.py:77-78), with the timing window around fit() matching
the reference's elapsed metric (reference dist_keras.py:41-43).
"""

from __future__ import annotations

import math
import time
from typing import Callable

import jax
import numpy as np

from distributed_tensorflow_tpu.data.device_prefetch import DevicePrefetch
from distributed_tensorflow_tpu.engines.sync import SyncEngine
from distributed_tensorflow_tpu.utils.metrics import StepTimer

# steady-state chunk length when no per-step cadence demands step-granular
# host control (see Trainer.resolve_steps_per_call)
DEFAULT_STEPS_PER_CALL = 8


class Trainer:
    def __init__(self, model, engine=None, mesh=None, learning_rate: float = 1e-3,
                 seed: int = 0, max_in_flight: int = 4, **engine_kw):
        self.engine = engine if engine is not None else SyncEngine(
            model, mesh=mesh, learning_rate=learning_rate, **engine_kw)
        self.model = self.engine.model
        self.seed = seed
        # Bound async dispatch: without a sync point the host enqueues the
        # whole epoch; on oversubscribed hosts (1-core CI with an 8-device
        # fake mesh) queued partitions can miss XLA's 40s collective
        # rendezvous timeout.  Costs nothing on real TPUs.
        self.max_in_flight = max_in_flight
        self.state = None
        self.history: list[dict] = []

    @staticmethod
    def resolve_steps_per_call_with_reason(
            steps_per_call: int | None, *,
            metrics_logger=None, watchdog=None,
            target_accuracy: float | None = None,
            checkpoint_every: int = 0,
            checkpoint_async: bool = False) -> tuple[int, str | None]:
        """(k, clamp_reason) — ``resolve_steps_per_call`` plus WHY auto
        mode downshifted ('target_accuracy' | 'checkpoint_sync' |
        'checkpoint_async' | None).  The reason comes from the SAME branch
        that picked k, so the run report's clamp attribution cannot desync
        from the resolution rules.  The two checkpoint reasons share one
        rule (the crash-loss window is a durability promise either way)
        but are reported distinctly: a synchronous sub-chunk cadence also
        costs a blocking save per chunk — worth a warning — while an
        overlapped save costs only a snapshot, so the async label tells
        the report reader the clamp is cadence-only, not a stall."""
        del metrics_logger, watchdog  # telemetry rides the chunked drain
        if steps_per_call is not None:
            if steps_per_call < 1:
                raise ValueError(
                    f"steps_per_call must be >= 1, got {steps_per_call}")
            return int(steps_per_call), None
        if target_accuracy is not None:
            return 1, "target_accuracy"
        if 0 < checkpoint_every < DEFAULT_STEPS_PER_CALL:
            return checkpoint_every, ("checkpoint_async" if checkpoint_async
                                      else "checkpoint_sync")
        return DEFAULT_STEPS_PER_CALL, None

    @staticmethod
    def resolve_steps_per_call(steps_per_call: int | None, *,
                               metrics_logger=None, watchdog=None,
                               target_accuracy: float | None = None,
                               checkpoint_every: int = 0) -> int:
        """Chunk length of the steady-state drain (``fit(steps_per_call=)``).

        An explicit value wins (validated ≥ 1).  Auto (``None``) picks
        ``DEFAULT_STEPS_PER_CALL`` unless a per-step cadence demands the
        host between every step:

        * ``target_accuracy`` — downshifts to 1: the near-target eval
          cadence (≤10 steps) IS the steps-to-target figure's resolution
          (BASELINE.md), and evals need boundary state every step.

        Telemetry does NOT downshift (the zero-downshift contract,
        observability/):

        * ``metrics_logger`` — per-step records ride the scan's stacked
          trajectory and are flushed to the async JSONL sink once per
          chunk, step-exact and bitwise identical to k=1;
        * ``watchdog`` — beats once per chunk flush with its stall budget
          rescaled to ``k × per-step timeout`` (Watchdog.rescale): k×
          coarser detection resolution, k× fewer host syncs;
        * heartbeat logging (``log_every``) — the drain returns the full
          per-step trajectory each chunk, so log lines stay step-exact.

        A ``checkpoint_every`` shorter than the chunk caps auto's k to it
        (state only exists at chunk boundaries, and silently saving
        k-coarser than asked would widen the crash-loss window); with an
        EXPLICIT steps_per_call, checkpoints land on the first chunk
        boundary at/after their due step instead.  ``metrics_logger`` and
        ``watchdog`` stay in the signature so call sites document what
        rides along, but no longer affect the result.
        """
        del metrics_logger, watchdog  # telemetry rides the chunked drain
        return Trainer.resolve_steps_per_call_with_reason(
            steps_per_call, target_accuracy=target_accuracy,
            checkpoint_every=checkpoint_every)[0]

    def fit(self, train_ds, epochs: int = 1, batch_size: int | None = None,
            log_every: int = 50, log_fn: Callable[[str], None] = print,
            checkpoint_manager=None, checkpoint_every: int = 0,
            metrics_logger=None, watchdog=None, nan_guard: bool = True,
            max_steps: int | None = None, eval_ds=None,
            target_accuracy: float | None = None, eval_every: int = 50,
            eval_batch: int = 100, steps_per_call: int | None = None,
            prefetch: int = 2, tracer=None,
            on_anomaly: str = "warn",
            should_stop: Callable[[int], str | None] | None = None,
            data_state: dict | None = None,
            straggler_detector=None, timeline=None, roofline=None) -> dict:
        """Train; returns {'elapsed': seconds_around_fit, 'steps': n, ...} —
        the reference's only training metrics (reference dist_keras.py:41-49).

        ``checkpoint_manager``/``checkpoint_every``: periodic TrainState
        checkpoints (+ one final); ``metrics_logger``: per-step JSONL sink.
        ``watchdog``: a utils.failure.Watchdog — beaten once per loop
        iteration (the throttle keeps the loop within max_in_flight of
        device progress, so a hung device stops the beats within that
        window and the watchdog's on_stall callback fires).
        ``nan_guard``: divergence check on metrics already materialized at
        the logging cadence (no extra device syncs; utils/failure.py).
        When the engine's health layer is on (``Engine.enable_health`` /
        ``--health on``) the per-step anomaly policy SUBSUMES this
        loss-only guard: every step's on-device health stats (grad norm,
        update ratio, non-finite leaf count, loss spike —
        observability/health.py) are checked host-side at chunk flush,
        and ``on_anomaly`` decides the response — ``'warn'`` records
        structured ``anomaly`` trace events and a ``health`` summary in
        the result, ``'halt'`` additionally raises ``AnomalyDetected`` at
        the offending step.  (At ``steps_per_call == 1`` the policy
        materializes each step's metrics — step-exact detection at the
        cost of a per-step host sync; the chunked drain keeps the
        zero-downshift contract.)
        ``tracer``: an observability.Tracer — spans ``compile`` /
        ``chunk_dispatch`` / ``materialize`` / ``checkpoint`` / ``eval``
        plus prefetch queue-depth gauges at chunk boundaries; defaults to
        the inert NULL_TRACER.  An async checkpoint manager
        (utils/checkpoint.AsyncCheckpointManager) replaces the blocking
        ``checkpoint`` span with ``ckpt_snapshot`` (training-thread
        blocked time: previous-write backpressure + device snapshot) and
        ``ckpt_write`` (the background Orbax write, emitted by the writer
        thread) — the fit result then splits the cost as
        ``checkpoint_wait_s`` (charged against throughput) vs
        ``checkpoint_overlapped_s`` (hidden behind training).
        ``max_steps``: hard step cap across epochs.  ``target_accuracy``
        (with ``eval_ds``): early-stop when test accuracy reaches the
        target — evaluated every ``eval_every`` steps far from the target
        and every ≤10 steps once within 0.05 of it, so the steps-to-target
        figure (BASELINE.md north star) has ≤10-step resolution without
        paying full-eval cost on every step.  The result then carries
        ``reached_target`` and ``eval_accuracy``.

        Elastic hooks (distributed_tensorflow_tpu/elastic/):
        ``should_stop(steps_done) -> reason | None`` is consulted at every
        chunk boundary (each step at k=1) — a truthy reason finishes the
        in-flight chunks, writes the final checkpoint (data state
        included) and returns with ``result['preempted'] = reason``: the
        graceful lease drain, composing with ``steps_per_call > 1`` by
        construction.  ``data_state`` (a checkpoint's elastic sidecar
        payload, possibly ``{}``) positions the batch stream for an
        exactly-once resume: a matching state continues the identical
        batch sequence at its (epoch, batch) and the result reports
        ``resume_replay_steps = 0``; a missing/mismatched state restarts
        the stream from epoch 0 and reports the unrecoverable positions
        (``resume_replay_steps = start_step``) — pass ``None`` (default)
        for the legacy non-elastic resume with no accounting.  Every
        checkpoint this fit writes carries its own data state + save wall
        time as the elastic sidecar, read-ahead drained/discounted (the
        position is the step counter, never the prefetch producer).
        ``straggler_detector`` (elastic.StragglerDetector) observes the
        per-chunk step times the loop already measures and emits
        structured ``straggler`` trace events on outliers; its summary
        rides the result as ``stragglers``.
        ``roofline`` (observability/roofline.Roofline, ``--roofline``):
        analytic model-FLOPs attribution — the result gains
        ``train_model_flops_per_step`` / ``train_achieved_flops_per_sec``
        / ``train_mfu`` (None when the model family or device kind is
        outside the analytic tables — a peak is never invented), and the
        chunked drain samples a per-chunk ``achieved_flops_per_sec``
        gauge on the ``--timeline`` series at the boundaries it already
        syncs.  With ``roofline=None`` (default) the result key set is
        byte-identical to round 18 — the parity pin.

        Steady state: host batches are staged onto the mesh ``prefetch``
        batches ahead (data/device_prefetch.py — transfer N+1 overlaps
        compute N), and ``steps_per_call`` > 1 drains chunks of k
        pre-staged batches through one jitted ``lax.scan`` of the engine's
        train step (``Engine.build_many_step``), with the per-step
        loss/accuracy trajectory carried on-device and materialized once
        per chunk — and, when no chunk-boundary state consumer (periodic
        checkpoints, target eval) is active, up to ``max_in_flight``
        dispatched chunks stay unmaterialized so a slow host↔device link
        is paid per window, not per chunk.  Default auto:
        ``resolve_steps_per_call`` — 8, unless ``target_accuracy``
        downshifts to 1 or a shorter ``checkpoint_every`` caps it;
        telemetry (metrics_logger, watchdog) rides the chunked drain
        without downshifting.  Checkpoint/eval/early-stop/
        nan-guard semantics hold at chunk boundaries; the chunked
        trajectory is step-for-step identical to ``steps_per_call=1`` on
        the same seed.
        """
        from distributed_tensorflow_tpu.observability import health as healthlib
        from distributed_tensorflow_tpu.observability.trace import NULL_TRACER
        from distributed_tensorflow_tpu.utils.failure import (
            AnomalyDetected, check_finite)
        if tracer is None:
            tracer = NULL_TRACER
        if on_anomaly not in ("warn", "halt"):
            raise ValueError(
                f"on_anomaly must be 'warn' or 'halt', got '{on_anomaly}'")
        # health policy state: the engine's health layer (enable_health)
        # carries the per-step stats; the anomaly decisions live here.
        # With health on, the loss-only nan_guard's CADENCE checks are
        # subsumed — but its fail-fast SEMANTIC survives as the alias:
        # divergence ('nonfinite' anomalies) stays fatal under
        # on_anomaly='warn' unless nan_guard was explicitly disabled, so
        # adding --health never silently downgrades a NaN'd run from
        # abort to train-to-completion.  'halt' makes every anomaly kind
        # fatal; 'warn' + nan_guard=False observes only (MIGRATING.md).
        health_cfg = getattr(self.engine, "health", None)
        guard_divergence = nan_guard
        nan_guard = nan_guard and health_cfg is None
        h_max: dict = {}
        anomaly_steps: list[int] = []
        first_anomaly = None
        n_anomalies = 0
        warned_anomaly = False
        # mixed-precision policy (engine --precision; parallel/precision.py)
        # — the fit result names it, and a loss-scaling policy gets its
        # per-step skip accounting surfaced: every skipped (non-finite-
        # grad) step becomes a structured `loss_scale` tracer event, and
        # the nan-guard's fatal-divergence response is WAIVED for that
        # step — the scaler already handled the overflow (backoff + no
        # update), which is the whole point of fp16-f32master
        precision_pol = getattr(self.engine, "precision", None)
        precision_name = getattr(precision_pol, "name", "f32")
        ls_active = bool(getattr(precision_pol, "loss_scaling", False))
        ls_skipped_steps: list[int] = []
        ls_n_skipped = 0
        ls_last_scale = None
        warned_skip = False

        def note_loss_scale(gstep: int, floats: dict) -> None:
            """Per-step loss-scale bookkeeping over materialized floats:
            record the running scale and turn each skipped step into a
            structured trace event (the observable half of the grow/
            backoff loop)."""
            nonlocal ls_last_scale, warned_skip, ls_n_skipped
            scale = floats.get("loss_scale")
            if scale is not None:
                ls_last_scale = scale
            if not floats.get("ls_skipped"):
                return
            ls_n_skipped += 1
            if len(ls_skipped_steps) < 64:  # bounded like anomaly_steps
                ls_skipped_steps.append(gstep)
            tracer.event("loss_scale", step=gstep, action="backoff_skip",
                         scale=scale)
            if not warned_skip:
                warned_skip = True
                log_fn(f"step {gstep}  LOSS-SCALE SKIP (non-finite grads; "
                       f"scale backed off to {scale}) — continuing")

        def note_health(gstep: int, floats: dict) -> None:
            """Per-step anomaly policy over materialized health floats:
            update the run maxima, emit one structured ``anomaly`` trace
            event per offending stat, and on 'halt' raise at THIS step —
            the metrics record was already logged (record first, so the
            diverging step's numbers reach the sink)."""
            nonlocal first_anomaly, n_anomalies, warned_anomaly
            for stat in ("grad_norm", "update_ratio", "loss_spike"):
                v = floats.get(stat)
                if v is not None and math.isfinite(v):
                    h_max[stat] = max(h_max.get(stat, v), v)
            anomalies = healthlib.detect_anomalies(floats, health_cfg)
            if not anomalies:
                return
            n_anomalies += len(anomalies)
            if first_anomaly is None:
                first_anomaly = gstep
            if len(anomaly_steps) < 64:  # bounded: a NaN'd run flags every
                anomaly_steps.append(gstep)  # step until it ends
            for a in anomalies:
                tracer.event("anomaly", step=gstep, policy=on_anomaly, **a)
            a = anomalies[0]
            if floats.get("ls_skipped"):
                # the loss scaler already answered this step's non-finite
                # gradients (skip + backoff — note_loss_scale recorded the
                # structured event): halting or raising here would defeat
                # fp16 training, where occasional overflow is EXPECTED and
                # handled.  The anomaly events above still reach the
                # trace, so nothing is silent.
                return
            if on_anomaly == "halt":
                raise AnomalyDetected(
                    f"health anomaly at step {gstep}: {a['stat']}="
                    f"{a['value']} ({a['reason']}; limit {a['limit']}) — "
                    f"halted by on_anomaly='halt'")
            diverged = [x for x in anomalies if x["kind"] == "nonfinite"]
            if guard_divergence and diverged:
                # the nan_guard alias: divergence is fatal even under
                # 'warn' (now step-exact, vs the old log-cadence check);
                # --no-nan-guard opts into observe-only
                d = diverged[0]
                raise AnomalyDetected(
                    f"training diverged at step {gstep}: {d['stat']}="
                    f"{d['value']} ({d['reason']}) — fatal under the "
                    f"nan-guard default; pass nan_guard=False "
                    f"(--no-nan-guard) to record and continue")
            if not warned_anomaly:
                warned_anomaly = True
                log_fn(f"step {gstep}  ANOMALY {a['stat']}={a['value']} "
                       f"({a['reason']}) — continuing under "
                       f"on_anomaly='warn'")
        if target_accuracy is not None and eval_ds is None:
            raise ValueError("target_accuracy requires eval_ds (nothing "
                             "would ever be evaluated against the target)")
        if prefetch < 1:
            # same contract as DevicePrefetch itself: reject, don't clamp
            # (a silently-promoted --prefetch 0 would misreport its depth)
            raise ValueError(f"prefetch depth must be >= 1, got {prefetch}")
        eng = self.engine
        bs = batch_size or train_ds.batch_size or 32
        bs = max(bs, eng.n_devices)
        bs = (bs // eng.n_devices) * eng.n_devices
        # process-sharded input (multi-host): this process's dataset holds
        # 1/P of the examples, so it iterates LOCAL batches of bs/P rows and
        # each step's global batch is assembled from every process's rows
        # (Engine.shard_batch process_local).  Shards are even (.shard
        # even=True), so all processes run the same number of steps — a
        # batch-count mismatch would wedge the collectives.
        shard = getattr(train_ds, "process_shard", None)
        n_procs = shard[1] if shard else 1
        if n_procs > 1:
            if n_procs != jax.process_count():
                # a mismatched shard count would feed
                # make_array_from_process_local_data wrongly-sized rows
                # (multi-process) or silently shrink the global batch to
                # one shard (single-process)
                raise ValueError(
                    f"dataset is sharded {n_procs} ways but this job has "
                    f"{jax.process_count()} process(es); shard with "
                    f"n_shards == process_count (Dataset.process_shard_of)")
            if bs % n_procs:
                # keep BOTH divisibilities: round to a multiple of
                # lcm(n_devices, n_procs) so per-device sharding survives
                unit = math.lcm(eng.n_devices, n_procs)
                bs = max((bs // unit) * unit, unit)
            local_bs = bs // n_procs
        else:
            local_bs = bs
        if self.state is None:
            rng = jax.random.key(self.seed)
            sample = train_ds.x[: max(1, eng.n_devices)]
            self.state = eng.init_state(rng, sample)
        # global step offset: nonzero after a checkpoint --resume, so metric
        # records and checkpoint cadence continue the original numbering
        # instead of restarting at 1
        # (.reshape(-1)[0]: async engine's step is per-device, one per shard)
        start_step = int(np.asarray(jax.device_get(self.state.step)).reshape(-1)[0])
        # exactly-once data resume (elastic/data_state.py): a restored
        # checkpoint's data state positions the batch stream at the exact
        # (epoch, batch) the saved step had consumed, so the resumed run
        # continues the IDENTICAL batch sequence — None (default) keeps
        # the legacy resume (stream restarts at epoch 0, no accounting);
        # a dict that fails to match this run's seed/batch-size/dataset
        # falls back to the same restart but REPORTS the unrecoverable
        # positions as resume_replay_steps
        start_epoch = 0
        start_batch = 0
        replay_steps = None
        if data_state is not None:
            from distributed_tensorflow_tpu.elastic.data_state import (
                DataState)

            restored_ds = DataState.from_json(data_state)
            if restored_ds is not None and restored_ds.matches(
                    seed=self.seed, batch_size=local_bs,
                    dataset_len=len(train_ds),
                    dataset=getattr(train_ds, "name", "dataset")):
                start_epoch, start_batch = (restored_ds.epoch,
                                            restored_ds.batch_index)
                replay_steps = 0
            else:
                replay_steps = start_step
                if start_step:
                    log_fn(f"elastic resume: checkpoint carries no "
                           f"matching data state — the batch stream "
                           f"restarts from epoch 0 "
                           f"(resume_replay_steps={start_step})")
        # async checkpoint discipline (utils/checkpoint.py
        # AsyncCheckpointManager): saves cost the training thread a device
        # snapshot; the write overlaps the next chunks on a background
        # writer.  The manager's writer emits ckpt_write spans through the
        # fit tracer so the timeline shows blocked vs overlapped time.
        ckpt_async = bool(getattr(checkpoint_manager, "asynchronous", False))
        if ckpt_async:
            checkpoint_manager.tracer = tracer
        ckpt_wait = 0.0  # training-thread seconds spent in checkpointing
        ckpt_last_step = None  # skip a final save the cadence already wrote
        # managers outlive fits (bench reuses one): report THIS fit's
        # overlapped seconds, not the manager's lifetime total
        ckpt_overlap0 = getattr(checkpoint_manager, "overlapped_s", 0.0)
        # batch-stream position of the CURRENT epoch, maintained by the
        # epoch loop: cur_epoch's stream started at epoch_offset and
        # epoch_base was the step counter then, so the boundary position
        # is epoch_offset + (steps - epoch_base) — the step counter, not
        # the prefetch producer, which is how read-ahead gets discounted
        cur_epoch = start_epoch
        epoch_base = 0
        epoch_offset = start_batch
        last_data_state = None

        def current_data_state() -> dict:
            from distributed_tensorflow_tpu.elastic.data_state import (
                DataState)

            return DataState(
                epoch=cur_epoch,
                batch_index=epoch_offset + (steps - epoch_base),
                seed=self.seed, batch_size=local_bs,
                dataset_len=len(train_ds),
                dataset=getattr(train_ds, "name", "dataset")).to_json()

        def do_checkpoint(step: int, final: bool = False) -> None:
            """One boundary checkpoint, both disciplines: sync blocks for
            the full write under a ``checkpoint`` span; async pays only
            the snapshot (+ any previous-write backpressure) under
            ``ckpt_snapshot`` — the final save additionally drains, so fit
            never returns with a write in flight.  Every write carries
            the elastic sidecar (data state + save wall time) that makes
            the checkpoint a resumable object."""
            nonlocal ckpt_wait, ckpt_last_step, last_data_state
            t0 = time.perf_counter()
            # the final boundary often IS the last cadence boundary (steps
            # divisible by checkpoint_every): that state is already saved
            # — or in flight — so re-writing it would only re-pay the full
            # write; the final call then just drains
            skip_write = final and step == ckpt_last_step
            if not skip_write:
                last_data_state = current_data_state()
                extra = {"data_state": last_data_state,
                         "wall_time": time.time(), "step": step,
                         "schema": 1}
            # the boundary step is known here — passing it spares save()
            # its state.step device sync on the training thread
            if ckpt_async:
                attrs = {"step": step, **({"final": True} if final else {})}
                with tracer.span("ckpt_snapshot", **attrs):
                    if not skip_write:
                        checkpoint_manager.save(self.state, step=step,
                                                extra=extra)
                    if final:
                        checkpoint_manager.wait()
            elif not skip_write:
                with tracer.span("checkpoint", step=step,
                                 **({"final": True} if final else {})):
                    jax.block_until_ready(self.state)
                    checkpoint_manager.save(self.state, step=step,
                                            extra=extra)
            ckpt_last_step = step
            ckpt_wait += time.perf_counter() - t0

        k, clamp_reason = self.resolve_steps_per_call_with_reason(
            steps_per_call, metrics_logger=metrics_logger, watchdog=watchdog,
            target_accuracy=target_accuracy,
            checkpoint_every=(checkpoint_every
                              if checkpoint_manager is not None else 0),
            checkpoint_async=ckpt_async)
        # surface auto-mode downshifts (the run report carries the reason,
        # attributed by the resolver itself; SYNC checkpoint clamps
        # additionally warn — the shortened chunk also costs a blocking
        # save per chunk, whereas an async clamp is cadence-only — and an
        # explicit steps_per_call is never clamped, checkpoints then land
        # on chunk boundaries)
        spc_clamp = None
        if clamp_reason is not None:
            spc_clamp = {"requested": DEFAULT_STEPS_PER_CALL,
                         "effective": k, "reason": clamp_reason}
            if clamp_reason == "checkpoint_sync":
                import warnings

                warnings.warn(
                    f"checkpoint_every={checkpoint_every} caps the "
                    f"steady-state drain at steps_per_call={k} (auto "
                    f"default {DEFAULT_STEPS_PER_CALL}): state exists only "
                    f"at chunk boundaries, so the requested crash-loss "
                    f"window shortens the chunk — and each boundary pays a "
                    f"blocking synchronous save.  Pass an explicit "
                    f"--steps-per-call to keep longer chunks (checkpoints "
                    f"then land on the first boundary at/after each due "
                    f"step), or use the async checkpoint manager to take "
                    f"the save off the critical path.", stacklevel=2)
        if watchdog is not None:
            # one beat per host sync = one beat per chunk: the per-step
            # stall budget becomes a per-beat budget of k × timeout, so
            # the watchdog rides the chunked drain instead of forcing k=1
            watchdog.rescale(k)
        # --roofline: analytic model FLOPs of one optimizer step (grad-
        # accum invariant — K microbatches sum to the same tokens).  The
        # cost model covers the GPT family only; a 2-D token batch is the
        # shape it describes, anything else keeps the honest None.
        rf_flops_step = None
        if roofline is not None and roofline.cost is not None:
            xshape = np.shape(train_ds.x)
            if len(xshape) == 2:
                rf_flops_step = roofline.cost.train_step_flops(
                    bs, int(xshape[1]),
                    grad_accum=int(getattr(eng, "grad_accum", 1) or 1))
        grad_bytes = eng.grad_collective_bytes(self.state)        # wire
        grad_bytes_raw = eng.grad_collective_bytes_raw(self.state)
        # per-device state footprint (Engine.param_bytes_per_device /
        # opt_state_bytes_per_device): the storage numbers the precision
        # policy moves — bf16 storage halves param bytes, a master policy
        # grows optimizer bytes by the f32 copy.  Measured off the real
        # shard sizes, reported in the run report and gated lower-is-
        # better by `analyze diff`.
        param_bytes_dev = eng.param_bytes_per_device(self.state)
        opt_bytes_dev = eng.opt_state_bytes_per_device(self.state)
        grad_codec = getattr(getattr(eng, "grad_codec", None), "name", "none")
        # overlap bucketing (parallel/overlap.py): 0.0 when the codec is
        # unbucketed — the wire figure above is then per-leaf, else
        # per-bucket (the honest int8 scale accounting)
        grad_bucket_mb = float(getattr(
            getattr(eng, "grad_codec", None), "bucket_mb", 0.0) or 0.0)
        if grad_bytes:
            # WIRE bytes one gradient collective moves per round under the
            # engine's --grad-compression codec, plus the raw (f32-era)
            # figure for comparison — the collective-path size every
            # scaling analysis starts from (param dtypes are real, the
            # bench_decode accounting)
            tracer.event("collective_profile",
                         grad_allreduce_bytes=grad_bytes,
                         grad_allreduce_bytes_raw=grad_bytes_raw,
                         grad_compression=grad_codec,
                         grad_bucket_mb=grad_bucket_mb,
                         n_devices=eng.n_devices)
        timer = StepTimer()
        t0 = time.perf_counter()
        steps = 0
        examples = 0
        last_metrics = {}
        in_flight: list = []
        eval_acc = 0.0
        reached = False
        stop = False
        preempted = None     # should_stop's reason once the drain fires
        compiled = False     # first dispatch carries the XLA compile —
        chunk_sizes: set[int] = set()  # its span is named 'compile'
        pf_starvation = 0    # prefetch gauges accumulated across epochs
        pf_fill_wait = 0.0
        prev_eval_step = 0   # step of the eval BEFORE the current one —
        eval_gap = None      # the honest resolution of a reached target

        def place(batch):
            # staged with the engine's input NamedSharding; device_put is
            # non-blocking, so the prefetcher's read-ahead IS the overlap
            bx, by, _mask = batch
            return self.engine.shard_batch(bx, by, process_local=n_procs > 1)

        def eval_and_maybe_stop(prev_steps: int, at_cap: bool) -> bool:
            """Target-accuracy eval at the cadence boundary (shared by both
            drain shapes); True = target reached, stop now.  Fine cadence
            when the answer could be near: the first window (fast-saturating
            tasks cross before a coarse first eval) and once accuracy is
            within 0.05 of the target; coarse in between.  Always evaluates
            at the cap so hitting max_steps can't return a stale (or
            never-computed) accuracy."""
            nonlocal eval_acc, prev_eval_step, eval_gap, reached, stop
            if target_accuracy is None or eval_ds is None:
                return False
            near = (eval_acc >= target_accuracy - 0.05 or steps <= eval_every)
            cadence = max(min(eval_every, 10) if near else eval_every, 1)
            # crossing test, not modulo: chunk boundaries may step past the
            # due step without landing on it (k == 1 reduces to steps%cadence)
            if not (steps // cadence > prev_steps // cadence or at_cap):
                return False
            gap = steps - prev_eval_step
            prev_eval_step = steps
            with tracer.span("eval", step=steps):
                eval_acc = self.evaluate(
                    eval_ds, batch_size=eval_batch)["accuracy"]
            if eval_acc >= target_accuracy:
                # the crossing lies somewhere in the gap since the previous
                # eval — report THAT as the steps-to-target resolution
                eval_gap = gap
                reached = stop = True
                return True
            return False

        def record_step(gstep: int, floats_fn) -> None:
            """Per-step sinks shared by both drain shapes: metrics-logger
            (log FIRST — a diverging step's NaN record must reach the sink
            before check_finite raises), then the log_every heartbeat with
            its nan guard.  ``floats_fn`` materializes the step's float
            metrics lazily: the k==1 path must not sync the device unless
            a cadence actually fires (max_in_flight keeps it async)."""
            nonlocal last_metrics
            if metrics_logger is not None and metrics_logger.should_log(gstep):
                floats = floats_fn()
                metrics_logger.log(gstep, **floats)
                if nan_guard:
                    check_finite(floats, gstep)
            if log_every and steps % log_every == 0:
                m = floats_fn()
                if nan_guard:
                    check_finite(m, gstep)
                last_metrics = m
                # progress heartbeat — reference client.py:92-94
                log_fn(f"step {gstep}  loss {m['loss']:.4f}"
                       f"  acc {m['accuracy']:.4f}")

        # A failed fit (AnomalyDetected halt, divergence, watchdog abort
        # path, a raising engine) must not leak background work: the
        # prefetcher is closed by its per-epoch finally below, and the
        # except block drains the async checkpoint writer and flushes the
        # telemetry sinks before the error propagates — no writer thread
        # or half-buffered JSONL record outlives the fit.  The cleanup
        # never masks the original error: the drain runs reraise=False
        # and the flushes swallow their own failures.
        try:
            for epoch in range(start_epoch, epochs):
                if stop:
                    break
                # mid-epoch resume: only the FIRST resumed epoch starts at
                # the restored batch offset; the shuffle permutation is a
                # function of (seed, epoch) alone, so the stream continues
                # the exact sequence the uninterrupted run would have
                ebatch = start_batch if epoch == start_epoch else 0
                cur_epoch, epoch_base, epoch_offset = epoch, steps, ebatch
                pf = DevicePrefetch(
                    train_ds.batches(local_bs, shuffle=True, seed=self.seed,
                                     epoch=epoch, drop_remainder=True,
                                     start_batch=ebatch),
                    place, depth=prefetch)
                try:
                    if k == 1:
                        for xs, ys in pf:
                            chunk_sizes.add(1)  # per ACTUAL dispatch: a
                            # zero-batch epoch must not report a chunk shape
                            with timer:  # amortized dispatch+throttle time
                                if not compiled:
                                    # first dispatch traces+compiles the step
                                    # synchronously — span it under the name
                                    # the run report splits out
                                    with tracer.span("compile", steps=1):
                                        self.state, metrics = eng.step(
                                            self.state, xs, ys)
                                    compiled = True
                                else:
                                    self.state, metrics = eng.step(
                                        self.state, xs, ys)
                                in_flight.append(metrics)
                                if len(in_flight) > self.max_in_flight:
                                    jax.block_until_ready(in_flight.pop(0))
                            if watchdog is not None:
                                # beat AFTER dispatch+throttle: the first beat
                                # arms the clock past the first-step XLA compile,
                                # and throttling bounds how far this loop runs
                                # ahead of the device, so a hung collective stops
                                # the beats within the window
                                watchdog.beat()
                            steps += 1
                            gstep = start_step + steps
                            examples += bs  # global examples per step
                            if straggler_detector is not None:
                                # the amortized dispatch+throttle time just
                                # appended — the k=1 rendering of the
                                # per-chunk average the drain observes
                                straggler_detector.observe(
                                    gstep, timer.times[-1])
                            dev_metrics = metrics
                            if health_cfg is not None or ls_active:
                                # the anomaly/loss-scale policy needs this
                                # step's values: materialize now (per-step
                                # sync — the honest cost of step-exact
                                # detection at k=1; the chunked drain pays
                                # one sync per chunk)
                                floats = {kk: float(v)
                                          for kk, v in dev_metrics.items()}
                                record_step(gstep, lambda f=floats: f)
                                if ls_active:
                                    note_loss_scale(gstep, floats)
                                if health_cfg is not None:
                                    note_health(gstep, floats)
                            else:
                                record_step(gstep, lambda: {
                                    kk: float(v) for kk, v in dev_metrics.items()})
                            if checkpoint_manager is not None and \
                                    checkpoint_every and \
                                    gstep % checkpoint_every == 0:
                                do_checkpoint(gstep)
                            if should_stop is not None:
                                # graceful drain: every step IS a chunk
                                # boundary at k=1 — the final checkpoint
                                # (data state included) runs at loop exit
                                reason = should_stop(steps)
                                if reason:
                                    preempted = reason
                                    stop = True
                                    break
                            at_cap = max_steps is not None and steps >= max_steps
                            if eval_and_maybe_stop(steps - 1, at_cap):
                                break
                            if at_cap:
                                stop = True
                                break
                    else:
                        # chunk-level in-flight window — the chunk rendering of
                        # the k==1 path's max_in_flight throttle: without
                        # chunk-boundary STATE consumers (periodic checkpoints,
                        # target eval — which auto mode downshifts for anyway)
                        # up to max_in_flight dispatched chunks stay
                        # unmaterialized, so a slow host↔device link (tunnel
                        # RTT) is paid once per window, not per chunk, and the
                        # device always has queued work.  With state consumers,
                        # window 0: every chunk flushes eagerly at its boundary
                        # so checkpoint/eval see exactly the boundary state.
                        # should_stop (the lease drain) is a chunk-boundary
                        # STATE consumer too: its decision must see flushed
                        # boundary state, so it forces the eager window
                        window = (self.max_in_flight
                                  if checkpoint_manager is None
                                  and target_accuracy is None
                                  and should_stop is None else 0)
                        in_flight_chunks: list = []  # (n_steps, t_disp, stacked)
                        t_mark = 0.0  # end of the previous flush (timing ref)

                        def flush_chunk():
                            """Materialize the oldest dispatched chunk — ONE
                            host sync for its (k,)-stacked per-step trajectory —
                            and run its per-step bookkeeping."""
                            nonlocal steps, examples, metrics, last_metrics, \
                                t_mark
                            n_chunk, t_disp, stacked = in_flight_chunks.pop(0)
                            with tracer.span("materialize", steps=n_chunk):
                                floats = {kk: np.asarray(jax.device_get(v))
                                          for kk, v in stacked.items()}
                            # chunk boundary: prefetch queue-depth/starvation
                            # gauges ride the same host sync
                            tracer.gauge("prefetch_depth", pf.queue_depth,
                                         starvation=pf.starvation)
                            now = time.perf_counter()
                            # per-step wall time as the chunk average over the
                            # non-overlapped span (the first chunk smears its
                            # XLA compile over its k entries)
                            dt = (now - max(t_disp, t_mark)) / n_chunk
                            t_mark = now
                            if timeline is not None:
                                # --timeline: chunk step-time + prefetch
                                # depth series at the SAME boundary the
                                # gauges above use — no extra syncs
                                tl_vals = {"chunk_step_time_s": dt,
                                           "prefetch_depth": pf.queue_depth}
                                if rf_flops_step is not None and dt > 0:
                                    # --roofline: the per-chunk achieved
                                    # model-flops rate on the same series
                                    tl_vals["achieved_flops_per_sec"] = \
                                        rf_flops_step / dt
                                timeline.sample_many(tl_vals,
                                                     group="trainer")
                            timer.times.extend([dt] * n_chunk)
                            if straggler_detector is not None:
                                # per-chunk average step time vs the
                                # running median (elastic/stragglers.py);
                                # labeled with the chunk's last step
                                straggler_detector.observe(
                                    start_step + steps + n_chunk, dt)
                            if watchdog is not None:
                                # flush beat: real device progress confirmed
                                # (the stall budget is k × per-step timeout —
                                # Watchdog.rescale above)
                                watchdog.beat()
                            for i in range(n_chunk):
                                steps += 1
                                gstep = start_step + steps
                                examples += bs  # global examples per step
                                m = {kk: float(v[i]) for kk, v in floats.items()}
                                metrics = m
                                record_step(gstep, lambda m=m: m)
                                if ls_active:
                                    note_loss_scale(gstep, m)
                                if health_cfg is not None:
                                    note_health(gstep, m)

                        dispatched = steps
                        next_chunk = pf.take(k if max_steps is None
                                             else min(k, max_steps - dispatched))
                        while not stop and next_chunk:
                            chunk = next_chunk
                            t_disp = time.perf_counter()
                            span_name = "chunk_dispatch" if compiled \
                                else "compile"
                            with tracer.span(span_name, steps=len(chunk)):
                                self.state, stacked = eng.many_step(
                                    self.state, [c[0] for c in chunk],
                                    [c[1] for c in chunk])
                            if not compiled:
                                # the first chunk smears its XLA compile over
                                # its k per-step time entries — tell the timer
                                # where steady state starts
                                timer.compile_steps = len(chunk)
                                compiled = True
                            if watchdog is not None:
                                # beat at dispatch too, not only at flush: the
                                # first dispatch's synchronous trace+compile is
                                # behind us here, so this arms the clock BEFORE
                                # the first flush — a device that hangs inside
                                # the first window would otherwise never arm an
                                # arm_on_first_beat watchdog (dispatches are
                                # bounded by the in-flight window, so a hung
                                # device still stops the beats within it)
                                watchdog.beat()
                            chunk_sizes.add(len(chunk))
                            dispatched += len(chunk)
                            in_flight_chunks.append((len(chunk), t_disp, stacked))
                            # assemble chunk N+1 while the device runs chunk N
                            # (dispatch above is async): host batch prep
                            # overlaps device compute
                            nxt = k if max_steps is None else min(
                                k, max_steps - dispatched)
                            next_chunk = pf.take(nxt) if nxt > 0 else []
                            while len(in_flight_chunks) > window:
                                chunk_start = steps
                                flush_chunk()
                                if window:
                                    continue
                                # eager boundary: state consumers run with
                                # self.state == the just-flushed boundary state
                                if checkpoint_manager is not None and \
                                        checkpoint_every and \
                                        (start_step + steps) // checkpoint_every \
                                        > (start_step + chunk_start) // checkpoint_every:
                                    # first chunk boundary at/after the due step
                                    do_checkpoint(start_step + steps)
                                if should_stop is not None:
                                    # graceful drain at the chunk boundary:
                                    # the in-flight chunk finished (it was
                                    # just flushed); remaining dispatched
                                    # chunks drain below and the final
                                    # checkpoint runs at loop exit
                                    reason = should_stop(steps)
                                    if reason:
                                        preempted = reason
                                        stop = True
                                        break
                                at_cap = (max_steps is not None
                                          and steps >= max_steps)
                                # evaluated at chunk boundaries (auto mode runs
                                # k=1 under target_accuracy, so boundary == step)
                                if eval_and_maybe_stop(chunk_start, at_cap):
                                    break
                        # epoch end (or early stop): drain the window in order
                        while in_flight_chunks:
                            flush_chunk()
                        if not stop and should_stop is not None:
                            # window > 0 fallback (no other state consumer):
                            # the drained epoch end is still a boundary
                            reason = should_stop(steps)
                            if reason:
                                preempted = reason
                                stop = True
                        if max_steps is not None and steps >= max_steps:
                            stop = True
                finally:
                    # the prefetcher read ahead of the consumer: release the
                    # source (a native batcher's busy claim) deterministically,
                    # folding its gauges into the run totals first
                    pf_starvation += pf.starvation
                    pf_fill_wait += pf.fill_wait_s
                    pf.close()
            if (target_accuracy is not None and eval_ds is not None
                    and not reached and steps and prev_eval_step != steps):
                # loop ended by exhausting epochs (not the cap): still finish
                # with a real eval so eval_accuracy is never stale/uncomputed
                eval_gap = steps - prev_eval_step
                eval_acc = self.evaluate(eval_ds, batch_size=eval_batch)["accuracy"]
                reached = eval_acc >= target_accuracy
                if not reached:
                    eval_gap = None
            jax.block_until_ready(self.state)
            if nan_guard and steps:
                final = {k: float(v) for k, v in metrics.items()}
                check_finite(final, start_step + steps)
                last_metrics = last_metrics or final
            elapsed = time.perf_counter() - t0
            if checkpoint_manager is not None:
                # final=True drains the async writer too: fit never returns
                # (or hands state to a resume) with a write still in flight
                do_checkpoint(start_step + steps, final=True)
        except BaseException:
            if checkpoint_manager is not None:
                try:
                    checkpoint_manager.wait(reraise=False)
                except Exception:
                    pass
            for _sink in (metrics_logger, tracer):
                _flush = getattr(_sink, "flush", None)
                if _flush is not None:
                    try:
                        _flush()
                    except Exception:
                        pass
            raise
        # --roofline: achieved model flops/s over the whole fit window
        # (compile included — the honest end-to-end number; the per-chunk
        # timeline gauge shows steady state) and its MFU against the
        # fleet peak.  None device kind / None cost model → None MFU.
        rf_achieved = (rf_flops_step * steps / elapsed
                       if rf_flops_step and steps and elapsed > 0 else None)
        result = {
            "elapsed": elapsed, "steps": steps, "epochs": epochs,
            # resolved drain shape (tests/tools read these back: auto mode
            # downshifts steps_per_call to 1 under target_accuracy)
            "steps_per_call": k, "prefetch_depth": prefetch,
            # chunk lengths actually dispatched (tail chunks, max_steps
            # truncation and the auto resolution all show up here)
            "chunk_sizes": sorted(chunk_sizes),
            # input-path gauges (run-report fodder): hand-offs with zero
            # read-ahead left, and seconds blocked on host batch production
            "prefetch_starvation": pf_starvation,
            "prefetch_fill_wait_s": pf_fill_wait,
            **({"grad_allreduce_bytes": grad_bytes,
                "grad_allreduce_bytes_raw": grad_bytes_raw,
                "grad_compression": grad_codec,
                "grad_bucket_mb": grad_bucket_mb} if grad_bytes else {}),
            # mixed-precision policy + the per-device storage footprint it
            # moves (parallel/precision.py; f32 reports the same keys so
            # trajectories stay comparable across policies)
            "precision": precision_name,
            "param_bytes_per_device": param_bytes_dev,
            "opt_state_bytes_per_device": opt_bytes_dev,
            # dynamic loss scaling (fp16-f32master): skip accounting — the
            # scaler's grow/backoff story, mirrored from the per-step
            # loss_scale/ls_skipped metrics riding the scan
            **({"loss_scale": {
                "final_scale": ls_last_scale,
                "skipped_steps": ls_n_skipped,
                "skipped_step_list": ls_skipped_steps,
            }} if ls_active else {}),
            # checkpoint cost accounting (MLPerf-style: blocked time is
            # charged against throughput, overlapped time is not):
            # checkpoint_wait_s = training-thread seconds inside save/
            # drain calls; checkpoint_overlapped_s = background-writer
            # seconds that ran concurrently with training (0.0 sync)
            **({"checkpoint_wait_s": ckpt_wait,
                "checkpoint_overlapped_s": (
                    getattr(checkpoint_manager, "overlapped_s", 0.0)
                    - ckpt_overlap0),
                "checkpoint_async": ckpt_async}
               if checkpoint_manager is not None else {}),
            **({"steps_per_call_clamp": spc_clamp} if spc_clamp else {}),
            # graceful-drain outcome (elastic/lease.py): the should_stop
            # reason when a lease ended the fit, None on a normal finish
            "preempted": preempted,
            # exactly-once resume accounting (only when this fit WAS an
            # elastic resume — data_state given): steps whose data
            # position could not be restored (0 = exact resume)
            **({"resume_replay_steps": replay_steps}
               if data_state is not None else {}),
            # step-time outlier summary (elastic/stragglers.py)
            **({"stragglers": straggler_detector.report()}
               if straggler_detector is not None else {}),
            # the batch-stream position of the LAST checkpoint written —
            # what its elastic sidecar carries
            **({"data_state": last_data_state}
               if last_data_state is not None else {}),
            **({"watchdog_beats": watchdog.beats,
                "watchdog_stalls": watchdog.stall_episodes}
               if watchdog is not None else {}),
            # numeric-health summary (engine health layer on): run maxima
            # of the per-step stats plus the anomaly record — the section
            # the run report / bench carry forward
            **({"health": {
                "on_anomaly": on_anomaly,
                "anomalies": n_anomalies,
                "anomaly_steps": anomaly_steps,
                "first_anomaly_step": first_anomaly,
                "max_grad_norm": h_max.get("grad_norm"),
                "max_update_ratio": h_max.get("update_ratio"),
                "max_loss_spike": h_max.get("loss_spike"),
            }} if health_cfg is not None else {}),
            "start_step": start_step, "examples": examples,
            "examples_per_sec": examples / elapsed if elapsed > 0 else 0.0,
            **({"reached_target": reached, "eval_accuracy": eval_acc,
                "eval_resolution": eval_gap}
               if target_accuracy is not None else {}),
            # per-step wall times.  steps_per_call == 1: first_step_s
            # isolates XLA compile, steady percentiles measure dispatch
            # pace (device-throughput-bound once the max_in_flight window
            # fills).  Chunked drain: entries are per-chunk AVERAGES, so
            # the first chunk smears its compile over its k entries —
            # compare step_time only between runs of equal steps_per_call
            "step_time": timer.summary(),
            # --roofline (flag-on keys only — flag-off parity is pinned):
            # analytic model flops per step, the achieved rate, and MFU
            # normalized over n_devices × the peak-table peak (None on an
            # unknown device kind or a non-GPT model — never invented)
            **({"train_model_flops_per_step": rf_flops_step,
                "train_achieved_flops_per_sec": rf_achieved,
                "train_mfu": roofline.mfu(rf_achieved),
                "roofline_peak_table_revision": roofline.revision}
               if roofline is not None else {}),
            **{f"final_{k}": v for k, v in last_metrics.items()},
        }
        self.history.append(result)
        return result

    def evaluate(self, test_ds, batch_size: int = 100) -> dict:
        """Full-test-set eval (reference parity: server.py:179-180)."""
        return self.engine.evaluate(self.state, test_ds, batch_size)
