"""Tensor parallelism via GSPMD auto-sharding.

No reference counterpart (SURVEY.md §2.2: the reference's model is always
replicated whole — reference client.py:72, server.py:150); this is TPU-native
new capability: layers whose weight matrices exceed one device's HBM shard
across a ``model`` mesh axis.

Unlike the shard_map engines (explicit collectives, L1 layer), this engine
uses the compiler-driven style — the "How to Scale Your Model" recipe: params
carry `PartitionSpec` annotations (via `flax.linen.with_partitioning`), the
batch is sharded over ``data``, everything runs under one `jax.jit`, and XLA
GSPMD inserts the all-gathers/reduce-scatters itself.  Megatron layout for
the MLP: first Dense column-parallel (hidden dim sharded), second Dense
row-parallel (contraction dim sharded) — the activation between them stays
sharded, and XLA emits exactly one psum on the way out.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

import flax.linen as nn

from distributed_tensorflow_tpu.engines.base import (
    Engine, TrainState, gspmd_value_and_grad, make_loss_fn)
from distributed_tensorflow_tpu.parallel import mesh as meshlib
from distributed_tensorflow_tpu.parallel import compression
from distributed_tensorflow_tpu.parallel import precision as precisionlib


class TPMLP(nn.Module):
    """MLP with Megatron-style tensor-parallel annotations.

    Same architecture as the reference default model_fn (reference
    initializer.py:14-19: Flatten→Dense(512)→Dropout→Dense(10)), but the
    hidden dimension is sharded over the 'model' mesh axis.
    """

    num_classes: int = 10
    hidden: int = 512
    dropout_rate: float = 0.2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = x.reshape((x.shape[0], -1))
        # column-parallel: kernel (in, hidden) sharded on hidden
        x = nn.Dense(
            self.hidden, dtype=self.dtype,
            kernel_init=nn.with_partitioning(
                nn.initializers.lecun_normal(), (None, meshlib.MODEL_AXIS)),
            bias_init=nn.with_partitioning(
                nn.initializers.zeros_init(), (meshlib.MODEL_AXIS,)),
        )(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        # row-parallel: kernel (hidden, classes) sharded on hidden (the
        # contraction dim) — XLA inserts the psum after the matmul
        x = nn.Dense(
            self.num_classes, dtype=self.dtype,
            kernel_init=nn.with_partitioning(
                nn.initializers.lecun_normal(), (meshlib.MODEL_AXIS, None)),
        )(x)
        return x.astype(jnp.float32)


class TensorParallelEngine(Engine):
    """Data×model parallel sync training under one jit (GSPMD).

    ``mesh`` must have axes ('data', 'model').  The model's params may carry
    `with_partitioning` annotations; unannotated params replicate.

    ``grad_accum`` K > 1 accumulates K microbatch gradients per optimizer
    step under the same GSPMD jit (base.gspmd_grad_accum) — identical math
    to K=1 on the same global batch, ~K× less activation memory.

    ``precision`` (parallel/precision.py): Megatron-annotated params (and
    a master policy's f32 copy — annotations survive the tree-mapped
    cast) store/compute low-precision; fp16-f32master's loss scale rides
    the shared ``gspmd_value_and_grad`` hook.
    """

    supports_loss_scaling = True

    def __init__(self, model, optimizer=None, mesh=None, learning_rate=1e-3,
                 grad_accum: int = 1, grad_compression: str = "none",
                 grad_bucket_mb: float = 0.0, precision: str = "f32"):
        if mesh is None or set(mesh.axis_names) != {meshlib.DATA_AXIS,
                                                    meshlib.MODEL_AXIS}:
            raise ValueError("TensorParallelEngine requires a ('data','model') mesh")
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        super().__init__(model, optimizer, mesh, learning_rate,
                         grad_compression=grad_compression,
                         grad_bucket_mb=grad_bucket_mb,
                         precision=precision)
        self.grad_accum = grad_accum

    def init_state(self, rng, sample_x) -> TrainState:
        return self._init_partitioned_state(rng, sample_x)

    def _build_step(self):
        loss_fn = make_loss_fn(self.model.apply)
        tx, K = self.tx, self.grad_accum
        codec = self.grad_codec

        scaling = self.precision.loss_scaling

        def train_step(state: TrainState, x, y):
            rng = jax.random.fold_in(state.rng, state.step)
            ls = (precisionlib.loss_scale_from(state.opt_state)
                  if scaling else None)
            grads, loss, acc = gspmd_value_and_grad(
                loss_fn, state.params, x, y, rng, K, mesh=self.mesh,
                loss_scale=ls)
            if codec.name != "none":
                # GSPMD inserts the data-axis gradient all-reduce itself,
                # so the codec applies as a quantize→dequantize roundtrip
                # (compressed-exchange numerics; parallel/compression.py)
                grads = codec.roundtrip(
                    grads, rng=compression.codec_rng(rng))
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(step=state.step + 1, params=params,
                                 opt_state=opt_state), \
                {"loss": loss, "accuracy": acc}

        # jit semantics are GLOBAL (unlike per-device shard_map): the loss is
        # the global batch mean as written; GSPMD lowers the collectives
        return jax.jit(train_step, donate_argnums=0)

    def _build_eval(self):
        apply_fn = self.model.apply
        return self._build_eval_gspmd(
            lambda params, x: apply_fn({"params": params}, x, train=False))
