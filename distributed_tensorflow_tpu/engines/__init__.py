"""L2 step engines — TPU-native renderings of the reference's four training
modes (SURVEY.md §7.2(3)):

  sync       — sync data parallelism: per-device grads, `pmean`, one global
               optimizer step.  Replaces the sync parameter server
               (reference server.py:90-96 + client.py:78-95); the "server"
               disappears — optimizer state is replicated on every device.
  async      — local-update data parallelism: per-device optimizer steps every
               batch, parameter averaging every K steps.  The honest SPMD
               rendering of the reference's Hogwild-at-the-optimizer async PS
               (reference server.py:98-102; SURVEY.md §2.4(2)).
  allreduce  — identical math to sync, exposed through a Keras-fit-like
               Trainer (replaces MultiWorkerMirroredStrategy + model.fit,
               reference dist_keras.py:22-58).
  gossip     — ring/graph neighbor averaging via `ppermute`, implementing for
               real the reference's NotImplementedError 'graph'/'custom'
               strategies (reference initializer.py:175-181).
  fsdp       — ZeRO-style fully-sharded data parallelism: params + optimizer
               state sharded over 'data' (the reference's single-home
               optimizer, reference server.py:52-55, re-imagined TPU-first).
"""

from distributed_tensorflow_tpu.engines.base import Engine, TrainState  # noqa: F401
from distributed_tensorflow_tpu.engines.sync import SyncEngine  # noqa: F401
from distributed_tensorflow_tpu.engines.async_local import AsyncLocalEngine  # noqa: F401
from distributed_tensorflow_tpu.engines.gossip import GossipEngine  # noqa: F401
from distributed_tensorflow_tpu.engines.allreduce import Trainer  # noqa: F401
from distributed_tensorflow_tpu.engines.seq_parallel import SeqParallelEngine  # noqa: F401
from distributed_tensorflow_tpu.engines.tensor_parallel import (  # noqa: F401
    TensorParallelEngine, TPMLP)
from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine  # noqa: F401
from distributed_tensorflow_tpu.engines.expert_parallel import (  # noqa: F401
    ExpertParallelEngine)
from distributed_tensorflow_tpu.engines.composite import CompositeEngine  # noqa: F401
from distributed_tensorflow_tpu.engines.fsdp import FSDPEngine  # noqa: F401

ENGINES = {
    "sync": SyncEngine,
    "async": AsyncLocalEngine,
    "allreduce": SyncEngine,
    "gossip": GossipEngine,
    "fsdp": FSDPEngine,
}


def create_engine(name: str, *args, **kw):
    if name not in ENGINES:
        raise KeyError(f"unknown engine '{name}'; known: {sorted(ENGINES)}")
    return ENGINES[name](*args, **kw)
