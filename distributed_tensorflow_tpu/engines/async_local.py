"""Async-flavored data parallelism: local optimizer steps + periodic averaging.

The reference's async PS is Hogwild-at-the-optimizer — lock-serialized
`apply_gradients` with no round structure; workers train on whatever weights
exist at pull time (reference server.py:98-102, SURVEY.md §2.4(2)).  True
asynchrony doesn't map onto a bulk-synchronous SPMD mesh, so the honest
TPU-native rendering (SURVEY.md §7.4) is *local SGD*: every device keeps its
own parameters and optimizer state, applies its own gradient every batch
(exactly as stale as one async round), and parameters are averaged across the
mesh every ``sync_every`` steps via `pmean`.

Layout: the whole TrainState is *stacked* — every leaf gains a leading
device axis sharded over ``data``, so device i owns row i.  Inside shard_map
each device sees a size-1 leading axis which we strip/restore.

Memory scaling (design note): local SGD *inherently* keeps one divergent
parameter+optimizer copy per device — aggregate state is O(n_devices) ×
model size by definition of the algorithm, not an implementation artifact.
Per-device HBM holds exactly ONE copy (the stack is sharded row-wise over
``data``; init materializes each row directly on its own device — verified
by tests/test_engines.py::test_async_state_sharded_one_copy_per_device).
For models near single-device HBM capacity, local SGD is the wrong tool:
use the sync/allreduce engines (replicated params, sharded batch) or the
GSPMD engines (sharded params).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.engines.base import Engine, TrainState, make_loss_fn
from distributed_tensorflow_tpu.parallel import collectives as coll
from distributed_tensorflow_tpu.parallel import compression
from distributed_tensorflow_tpu.parallel import mesh as meshlib


class AsyncLocalEngine(Engine):
    def __init__(self, *args, sync_every: int = 10, **kw):
        super().__init__(*args, **kw)
        self.sync_every = sync_every

    # state is per-device: every leaf stacked along a leading device axis
    def init_state(self, rng, sample_x) -> TrainState:
        x = jnp.asarray(sample_x[:1])
        n = self.n_devices

        def init_fn(rng):
            params = self.model.init(rng, x, train=False)["params"]
            # precision storage cast before tx.init (no-op for f32): the
            # per-device stack — and a master policy's f32 copy — carry
            # the policy dtypes from materialization
            params = self.precision.cast_params(params)
            opt_state = self.tx.init(params)
            state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                               opt_state=opt_state, rng=rng)
            return jax.tree.map(
                lambda a: jnp.broadcast_to(a, (n, *jnp.shape(a))), state)

        # jit with out_shardings: each stacked row materializes directly on
        # its own device — a plain broadcast_to would build the full n× stack
        # on one device before resharding
        return jax.jit(
            init_fn,
            out_shardings=meshlib.per_device_sharding(self.mesh))(rng)

    def grad_collective_bytes_raw(self, state: TrainState) -> int:
        """One parameter-averaging round moves ONE model copy per device,
        not the n_devices-stacked state the base accounting would count
        (every leaf here carries a leading device axis) — and it runs
        every ``sync_every`` steps, not per step; the telemetry event
        records the per-round payload."""
        return (super().grad_collective_bytes_raw(state)
                // max(self.n_devices, 1))

    def grad_collective_bytes(self, state: TrainState) -> int:
        """Wire bytes of one parameter-averaging round under the codec,
        computed on a DE-STACKED abstract copy of the params — the codec
        accounting must see the exchanged one-copy-per-device shapes
        (dividing the stacked total by n would shrink int8's per-leaf
        4-byte scale overhead to 4/n)."""
        params = getattr(state, "params", None)
        if params is None:
            return 0
        try:
            one_copy = jax.eval_shape(
                lambda p: jax.tree.map(lambda a: a[0], p), params)
            return self.grad_codec.wire_bytes(jax.tree.leaves(one_copy))
        except Exception:  # exotic leaf without shape/dtype
            return 0

    def _build_step(self):
        loss_fn = make_loss_fn(self.model.apply)
        tx, axis, sync_every = self.tx, self.axis, self.sync_every
        codec = self.grad_codec

        def device_step(state_1: TrainState, x, y):
            s = jax.tree.map(lambda a: a[0], state_1)  # strip size-1 device axis
            rng = self._per_device_rng(s.rng, s.step)
            # per-device rounding key for the codec: each device quantizes
            # its OWN parameter copy before the exchange
            codec_key = compression.codec_rng(rng)
            (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                s.params, x, y, rng)
            # local apply — the analogue of one lock-serialized async update
            updates, opt_state = tx.update(grads, s.opt_state, s.params)
            params = optax.apply_updates(s.params, updates)
            step = s.step + 1
            do_sync = (step % sync_every) == 0
            # periodic parameter averaging (the "weight exchange") through
            # the compression codec — local SGD's sync payload is the
            # PARAMETER copy, so that is what gets the reduced-precision
            # wire treatment ('none' is the plain pmean); predicate is
            # device-invariant so all devices enter the collective together
            params = jax.lax.cond(
                do_sync,
                lambda p: codec.all_reduce_mean(p, axis, rng=codec_key),
                lambda p: p,
                params,
            )
            metrics = coll.all_reduce_mean({"loss": loss, "accuracy": acc}, axis)
            new_s = s.replace(step=step, params=params, opt_state=opt_state)
            return jax.tree.map(lambda a: a[None], new_s), metrics

        smapped = jax.shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis), P(self.axis)),
            out_specs=(P(self.axis), P()),
            check_vma=False,  # step is replicated in value; vma can't see that
        )
        return jax.jit(smapped, donate_argnums=0)

    def eval_params(self, state: TrainState):
        """Average the per-device parameter copies for evaluation (the final
        'consensus' model — comparable to the async PS's single server model)."""

        @jax.jit
        def mean_params(p):
            return jax.tree.map(lambda a: a.mean(axis=0), p)

        return meshlib.state_to_global(mean_params(state.params),
                                       meshlib.replicated(self.mesh))
