"""Pipeline parallelism: GPipe-style microbatched collective pipelining.

No reference counterpart (SURVEY.md §2.2: the reference's models are
single-stage, reference initializer.py:14-19); this is TPU-native new
capability completing the parallelism matrix.

Design — the "collective pipeline" from the scaling playbook, written as ONE
SPMD program under `jax.shard_map` over a ``('data', 'pipe')`` mesh:

* Stage parameters are *stacked* with a leading stage dimension and sharded
  ``P('pipe')`` — each device on the pipe axis holds exactly one stage.
* The step splits its data-shard batch into M microbatches and runs a
  ``lax.scan`` of ``M + S - 1`` ticks.  Every tick each device applies its
  stage to the activation in its buffer, then the buffer rotates one hop
  along the pipe axis via ``ppermute`` — activations ride ICI, never the
  host.  Stage 0 injects microbatch ``i``; the last stage emits the loss for
  microbatch ``i - (S - 1)``.  The bubble is the standard ``(S-1)/(M+S-1)``.
* Backward is just ``jax.grad`` through the scan: the AD transpose of
  ``ppermute`` is the reverse rotation, so the backward pipeline runs in the
  opposite direction automatically — no hand-written schedule.
* Gradients: stage params are pipe-varying (each stage's grad stays local);
  embed/head params enter replicated, so the AD transpose psums their grads
  over both mesh axes — the same implicit-allreduce mechanism the sync
  engine documents (engines/sync.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import flax.linen as nn

from distributed_tensorflow_tpu.engines.base import (
    Engine, TrainState, cross_entropy)
from distributed_tensorflow_tpu.parallel import mesh as meshlib


class PipelineEmbed(nn.Module):
    """Input stage: flatten → project to the pipeline's hidden width."""

    hidden: int = 128
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        return nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))


class PipelineBlock(nn.Module):
    """One pipeline stage: pre-norm residual MLP block (hidden-preserving,
    so every stage has identical parameter structure and can be stacked)."""

    hidden: int = 128
    expansion: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h):
        y = nn.LayerNorm(dtype=self.dtype)(h)
        y = nn.Dense(self.hidden * self.expansion, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Dense(self.hidden, dtype=self.dtype)(y)
        return h + y


class PipelineHead(nn.Module):
    """Output stage: norm → logits (always f32 for a stable softmax)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h):
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(
            nn.LayerNorm(dtype=self.dtype)(h))
        return logits.astype(jnp.float32)


def _pipe_spec_tree(tree):
    """PartitionSpec tree: leaves under a 'blocks' dict key are stage-stacked
    → sharded P('pipe') on the leading (stage) dim; everything else
    replicated.  Works for params AND optimizer state (optax mu/nu mirror the
    param tree, so their paths also contain the 'blocks' key)."""

    def spec(path, leaf):
        for k in path:
            if isinstance(k, jax.tree_util.DictKey) and k.key == "blocks":
                return P(meshlib.PIPE_AXIS)
        return P()

    return jax.tree_util.tree_map_with_path(spec, tree)


class PipelineEngine(Engine):
    """data × pipe parallel training of an embed → S blocks → head model.

    ``mesh`` must have axes ('data', 'pipe'); the number of stages S is the
    pipe-axis size.  ``microbatches`` (M) must divide the per-data-shard
    batch.  Throughput approaches M/(M+S-1) of bubble-free as M grows.

    ``stages`` plugs in custom (embed, block, head) modules — e.g.
    ``models.bert.bert_pipeline_stages`` to pipeline a transformer encoder.
    Contract: ``block(carry) -> carry`` where ``carry`` is whatever pytree
    ``embed(x)`` returns (it rides the pipe-axis ppermute between stages, so
    keep it activation-sized), every stage has identical parameter structure
    (they are stacked and sharded P('pipe')), and all three modules are
    deterministic — the schedule re-applies embed/head every tick, so rng-
    consuming ops (dropout) would draw inconsistent masks across ticks.
    """

    def __init__(
        self,
        num_classes: int = 10,
        hidden: int = 128,
        microbatches: int = 4,
        optimizer=None,
        mesh=None,
        learning_rate: float = 1e-3,
        expansion: int = 2,
        dtype: jnp.dtype = jnp.float32,
        stages: tuple[nn.Module, nn.Module, nn.Module] | None = None,
    ):
        if mesh is None or set(mesh.axis_names) != {meshlib.DATA_AXIS,
                                                    meshlib.PIPE_AXIS}:
            raise ValueError("PipelineEngine requires a ('data','pipe') mesh")
        if stages is not None:
            self.embed, self.block, self.head = stages
        else:
            self.embed = PipelineEmbed(hidden=hidden, dtype=dtype)
            self.block = PipelineBlock(hidden=hidden, expansion=expansion,
                                       dtype=dtype)
            self.head = PipelineHead(num_classes=num_classes, dtype=dtype)
        self.n_stages = mesh.shape[meshlib.PIPE_AXIS]
        self.microbatches = microbatches
        super().__init__(model=None, optimizer=optimizer, mesh=mesh,
                         learning_rate=learning_rate)

    # ---------------------------------------------------------------- init
    def init_state(self, rng, sample_x) -> TrainState:
        x = jnp.asarray(sample_x[:1])
        e_rng, b_rng, h_rng = jax.random.split(rng, 3)
        embed_p = self.embed.init(e_rng, x)["params"]
        h = self.embed.apply({"params": embed_p}, x)
        blocks_p = jax.vmap(
            lambda k: self.block.init(k, h)["params"]
        )(jax.random.split(b_rng, self.n_stages))
        head_p = self.head.init(h_rng, h)["params"]
        params = {"embed": embed_p, "blocks": blocks_p, "head": head_p}
        opt_state = self.tx.init(params)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           opt_state=opt_state, rng=rng)
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), _pipe_spec_tree(state),
            is_leaf=lambda x: isinstance(x, P))
        return meshlib.state_to_global(state, shardings)

    # ------------------------------------------------------------- forward
    def _sequential_logits(self, params, x):
        """Un-pipelined forward (scan over the stacked stages) — used for
        eval and as the parity oracle in tests."""
        h = self.embed.apply({"params": params["embed"]}, x)

        def body(h, bp):
            return self.block.apply({"params": bp}, h), None

        h, _ = lax.scan(body, h, params["blocks"])
        return self.head.apply({"params": params["head"]}, h)

    # ---------------------------------------------------------------- step
    def _build_step(self):
        tx = self.tx
        embed, block, head = self.embed, self.block, self.head
        M = self.microbatches
        data_axis, pipe_axis = meshlib.DATA_AXIS, meshlib.PIPE_AXIS

        def device_step(state: TrainState, x, y):
            S = lax.axis_size(pipe_axis)
            n_data = lax.axis_size(data_axis)
            stage = lax.axis_index(pipe_axis)
            mb = x.shape[0] // M
            micro_x = x.reshape((M, mb) + x.shape[1:])
            micro_y = y.reshape((M, mb))
            perm = [(i, (i + 1) % S) for i in range(S)]

            def loss_fn(params):
                blocks_local = jax.tree.map(lambda a: a[0], params["blocks"])

                def tick(buf, i):
                    # stage 0 injects microbatch i (clamped past the drain)
                    xi = lax.dynamic_index_in_dim(
                        micro_x, jnp.clip(i, 0, M - 1), keepdims=False)
                    h_src = embed.apply({"params": params["embed"]}, xi)
                    h_src = jax.tree.map(
                        lambda a: lax.pcast(a, pipe_axis, to="varying"), h_src)
                    h_in = jax.tree.map(
                        lambda s, b: jnp.where(stage == 0, s, b), h_src, buf)
                    h_out = block.apply({"params": blocks_local}, h_in)
                    # last stage drains microbatch i-(S-1)
                    oi = i - (S - 1)
                    yi = lax.dynamic_index_in_dim(
                        micro_y, jnp.clip(oi, 0, M - 1), keepdims=False)
                    yi = lax.pcast(yi, pipe_axis, to="varying")
                    logits = head.apply({"params": params["head"]}, h_out)
                    w = ((oi >= 0) & (oi < M) & (stage == S - 1)).astype(
                        jnp.float32)
                    loss_i = cross_entropy(logits, yi).mean() * w
                    acc_i = (logits.argmax(-1) == yi).mean(
                        ).astype(jnp.float32) * w
                    buf_next = jax.tree.map(
                        lambda a: lax.ppermute(a, axis_name=pipe_axis,
                                               perm=perm), h_out)
                    return buf_next, (loss_i, acc_i, w)

                # buffer shape/dtype comes from the embed output itself, so
                # any activation pytree (arrays, (h, mask) tuples, ...) works
                h0 = jax.eval_shape(
                    lambda p, a: embed.apply({"params": p}, a),
                    params["embed"], micro_x[0])
                buf0 = jax.tree.map(
                    lambda a: lax.pcast(jnp.zeros(a.shape, a.dtype),
                                        (data_axis, pipe_axis), to="varying"),
                    h0)
                _, (losses, accs, ws) = lax.scan(
                    tick, buf0, jnp.arange(M + S - 1))
                # nonzero only on the last stage; scale so the implicit psum
                # over BOTH axes at the AD boundary yields the global batch
                # mean (same mechanism as engines/sync.py)
                local_sum = losses.sum()
                scaled = local_sum / (M * n_data)
                return scaled, (losses.sum(), accs.sum(), ws.sum())

            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
            (_, (loss_sum, acc_sum, w_sum)), grads = grad_fn(state.params)
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            both = (data_axis, pipe_axis)
            # w_sum depends only on the stage index → data-invariant; make it
            # data-varying so it can ride the same two-axis psum
            w_sum = lax.pcast(w_sum, data_axis, to="varying")
            tot_w = lax.psum(w_sum, both)
            metrics = {
                "loss": lax.psum(loss_sum, both) / tot_w,
                "accuracy": lax.psum(acc_sum, both) / tot_w,
            }
            new_state = state.replace(step=state.step + 1, params=params,
                                      opt_state=opt_state)
            return new_state, metrics

        # the in/out spec trees depend on the concrete state structure, so
        # the shard_map is built lazily on first call
        compiled = {}

        def step_fn(state, x, y):
            if "fn" not in compiled:
                spec = _pipe_spec_tree(state)
                smapped = jax.shard_map(
                    device_step, mesh=self.mesh,
                    in_specs=(spec, P(data_axis), P(data_axis)),
                    out_specs=(spec, P()),
                )
                compiled["fn"] = jax.jit(smapped, donate_argnums=0)
            return compiled["fn"](state, x, y)

        return step_fn

    # ---------------------------------------------------------------- eval
    def eval_params(self, state: TrainState):
        return state.params

    def _build_eval(self):
        def eval_step(params, x, y, mask):
            logits = self._sequential_logits(params, x)
            correct = ((logits.argmax(-1) == y) * mask).sum()
            loss_sum = (cross_entropy(logits, y) * mask).sum()
            return correct, loss_sum, mask.sum()

        # GSPMD jit: blocks stay sharded over 'pipe'; XLA moves stage params
        # to where the scan needs them
        return jax.jit(eval_step)
