"""Pipeline parallelism: GPipe-style microbatched collective pipelining.

No reference counterpart (SURVEY.md §2.2: the reference's models are
single-stage, reference initializer.py:14-19); this is TPU-native new
capability completing the parallelism matrix.

Design — the "collective pipeline" from the scaling playbook, written as ONE
SPMD program under `jax.shard_map` over a ``('data', 'pipe')`` mesh:

* Stage parameters are *stacked* with a leading stage dimension and sharded
  ``P('pipe')`` — each device on the pipe axis holds exactly one stage.
* The step splits its data-shard batch into M microbatches and runs a
  ``lax.scan`` of ``M + S - 1`` ticks.  Every tick each device applies its
  stage to the activation in its buffer, then the buffer rotates one hop
  along the pipe axis via ``ppermute`` — activations ride ICI, never the
  host.  Stage 0 injects microbatch ``i``; the last stage emits the loss for
  microbatch ``i - (S - 1)``.  The bubble is the standard ``(S-1)/(M+S-1)``.
* Backward is just ``jax.grad`` through the scan: the AD transpose of
  ``ppermute`` is the reverse rotation, so the backward pipeline runs in the
  opposite direction automatically — no hand-written schedule.
* Gradients: stage params are pipe-varying (each stage's grad stays local);
  embed/head params enter replicated, so the AD transpose psums their grads
  over both mesh axes — the same implicit-allreduce mechanism the sync
  engine documents (engines/sync.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import flax.linen as nn

from distributed_tensorflow_tpu.engines.base import (
    Engine, TrainState, cross_entropy)
from distributed_tensorflow_tpu.parallel import mesh as meshlib


class PipelineEmbed(nn.Module):
    """Input stage: flatten → project to the pipeline's hidden width."""

    hidden: int = 128
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        return nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))


class PipelineBlock(nn.Module):
    """One pipeline stage: pre-norm residual MLP block (hidden-preserving,
    so every stage has identical parameter structure and can be stacked)."""

    hidden: int = 128
    expansion: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h):
        y = nn.LayerNorm(dtype=self.dtype)(h)
        y = nn.Dense(self.hidden * self.expansion, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Dense(self.hidden, dtype=self.dtype)(y)
        return h + y


class PipelineHead(nn.Module):
    """Output stage: norm → logits (always f32 for a stable softmax)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h):
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(
            nn.LayerNorm(dtype=self.dtype)(h))
        return logits.astype(jnp.float32)


def _match_vma(a, b):
    """Cast ``a`` to ``b``'s dtype and widen its varying-axes set to match.

    Used where an embed output joins the rotating buffer: leaves touching
    the pre-cast varying params join for free; leaves derived from the
    device-invariant token input alone (e.g. a carried padding mask) are
    upcast here.  Differentiated paths never traverse those leaves, so this
    in-branch pcast transposes to nothing (no collective inside a cond)."""
    missing = tuple(set(jax.typeof(b).vma) - set(jax.typeof(a).vma))
    if missing:
        a = lax.pcast(a, missing, to="varying")
    return a.astype(b.dtype)


def _pipe_spec_tree(tree):
    """MANUAL-axes PartitionSpec tree (shard_map in/out_specs): leaves under
    a 'blocks' dict key are stage-stacked → sharded P('pipe') on the leading
    (stage) dim; everything else replicated over the manual axes.  Works for
    params AND optimizer state (optax mu/nu mirror the param tree, so their
    paths also contain the 'blocks' key).  Model-axis (TP) sharding is NOT
    expressed here — it lives on the arrays themselves and GSPMD handles it
    as an auto axis (see _full_spec_tree)."""

    def spec(path, leaf):
        for k in path:
            if isinstance(k, jax.tree_util.DictKey) and k.key == "blocks":
                return P(meshlib.PIPE_AXIS)
        return P()

    return jax.tree_util.tree_map_with_path(spec, tree)


def _full_spec_tree(tree, stage_specs: dict):
    """FULL PartitionSpec tree for array placement at init: combines the
    pipe stacking with each stage's Megatron annotations.  ``stage_specs``
    maps 'embed'/'blocks'/'head' to that stage's annotation-derived spec
    subtree ('blocks' entries already carry the leading 'pipe' dim).
    Optimizer state resolves through the same lookup because optax mu/nu
    mirror the param tree paths."""

    def lookup(sub, remainder):
        for k in remainder:
            if (isinstance(k, jax.tree_util.DictKey) and isinstance(sub, dict)
                    and k.key in sub):
                sub = sub[k.key]
            else:
                return None
        return sub if isinstance(sub, P) else None

    def spec(path, leaf):
        for i, k in enumerate(path):
            if isinstance(k, jax.tree_util.DictKey) and k.key in stage_specs:
                s = lookup(stage_specs[k.key], path[i + 1:])
                if s is not None:
                    return s
        return P()

    return jax.tree_util.tree_map_with_path(spec, tree)


class PipelineEngine(Engine):
    """data × pipe parallel training of an embed → S blocks → head model.

    ``mesh`` must have axes ('data', 'pipe'); the number of stages S is the
    pipe-axis size.  ``microbatches`` (M) must divide the per-data-shard
    batch.  Throughput approaches M/(M+S-1) of bubble-free as M grows.

    ``schedule`` picks the microbatch schedule:

    * ``'gpipe'`` (default): all-forward-then-all-backward via `jax.grad`
      through the tick scan.  AD stores one residual set per tick, so
      activation memory grows with M + S − 1.
    * ``'1f1b'``: the one-forward-one-backward schedule (PipeDream-flush):
      after an S-tick warmup each device alternates forward and backward
      microbatches, so at most S microbatches are ever in flight and the
      activation stash is a fixed S slots regardless of M.  Backward is
      hand-scheduled with per-stage `jax.vjp` (input-stash + recompute),
      cotangents ride a reverse `ppermute` ring; the math is identical to
      GPipe (same grads, different order — tests/test_pipeline.py holds
      both to the same sequential oracle).

    Optional extra mesh axes compose: 'model' (pp×tp, Megatron GSPMD auto
    axis), 'seq' (pp×sp, manual ring attention inside stages), 'expert'
    (pp×ep, MoE-FFN stage blocks with experts sharded over a GSPMD auto
    axis; GPipe only — the router aux/z losses join the objective through
    the tick scan, gated to real-microbatch ticks).

    ``stages`` plugs in custom (embed, block, head) modules — e.g.
    ``models.bert.bert_pipeline_stages`` to pipeline a transformer encoder.
    Contract: ``block(carry) -> carry`` where ``carry`` is whatever pytree
    ``embed(x)`` returns (it rides the pipe-axis ppermute between stages, so
    keep it activation-sized), every stage has identical parameter structure
    (they are stacked and sharded P('pipe')), and all three modules are
    deterministic — the schedule replays the tick program under AD, so rng-
    consuming ops (dropout) would need tick-stable keys the stage contract
    does not provide.  Embed runs only on stage 0 during the fill and head
    only on the last stage during the drain (`lax.cond`, so the other
    stages genuinely skip those FLOPs rather than mask them).
    """

    def __init__(
        self,
        num_classes: int = 10,
        hidden: int = 128,
        microbatches: int = 4,
        optimizer=None,
        mesh=None,
        learning_rate: float = 1e-3,
        expansion: int = 2,
        dtype: jnp.dtype = jnp.float32,
        stages: tuple[nn.Module, nn.Module, nn.Module] | None = None,
        schedule: str = "gpipe",
        remat: bool = False,
        aux_weight: float = 0.01,
        router_z_weight: float = 0.0,
        overflow_warn_threshold: float = 0.25,
        overflow_window: int = 50,
    ):
        if mesh is None or not {meshlib.DATA_AXIS,
                                meshlib.PIPE_AXIS} <= set(mesh.axis_names):
            raise ValueError("PipelineEngine requires a ('data','pipe') mesh")
        extra = set(mesh.axis_names) - {meshlib.DATA_AXIS, meshlib.PIPE_AXIS,
                                        meshlib.MODEL_AXIS, meshlib.SEQ_AXIS,
                                        meshlib.EXPERT_AXIS}
        if extra:
            raise ValueError(
                f"unsupported mesh axes {sorted(extra)}; PipelineEngine "
                f"composes data×pipe(×model)(×seq)(×expert)")
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown schedule '{schedule}'; "
                             f"choose 'gpipe' or '1f1b'")
        self.schedule = schedule
        # activation checkpointing for the gpipe tick body: AD through the
        # tick scan stores one residual set per tick (M+S−1 of them); with
        # remat it stores only each tick's block INPUT and recomputes the
        # block forward in backward — the per-tick stash drops from every
        # block intermediate (attention scores, FFN hidden) to one
        # activation, at one extra block-forward per tick.  This is the
        # memory-bounded long-context schedule pp×sp lacked (1F1B rejects a
        # 'seq' axis; it already stash-and-recomputes by construction, so
        # remat=True is a documented no-op there).
        self.remat = remat and schedule == "gpipe"
        # optional Megatron TP inside each stage: 'model' is a GSPMD auto
        # axis — the shard_map is manual over (data, pipe) only, and the
        # stage params' with_partitioning annotations drive the in-stage
        # model-axis collectives (pp×tp)
        self.tp_n = mesh.shape.get(meshlib.MODEL_AXIS, 1)
        # optional sequence/context parallelism inside each stage (pp×sp):
        # 'seq' is a MANUAL axis — the stage blocks must run ring/Ulysses
        # attention over it (e.g. models.gpt.gpt_pipeline_stages with
        # attention_impl='ring', seq_axis='seq'); activations stay
        # seq-sharded while they ride the pipe ppermute ring
        self.sp_n = mesh.shape.get(meshlib.SEQ_AXIS, 1)
        if self.sp_n > 1 and schedule == "1f1b":
            # 1F1B gates the block forward/backward behind lax.cond on a
            # pipe-varying predicate; a seq collective (the ring's ppermute)
            # inside a partially-taken conditional aborts XLA's thunk
            # executor (measured: CPU rendezvous abort) — the same rule the
            # gpipe tick documents for embed/head.  GPipe keeps the block
            # unconditional, so it is the schedule that composes with seq.
            raise ValueError(
                "schedule='1f1b' does not compose with a 'seq' mesh axis "
                "(ring collectives cannot live inside the schedule's "
                "conditionals); use schedule='gpipe' for pp×sp")
        if stages is not None:
            self.embed, self.block, self.head = stages
        else:
            self.embed = PipelineEmbed(hidden=hidden, dtype=dtype)
            self.block = PipelineBlock(hidden=hidden, expansion=expansion,
                                       dtype=dtype)
            self.head = PipelineHead(num_classes=num_classes, dtype=dtype)
        # pp×ep: MoE-FFN stage blocks (models/gpt.py GPTPipeBlock /
        # models/bert.py BertPipeBlock with moe_experts > 0) over an
        # 'expert' GSPMD auto axis — same partial-manual recipe as pp×tp's
        # 'model' axis, with the router aux losses joining the objective in
        # the gpipe tick (see _build_step_gpipe).
        from distributed_tensorflow_tpu.engines.expert_parallel import (
            _OverflowMonitor)

        self.moe = getattr(self.block, "moe_experts", 0) > 0
        self.ep_n = mesh.shape.get(meshlib.EXPERT_AXIS, 1)
        if self.moe and schedule == "1f1b":
            # 1F1B's backward is hand-scheduled per-stage jax.vjp of the
            # task cotangent alone — the router aux/z losses would need
            # their own per-stage cotangent seeds injected into each bwd
            # sub-tick, which the schedule does not wire.  GPipe
            # differentiates the whole tick scan, so aux terms flow for
            # free; it is the schedule that composes with MoE.
            raise ValueError(
                "schedule='1f1b' does not compose with MoE stage blocks "
                "(the hand-scheduled backward carries only the task-loss "
                "cotangent; router aux losses would silently drop out of "
                "the objective); use schedule='gpipe' for pp×ep")
        if self.ep_n > 1:
            if not self.moe:
                raise ValueError(
                    "mesh has an 'expert' axis but the stage block has no "
                    "MoE FFN (moe_experts == 0); experts would silently "
                    "replicate")
            if not getattr(self.block, "partition_experts", False):
                raise ValueError(
                    "an 'expert' mesh axis needs partition_experts=True on "
                    "the stage block — without the "
                    "with_partitioning('expert') annotations the expert "
                    "weights replicate and no expert parallelism happens")
            if getattr(self.block, "moe_experts", 0) % self.ep_n:
                raise ValueError(
                    f"moe_experts {self.block.moe_experts} not divisible "
                    f"by expert axis size {self.ep_n}")
        self.aux_weight = aux_weight
        self.router_z_weight = router_z_weight
        # None on dense pipelines so the harness summary only carries the
        # router-health fields when there are routers (harness.py reads the
        # attribute with a None guard)
        self.overflow_monitor = (_OverflowMonitor(overflow_warn_threshold,
                                                  overflow_window)
                                 if self.moe else None)
        self.n_stages = mesh.shape[meshlib.PIPE_AXIS]
        self.microbatches = microbatches
        self._decode_cache = {}  # generate: jitted decode per length pair
        super().__init__(model=None, optimizer=optimizer, mesh=mesh,
                         learning_rate=learning_rate)

    # ------------------------------------------------------------- batches
    def shard_batch(self, x, y, mask=None, process_local=False):
        if self.sp_n == 1:
            return super().shard_batch(x, y, mask, process_local)
        if x.ndim < 2 or x.shape[1] % self.sp_n:
            raise ValueError(
                f"pp×sp needs (batch, seq, ...) input with seq divisible by "
                f"the seq axis size {self.sp_n}, got shape {x.shape}")
        xs = self._place(x, NamedSharding(
            self.mesh, P(meshlib.DATA_AXIS, meshlib.SEQ_AXIS)), process_local)
        y_spec = (P(meshlib.DATA_AXIS, meshlib.SEQ_AXIS) if y.ndim >= 2
                  else P(meshlib.DATA_AXIS))
        ys = self._place(y, NamedSharding(self.mesh, y_spec), process_local)
        if mask is None:
            return xs, ys
        ms = self._place(mask, NamedSharding(self.mesh, P(meshlib.DATA_AXIS)),
                         process_local)
        return xs, ys, ms

    # ---------------------------------------------------------------- init
    def _oracle_stages(self):
        """Seq-disabled twins of (embed, block) with identical param
        structure: ring/Ulysses collectives and seq-offset positions cannot
        trace outside the manual shard_map, so init and the sequential
        eval/parity oracle run the dense single-device algorithm."""
        embed, block = self.embed, self.block
        if getattr(embed, "seq_axis", None) is not None:
            embed = embed.clone(seq_axis=None)
        if getattr(block, "seq_axis", None) is not None:
            block = block.clone(seq_axis=None)
        if getattr(block, "attention_impl", "dense") in (
                "ring", "ring_flash", "ulysses", "ulysses_flash"):
            block = block.clone(attention_impl="dense")
        return embed, block

    def init_state(self, rng, sample_x) -> TrainState:
        x = jnp.asarray(sample_x[:1])
        o_embed, o_block = self._oracle_stages()
        e_rng, b_rng, h_rng = jax.random.split(rng, 3)
        embed_v = o_embed.init(e_rng, x)
        embed_p = nn.unbox(embed_v)["params"]
        h = o_embed.apply({"params": embed_p}, x)
        blocks_p = jax.vmap(
            lambda k: nn.unbox(o_block.init(k, h))["params"]
        )(jax.random.split(b_rng, self.n_stages))
        head_v = self.head.init(h_rng, h)
        head_p = nn.unbox(head_v)["params"]
        params = {"embed": embed_p, "blocks": blocks_p, "head": head_p}
        opt_state = self.tx.init(params)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           opt_state=opt_state, rng=rng)
        # full placement specs: pipe stacking (+ per-stage Megatron
        # annotations when the stages carry them).  A single un-stacked
        # block init supplies the annotation specs; the stacked leaves get
        # 'pipe' prepended.
        block_abs = jax.eval_shape(lambda k: o_block.init(k, h),
                                   jax.random.key(0))
        block_ann = nn.get_partition_spec(block_abs)["params"]
        stage_specs = {
            "embed": nn.get_partition_spec(embed_v)["params"],
            "head": nn.get_partition_spec(head_v)["params"],
            "blocks": jax.tree.map(
                lambda s: P(meshlib.PIPE_AXIS, *s), block_ann,
                is_leaf=lambda s: isinstance(s, P)),
        }
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            _full_spec_tree(state, stage_specs),
            is_leaf=lambda x: isinstance(x, P))
        return meshlib.state_to_global(state, shardings)

    # ------------------------------------------------------------- forward
    def _sequential_logits(self, params, x):
        """Un-pipelined forward (scan over the stacked stages) — used for
        eval and as the parity oracle in tests.  Uses the seq-disabled
        stage twins: outside the manual shard_map the full sequence is in
        one piece, so dense attention at global positions IS the oracle
        semantics of the seq-sharded pipeline."""
        o_embed, o_block = self._oracle_stages()
        h = o_embed.apply({"params": params["embed"]}, x)

        def body(h, bp):
            return o_block.apply({"params": bp}, h), None

        h, _ = lax.scan(body, h, params["blocks"])
        return self.head.apply({"params": params["head"]}, h)

    # ---------------------------------------------------------------- step
    def step(self, state, x, y):
        state, metrics = super().step(state, x, y)
        if self.moe:
            self.overflow_monitor.observe(metrics["overflow"])
        return state, metrics

    def _build_step(self):
        if self.schedule == "1f1b":
            return self._build_step_1f1b()
        return self._build_step_gpipe()

    def _build_step_gpipe(self):
        from distributed_tensorflow_tpu.engines.expert_parallel import (
            router_losses)

        tx = self.tx
        embed, block, head = self.embed, self.block, self.head
        M = self.microbatches
        sp = self.sp_n
        moe = self.moe
        aux_w, z_w = self.aux_weight, self.router_z_weight

        if moe:
            # MoE stage: capture the sown router diagnostics alongside the
            # activations.  Bubble ticks run the block on garbage buffers
            # like every other tick; their (finite, meaningless) router
            # stats are masked out of the objective in the tick below.
            def block_apply(bp, h):
                out, col = block.apply({"params": bp}, h,
                                       mutable=["intermediates"])
                return out, router_losses(col["intermediates"])
        else:
            def block_apply(bp, h):
                return block.apply({"params": bp}, h)

        if self.remat:
            # recompute-in-backward: safe under a manual 'seq' axis because
            # the block runs unconditionally on every device each tick, so
            # the ring's ppermutes replay symmetrically during recompute
            block_apply = jax.checkpoint(block_apply)
        data_axis, pipe_axis = meshlib.DATA_AXIS, meshlib.PIPE_AXIS
        # with a manual 'seq' axis, per-device losses are per-token-block
        # partial means: they reduce (and the AD-boundary psum runs) over
        # all three axes, and the mean-gradient scale gains a 1/sp
        seq_axes = (meshlib.SEQ_AXIS,) if sp > 1 else ()

        def device_step(state: TrainState, x, y):
            S = lax.axis_size(pipe_axis)
            n_data = lax.axis_size(data_axis)
            stage = lax.axis_index(pipe_axis)
            mb = x.shape[0] // M
            micro_x = x.reshape((M, mb) + x.shape[1:])
            micro_y = y.reshape((M, mb) + y.shape[1:])
            perm = [(i, (i + 1) % S) for i in range(S)]

            def loss_fn(params):
                blocks_local = jax.tree.map(lambda a: a[0], params["blocks"])
                # embed/head params enter replicated (pipe-invariant); cast
                # them varying HERE, outside the conds below.  The AD
                # transpose of this pcast is the psum that combines their
                # grads across the mesh — it must not live inside a cond
                # branch that only some devices execute (a collective in a
                # partially-taken ConditionalThunk deadlocks/aborts), and
                # hoisting it here also means one psum per step instead of
                # one per tick.
                both = (data_axis, pipe_axis) + seq_axes
                embed_v = jax.tree.map(
                    lambda a: lax.pcast(a, both, to="varying"),
                    params["embed"])
                head_v = jax.tree.map(
                    lambda a: lax.pcast(a, both, to="varying"),
                    params["head"])

                def tick(buf, i):
                    # stage 0 injects microbatch i — under lax.cond, so only
                    # stage 0 (and only during the fill, i < M) pays the
                    # embed FLOPs: the predicate is device-varying, and in
                    # shard_map's per-device SPMD program each core takes
                    # its own branch at runtime.  Other stages (and the
                    # drain ticks) pass their rotated buffer through; that
                    # garbage follows a path that never reaches the head
                    # within this scan (a microbatch injected at tick
                    # i ≥ M would drain at i+S-1 > the last tick M+S-2)
                    xi = lax.dynamic_index_in_dim(
                        micro_x, jnp.clip(i, 0, M - 1), keepdims=False)

                    def inject(_):
                        h = embed.apply({"params": embed_v}, xi)
                        return jax.tree.map(_match_vma, h, buf)

                    h_in = lax.cond((stage == 0) & (i < M), inject,
                                    lambda _: buf, None)
                    if moe:
                        h_out, (aux_r, z_r, ovf_r) = block_apply(
                            blocks_local, h_in)
                        # this device's buffer holds a REAL microbatch
                        # (number i − stage) only while 0 ≤ i − stage < M;
                        # bubble ticks' router stats are masked to zero so
                        # they contribute nothing to the objective (and a
                        # zero gradient through the multiply)
                        bvalid = ((i - stage >= 0)
                                  & (i - stage < M)).astype(jnp.float32)
                        aux_i = aux_r * bvalid
                        z_i = z_r * bvalid
                        ovf_i = ovf_r * bvalid
                    else:
                        h_out = block_apply(blocks_local, h_in)
                    # last stage drains microbatch i-(S-1); the head matmul
                    # and loss run only there (again lax.cond, not masking)
                    oi = i - (S - 1)
                    yi = lax.dynamic_index_in_dim(
                        micro_y, jnp.clip(oi, 0, M - 1), keepdims=False)
                    yi = lax.pcast(yi, pipe_axis, to="varying")
                    valid = ((oi >= 0) & (oi < M)).astype(jnp.float32)
                    valid = lax.pcast(valid, pipe_axis, to="varying")
                    if sp > 1:
                        # loss/acc must come out fully varying to match the
                        # zero branch; valid (tick-derived) starts invariant
                        # over seq
                        valid = lax.pcast(valid, seq_axes, to="varying")

                    def drain(h):
                        logits = head.apply({"params": head_v}, h)
                        loss_i = cross_entropy(logits, yi).mean() * valid
                        acc_i = (logits.argmax(-1) == yi).mean(
                            ).astype(jnp.float32) * valid
                        return loss_i, valid, acc_i

                    # branch outputs must carry identical varying-axes
                    # types: loss/acc are (data, pipe)-varying, w pipe-only
                    zero_dp = lax.pcast(jnp.zeros((), jnp.float32),
                                        (data_axis, pipe_axis) + seq_axes,
                                        to="varying")
                    zero_p = lax.pcast(jnp.zeros((), jnp.float32),
                                       (pipe_axis,) + seq_axes, to="varying")
                    loss_i, w, acc_i = lax.cond(
                        stage == S - 1, drain,
                        lambda h: (zero_dp, zero_p, zero_dp), h_out)
                    buf_next = jax.tree.map(
                        lambda a: lax.ppermute(a, axis_name=pipe_axis,
                                               perm=perm), h_out)
                    outs = (loss_i, acc_i, w)
                    if moe:
                        outs = outs + (aux_i, z_i, ovf_i)
                    return buf_next, outs

                # buffer shape/dtype comes from the embed output itself, so
                # any activation pytree (arrays, (h, mask) tuples, ...) works
                h0 = jax.eval_shape(
                    lambda p, a: embed.apply({"params": p}, a),
                    params["embed"], micro_x[0])
                buf0 = jax.tree.map(
                    lambda a: lax.pcast(jnp.zeros(a.shape, a.dtype),
                                        (data_axis, pipe_axis) + seq_axes,
                                        to="varying"),
                    h0)
                _, ys = lax.scan(tick, buf0, jnp.arange(M + S - 1))
                # losses nonzero only on the last stage; scale so the
                # implicit psum over BOTH axes at the AD boundary yields
                # the global batch mean (same mechanism as engines/sync.py).
                # The router aux/z sums ride the SAME scale: the pipe psum
                # turns each stage's local router sum into the sum over ALL
                # the model's routers (router_losses is a sum over a
                # stage's routers — matching the composite's
                # sum-over-blocks objective, engines/composite.py), while
                # /(M·n_data·sp) averages over the microbatch × data-shard
                # × seq-block applications.
                if moe:
                    losses, accs, ws, auxs, zs, ovfs = ys
                    local_sum = (losses.sum() + aux_w * auxs.sum()
                                 + z_w * zs.sum())
                    ovf_sum = ovfs.sum()
                else:
                    losses, accs, ws = ys
                    local_sum = losses.sum()
                    ovf_sum = jnp.zeros((), jnp.float32)
                scaled = local_sum / (M * n_data * sp)
                return scaled, (losses.sum(), accs.sum(), ws.sum(), ovf_sum)

            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
            ((_, (loss_sum, acc_sum, w_sum, ovf_sum)),
             grads) = grad_fn(state.params)
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            both = (data_axis, pipe_axis) + seq_axes
            # w_sum is data-invariant (stage/tick-derived; the drain pcast
            # already made it seq-varying when sp > 1) — add the data axis
            # so it can ride the same all-axes psum
            w_sum = lax.pcast(w_sum, data_axis, to="varying")
            tot_w = lax.psum(w_sum, both)
            metrics = {
                "loss": lax.psum(loss_sum, both) / tot_w,
                "accuracy": lax.psum(acc_sum, both) / tot_w,
            }
            if moe:
                # mean over every (stage, microbatch, data-shard, seq-block)
                # router application — the same overflow_mean semantics as
                # engines/expert_parallel.py, watched by the monitor in step()
                metrics["overflow"] = lax.psum(ovf_sum, both) / (
                    S * M * n_data * sp)
            new_state = state.replace(step=state.step + 1, params=params,
                                      opt_state=opt_state)
            return new_state, metrics

        return self._wrap_pipe_step(device_step)

    def _build_step_1f1b(self):
        """One-forward-one-backward schedule, hand-scheduled backward.

        Lockstep timetable (tick t, stage s, microbatch i):
          fwd(s, i) at t = 2i + s          bwd(s, i) at t = 2i + 2S − 1 − s
        so fwd and bwd ticks interleave per device (opposite parities), at
        most S microbatches are in flight per stage (stash is S slots,
        indexed i mod S — collision-free because in-flight span < S), and
        the whole step is T = 2(M + S − 1) ticks.  Backward recomputes the
        stage forward from the stashed INPUT (remat) inside `jax.vjp`;
        cotangents hop s → s−1 on a reverse ppermute ring.

        Every pcast is hoisted out of the `lax.cond`s: a cond branch taken
        by only some devices must stay collective-free (see the gpipe tick
        comment), so all branch operands are pre-cast (data, pipe)-varying
        and the cross-device grad reductions happen as explicit psums after
        the scan."""
        tx = self.tx
        embed, block, head = self.embed, self.block, self.head
        M = self.microbatches
        S = self.n_stages
        data_axis, pipe_axis = meshlib.DATA_AXIS, meshlib.PIPE_AXIS

        def device_step(state: TrainState, x, y):
            n_data = lax.axis_size(data_axis)
            stage = lax.axis_index(pipe_axis)
            mb = x.shape[0] // M
            micro_x = lax.pcast(
                x.reshape((M, mb) + x.shape[1:]), pipe_axis, to="varying")
            micro_y = lax.pcast(
                y.reshape((M, mb) + y.shape[1:]), pipe_axis, to="varying")
            perm_f = [(i, (i + 1) % S) for i in range(S)]
            perm_b = [(i, (i - 1) % S) for i in range(S)]
            both = (data_axis, pipe_axis)
            params = state.params

            # everything a cond branch touches is pre-cast fully varying
            blocks_v = jax.tree.map(
                lambda a: lax.pcast(a[0], data_axis, to="varying"),
                params["blocks"])
            embed_v = jax.tree.map(
                lambda a: lax.pcast(a, both, to="varying"), params["embed"])
            head_v = jax.tree.map(
                lambda a: lax.pcast(a, both, to="varying"), params["head"])
            one_v = lax.pcast(jnp.ones((), jnp.float32), both, to="varying")
            zero_v = one_v * 0.0

            h0 = jax.eval_shape(
                lambda p, a: embed.apply({"params": p}, a),
                params["embed"], micro_x[0])

            def zeros_v(tree, lead=()):
                return jax.tree.map(
                    lambda a: lax.pcast(jnp.zeros(lead + a.shape, a.dtype),
                                        both, to="varying"), tree)

            fbuf0, bbuf0 = zeros_v(h0), zeros_v(h0)
            stash0 = zeros_v(h0, lead=(S,))
            gblk0 = jax.tree.map(lambda a: a * 0.0, blocks_v)
            gemb0 = jax.tree.map(lambda a: a * 0.0, embed_v)
            ghead0 = jax.tree.map(lambda a: a * 0.0, head_v)

            def tick(carry, t):
                (fbuf, bbuf, stash, g_blk, g_emb, g_head,
                 loss_s, acc_s, w_s) = carry

                # ---------------- forward sub-tick: fwd(s, i) at t = 2i+s
                tf = t - stage
                f_valid = (tf >= 0) & (tf % 2 == 0) & (tf < 2 * M)
                i_f = jnp.clip(tf // 2, 0, M - 1)
                xi = lax.dynamic_index_in_dim(micro_x, i_f, keepdims=False)

                def inject(_):
                    h = embed.apply({"params": embed_v}, xi)
                    return jax.tree.map(_match_vma, h, fbuf)

                h_in = lax.cond(f_valid & (stage == 0), inject,
                                lambda _: fbuf, None)

                def fwd(ops):
                    h_in, stash = ops
                    h_out = block.apply({"params": blocks_v}, h_in)
                    stash = jax.tree.map(
                        lambda st, v: lax.dynamic_update_index_in_dim(
                            st, v, i_f % S, 0), stash, h_in)
                    return h_out, stash

                h_out, stash = lax.cond(f_valid, fwd,
                                        lambda ops: ops, (h_in, stash))

                # --------------- backward sub-tick: bwd(s, i) at 2i+2S-1-s
                tb = t - (2 * S - 1 - stage)
                b_valid = (tb >= 0) & (tb % 2 == 0) & (tb < 2 * M)
                i_b = jnp.clip(tb // 2, 0, M - 1)
                xb = lax.dynamic_index_in_dim(micro_x, i_b, keepdims=False)
                yb = lax.dynamic_index_in_dim(micro_y, i_b, keepdims=False)

                def bwd(ops):
                    bbuf, g_blk, g_emb, g_head, loss_s, acc_s, w_s = ops
                    h_saved = jax.tree.map(
                        lambda st: lax.dynamic_index_in_dim(
                            st, i_b % S, keepdims=False), stash)
                    # recompute this stage's forward under vjp (remat)
                    h_re, blk_vjp = jax.vjp(
                        lambda bp, h: block.apply({"params": bp}, h),
                        blocks_v, h_saved)

                    def head_cot(_):
                        def scalar(hv, h):
                            logits = head.apply({"params": hv}, h)
                            l_raw = cross_entropy(logits, yb).mean()
                            acc = (logits.argmax(-1) == yb).mean(
                                ).astype(jnp.float32)
                            # same scale as the gpipe path: the psum'd sum
                            # over stages/shards is the global batch mean
                            return l_raw / (M * n_data), (l_raw, acc)

                        (g_hv, cot), (l_raw, acc) = jax.grad(
                            scalar, argnums=(0, 1), has_aux=True)(
                                head_v, h_re)
                        return cot, g_hv, l_raw * one_v, acc * one_v, one_v

                    cot_out, g_hv, l_raw, acc, w = lax.cond(
                        stage == S - 1, head_cot,
                        lambda _: (bbuf, ghead0, zero_v, zero_v, zero_v),
                        None)
                    g_bp, cot_in = blk_vjp(cot_out)

                    def embed_grads(_):
                        _, evjp = jax.vjp(
                            lambda p: embed.apply({"params": p}, xb),
                            embed_v)
                        return evjp(cot_in)[0]

                    g_e = lax.cond((stage == 0), embed_grads,
                                   lambda _: gemb0, None)
                    return (cot_in,
                            jax.tree.map(jnp.add, g_blk, g_bp),
                            jax.tree.map(jnp.add, g_emb, g_e),
                            jax.tree.map(jnp.add, g_head, g_hv),
                            loss_s + l_raw, acc_s + acc, w_s + w)

                (cot_send, g_blk, g_emb, g_head,
                 loss_s, acc_s, w_s) = lax.cond(
                    b_valid, bwd,
                    lambda ops: ops,
                    (bbuf, g_blk, g_emb, g_head, loss_s, acc_s, w_s))

                # ring hops happen unconditionally — every device must join
                fbuf = jax.tree.map(
                    lambda a: lax.ppermute(a, axis_name=pipe_axis,
                                           perm=perm_f), h_out)
                bbuf = jax.tree.map(
                    lambda a: lax.ppermute(a, axis_name=pipe_axis,
                                           perm=perm_b), cot_send)
                return (fbuf, bbuf, stash, g_blk, g_emb, g_head,
                        loss_s, acc_s, w_s), None

            carry0 = (fbuf0, bbuf0, stash0, gblk0, gemb0, ghead0,
                      zero_v, zero_v, zero_v)
            (_, _, _, g_blk, g_emb, g_head,
             loss_s, acc_s, w_s), _ = lax.scan(
                tick, carry0, jnp.arange(2 * (M + S - 1)))

            grads = {
                "embed": jax.tree.map(
                    lambda a: lax.psum(a, both), g_emb),
                "blocks": jax.tree.map(
                    lambda a: lax.psum(a, data_axis)[None], g_blk),
                "head": jax.tree.map(
                    lambda a: lax.psum(a, both), g_head),
            }
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            tot_w = lax.psum(w_s, both)
            metrics = {
                "loss": lax.psum(loss_s, both) / tot_w,
                "accuracy": lax.psum(acc_s, both) / tot_w,
            }
            new_state = state.replace(step=state.step + 1, params=params,
                                      opt_state=opt_state)
            return new_state, metrics

        return self._wrap_pipe_step(device_step)

    def _wrap_pipe_step(self, device_step):
        """Lazy shard_map+jit wrapper shared by both schedules: the in/out
        spec trees depend on the concrete state structure, so the shard_map
        is built on first call.  With a 'model' mesh axis the shard_map is
        PARTIAL-manual — manual over (data, pipe) so the schedule's
        ppermute ring is explicit, auto over 'model' so GSPMD inserts the
        Megatron collectives inside each stage (every model-axis peer holds
        the same stage index, so per-device `lax.cond` branching stays
        uniform along the auto axis and its collectives cannot deadlock).
        The jit is kept on ``self._jit_step`` so tests can inspect the
        compiled HLO (e.g. assert embed/head sit behind `conditional`s)."""
        compiled = {}
        manual = {meshlib.DATA_AXIS, meshlib.PIPE_AXIS}
        if self.sp_n > 1:
            manual.add(meshlib.SEQ_AXIS)

        def step_fn(state, x, y):
            if "fn" not in compiled:
                spec = _pipe_spec_tree(state)
                # any mesh axis outside the manual set ('model' for pp×tp,
                # 'expert' for pp×ep) stays a GSPMD auto axis
                kw = ({"axis_names": manual}
                      if set(self.mesh.axis_names) - manual else {})
                if self.sp_n > 1:
                    x_spec = P(meshlib.DATA_AXIS, meshlib.SEQ_AXIS)
                    y_spec = (P(meshlib.DATA_AXIS, meshlib.SEQ_AXIS)
                              if np.ndim(y) >= 2 else P(meshlib.DATA_AXIS))
                else:
                    x_spec = y_spec = P(meshlib.DATA_AXIS)
                smapped = jax.shard_map(
                    device_step, mesh=self.mesh,
                    in_specs=(spec, x_spec, y_spec),
                    out_specs=(spec, P()),
                    **kw,
                )
                compiled["fn"] = self._jit_step = jax.jit(
                    smapped, donate_argnums=0)
            return compiled["fn"](state, x, y)

        return step_fn

    # ---------------------------------------------------------------- eval
    def eval_params(self, state: TrainState):
        return state.params

    def _build_eval(self):
        # GSPMD jit: blocks stay sharded over 'pipe'; XLA moves stage params
        # to where the scan needs them
        return self._build_eval_gspmd(self._sequential_logits)

    # ------------------------------------------------------------- generate
    def generate(self, state: TrainState, prompt, max_new_tokens: int):
        """Greedy-decode ``max_new_tokens`` per prompt row from pipe-stacked
        GPT stage params.

        KV caches don't exist for stacked stages (each GPTBlock's cache
        would need a 'pipe'-stacked twin threaded through the schedule), so
        decoding reuses the eval path instead: one fixed-length sequential
        forward (``_sequential_logits`` — GSPMD moves stage params through
        the block scan) inside a ``lax.fori_loop`` that fills one token per
        iteration.  Causal attention makes the not-yet-written zero padding
        invisible to positions already decoded, so ONE compile covers the
        whole decode; cost is O(N) full forwards instead of the KV sampler's
        O(N) single-token steps — the right trade for post-train sampling,
        wrong for serving (which would re-assemble a monolithic model from
        a checkpoint instead).

        ``prompt``: (B, P) int32 token ids.  Returns (B, P + N) int32 —
        prompt followed by the greedy continuation.  GPT stage families
        only (the BERT stages end in a classifier, not a vocab head), and
        DENSE-FFN stages only: the padding-invisibility argument is a
        causal-attention property — capacity-limited MoE routing flattens
        ALL positions into its dispatch (capacity and slot priority depend
        on the not-yet-decoded zeros), so a fixed-length forward over a
        partially-filled buffer is not the greedy continuation there."""
        from distributed_tensorflow_tpu.models.gpt import GPTPipeEmbed

        if not isinstance(self.embed, GPTPipeEmbed):
            raise ValueError(
                f"generate needs GPT decoder stages (vocab-head output); "
                f"this engine's embed stage is "
                f"{type(self.embed).__name__}")
        if self.moe:
            raise ValueError(
                "generate does not support MoE stage blocks: the routers' "
                "capacity-limited dispatch sees every position of the "
                "fixed-length buffer, so the zero padding claims expert "
                "capacity and shifts routing — the decode would not be the "
                "true greedy continuation.  Sample from a dense-FFN "
                "pipeline run, or train MoE without -pp and use the "
                "KV-cache sampler")
        prompt = jnp.asarray(prompt, jnp.int32)
        if prompt.ndim != 2:
            raise ValueError(f"prompt must be (batch, len), got "
                             f"{prompt.shape}")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be positive, got {max_new_tokens}")
        p_len = prompt.shape[1]
        total = p_len + int(max_new_tokens)
        if total > self.embed.max_len:
            raise ValueError(
                f"prompt {p_len} + {max_new_tokens} new tokens exceeds the "
                f"stages' max_len {self.embed.max_len}")

        # one compiled program per (prompt_len, total) — repeated sampling
        # (per-eval-batch loops) reuses it instead of re-jitting, the same
        # reason models/gpt.py lru-caches its compiled KV sampler
        key = (p_len, total)
        if key not in self._decode_cache:
            def decode(params, toks):
                def one(i, tk):
                    logits = self._sequential_logits(params, tk)
                    nxt = jnp.argmax(logits[:, i - 1, :], axis=-1)
                    return tk.at[:, i].set(nxt.astype(jnp.int32))

                return lax.fori_loop(p_len, total, one, toks)

            self._decode_cache[key] = jax.jit(decode)

        toks0 = jnp.zeros((prompt.shape[0], total), jnp.int32)
        toks0 = toks0.at[:, :p_len].set(prompt)
        return jax.device_get(self._decode_cache[key](state.params, toks0))
