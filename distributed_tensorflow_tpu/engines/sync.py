"""Sync data-parallel engine.

TPU-native replacement for the reference's *sync parameter server*: where the
reference's N worker threads serially `apply_gradients` on one shared model
under a lock and barrier (reference server.py:90-96), here each device
computes gradients on its batch shard, `pmean` combines them over ICI, and
every device applies one identical optimizer update — standard sync-SGD
semantics (the deliberate semantic delta from the reference's
sequential-apply is documented in SURVEY.md §2.4(1)).

Also serves as the math core of the 'allreduce' mode (the
MultiWorkerMirroredStrategy RING replacement, reference dist_keras.py:77-78):
`pmean` of gradients *is* a ring allreduce on a TPU torus.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.engines.base import Engine, TrainState, make_loss_fn
from distributed_tensorflow_tpu.parallel import collectives as coll
from distributed_tensorflow_tpu.parallel import compression
from distributed_tensorflow_tpu.parallel import overlap
from distributed_tensorflow_tpu.parallel import precision as precisionlib


class SyncEngine(Engine):
    """``grad_accum`` K > 1 splits each device's batch shard into K
    microbatches and accumulates their gradients inside one jitted step
    before the single optimizer update — identical math to K=1 on the same
    global batch (mean of equal-sized chunk means; parity-tested with SGD in
    tests/test_engines.py), but peak activation memory drops ~K×.  This is
    the standard large-batch-beyond-HBM device-side technique; the reference
    has no counterpart (its batch lives on the host and grads stream out
    per-batch, reference client.py:78-95).

    ``grad_compression`` routes the gradient allreduce through a codec
    (parallel/compression.py): 'none' keeps the exact pre-codec program
    (``_build_step_exact``); bf16/int8 build a separate step whose ONE
    explicit collective is the codec's (``_build_step_compressed``).

    ``precision`` (parallel/precision.py): low-precision param storage
    makes the gradient psum itself move the narrow dtype (grads share
    the params' dtype — the wire win with NO codec); fp16-f32master's
    loss scale is threaded out of opt_state into the loss here
    (``supports_loss_scaling``), and the master-weights wrapper installed
    by the base unscales the gradients after the reduce."""

    supports_loss_scaling = True

    def __init__(self, *args, grad_accum: int = 1, **kw):
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        super().__init__(*args, **kw)
        self.grad_accum = grad_accum

    def _loss_scale(self, opt_state):
        """The traced loss scale of the entering state, or None when the
        policy does not scale (python gate: the scale-free programs stay
        byte-identical)."""
        if not self.precision.loss_scaling:
            return None
        return precisionlib.loss_scale_from(opt_state)

    def _build_step(self):
        # bucketing alone (codec 'none' + --grad-bucket-mb) also takes the
        # explicit-collective step: the per-bucket psums are what the
        # latency-hiding scheduler overlaps with backward compute
        if not compression.codec_active(self.grad_codec):
            return self._build_step_exact()
        return self._build_step_compressed()

    def _build_step_exact(self):
        """The uncompressed program, UNTOUCHED by the codec work — so
        ``--grad-compression none`` stays bitwise identical to the
        pre-codec engine (acceptance-tested at k=1 and k=8)."""
        loss_fn = make_loss_fn(self.model.apply)
        tx, axis, K = self.tx, self.axis, self.grad_accum

        def device_step(state: TrainState, x, y):
            rng = self._per_device_rng(state.rng, state.step)
            n = jax.lax.axis_size(axis)
            # dynamic loss scale (fp16-f32master): multiply the
            # differentiated loss by the scale the entering opt_state
            # carries; the master-weights wrapper divides the gradients
            # back out.  None (every other policy) adds nothing.
            ls = self._loss_scale(state.opt_state)

            def scaled_loss(params, xc, yc, rng_c):
                loss, acc = loss_fn(params, xc, yc, rng_c)
                if ls is not None:
                    return loss * ls / (n * K), (loss, acc)
                # scale so the cross-device AND cross-microbatch SUM of
                # losses is the global batch mean: under shard_map's
                # varying-axes typing, grad-of-replicated-params IS psum'd
                # over the data axis by the AD transpose (the
                # varying→invariant boundary).  That implicit psum is the
                # allreduce of sync DP — the XLA equivalent of the
                # reference's per-batch TCP round-trip of pickled grads up +
                # weights down (reference client.py:85-90).  An explicit
                # pmean here would silently no-op (invariant input),
                # wrecking the scale — tested against single-device training
                # with SGD in tests/test_engines.py.
                return loss / (n * K), (loss, acc)

            grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)

            if K == 1:
                (_, (loss, acc)), grads = grad_fn(state.params, x, y, rng)
            else:
                if x.shape[0] % K:
                    raise ValueError(
                        f"per-device batch {x.shape[0]} not divisible by "
                        f"grad_accum {K}")
                xm = x.reshape((K, x.shape[0] // K) + x.shape[1:])
                ym = y.reshape((K, y.shape[0] // K) + y.shape[1:])
                # differentiate w.r.t. a VARYING copy of the params so each
                # microbatch's gradient stays device-local (no varying→
                # invariant boundary inside the scan body): the implicit
                # AD-transpose psum would otherwise all-reduce the full
                # gradient K times per step, multiplying DP communication
                # by K — the one explicit psum after the scan is the whole
                # cross-device cost, same as K=1
                params_v = jax.tree.map(
                    lambda p: jax.lax.pcast(p, axis, to="varying"),
                    state.params)

                def micro(carry, chunk):
                    g_acc, l_acc, a_acc, i = carry
                    xc, yc = chunk
                    # independent dropout per microbatch, like separate steps
                    (_, (l, a)), g = grad_fn(params_v, xc, yc,
                                             jax.random.fold_in(rng, i))
                    g_acc = jax.tree.map(jnp.add, g_acc, g)
                    return (g_acc, l_acc + l, a_acc + a, i + 1), None

                # typed carry (weak Python scalars would change dtype after
                # one addition), all varying: grads/loss/acc accumulate
                # per-device values until the final psum
                zeros = jax.tree.map(
                    lambda p: jax.lax.pcast(jnp.zeros_like(p), axis,
                                            to="varying"), state.params)
                var0 = jax.lax.pcast(jnp.zeros((), jnp.float32), axis,
                                     to="varying")
                init = (zeros, var0, var0, jnp.zeros((), jnp.int32))
                (g_local, loss, acc, _), _ = jax.lax.scan(micro, init,
                                                          (xm, ym))
                # the 1/(n·K) loss scale makes this sum the global-batch
                # mean gradient
                grads = jax.tree.map(lambda g: jax.lax.psum(g, axis), g_local)
                loss, acc = loss / K, acc / K

            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            metrics = coll.all_reduce_mean({"loss": loss, "accuracy": acc}, axis)
            new_state = state.replace(
                step=state.step + 1, params=params, opt_state=opt_state)
            return new_state, metrics

        smapped = jax.shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P(), P(self.axis), P(self.axis)),
            out_specs=(P(), P()),
        )
        return jax.jit(smapped, donate_argnums=0)

    def _build_step_compressed(self, codec=None, reduce_in_scan=None):
        """Codec-active step: gradients stay device-local through AD and
        the explicit collectives are the codec's — encode on-device,
        reduce in the codec's wire dtype, widen back to f32 for the
        optimizer after.  The 1/(n·K) loss scale makes the codec's sum the
        global batch-mean gradient, exactly as the exact path's psum.

        Built with ``check_vma=False`` (like the async/gossip engines):
        the int8 codec's two-phase reduce ends in an ``all_gather``, whose
        output is replicated in VALUE but not provably so to the static
        replication checker — with checking off, shard_map also inserts no
        automatic AD-transpose psum at the replicated-params boundary, so
        the gradients reach the codec device-local with no ``pcast``
        bookkeeping.  Correctness is covered by the compressed-vs-exact
        closeness and k-parity tests (tests/test_compression.py,
        tests/test_overlap.py).

        Overlap restructure (``reduce_in_scan``, defaulting to the
        engine codec's bucketed-ness): with a BUCKETED codec and K > 1
        microbatches, the reduce moves INSIDE the accumulation scan —
        microbatch i's bucketed exchange is then data-independent of
        microbatch i+1's backward, so XLA's latency-hiding scheduler can
        run them concurrently.  Numerics: Σᵢ psum(gᵢ) instead of
        psum(Σᵢ gᵢ) — the same value up to fp addition order (the
        documented accumulation tolerance, MIGRATING.md); the
        stochastic-rounding key folds the microbatch index so each
        exchange rounds independently.  Without bucketing the PR 3
        single-reduce-after-scan program is kept verbatim.

        ``codec`` overrides the engine's codec for the overlap probe's
        compute-only twin (parallel/overlap.ProbeLocalCodec) — the
        returned program is fresh, never cached on the engine."""
        loss_fn = make_loss_fn(self.model.apply)
        tx, axis, K = self.tx, self.axis, self.grad_accum
        if codec is None:
            codec = self.grad_codec
        if reduce_in_scan is None:
            reduce_in_scan = bool(getattr(self.grad_codec, "bucketed",
                                          False))

        def device_step(state: TrainState, x, y):
            rng = self._per_device_rng(state.rng, state.step)
            n = jax.lax.axis_size(axis)
            # per-device key for the codec's stochastic rounding: each
            # device quantizes its LOCAL gradient independently before the
            # exchange (that independence is what makes the rounding noise
            # average out across the ring)
            codec_key = compression.codec_rng(rng)
            ls = self._loss_scale(state.opt_state)

            def scaled_loss(params, xc, yc, rng_c):
                loss, acc = loss_fn(params, xc, yc, rng_c)
                # same 1/(n·K) scale as the exact path: the codec's SUM of
                # per-device (per-microbatch) grads is the global mean —
                # times the dynamic loss scale when the policy scales
                # (unscaled by the master-weights wrapper after the
                # reduce; the python gate keeps scale-free programs
                # byte-identical)
                scaled = loss / (n * K)
                if ls is not None:
                    scaled = scaled * ls
                return scaled, (loss, acc)

            grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)

            if K == 1:
                (_, (loss, acc)), g_local = grad_fn(state.params, x, y, rng)
            else:
                if x.shape[0] % K:
                    raise ValueError(
                        f"per-device batch {x.shape[0]} not divisible by "
                        f"grad_accum {K}")
                xm = x.reshape((K, x.shape[0] // K) + x.shape[1:])
                ym = y.reshape((K, y.shape[0] // K) + y.shape[1:])

                def micro(carry, chunk):
                    g_acc, l_acc, a_acc, i = carry
                    xc, yc = chunk
                    # independent dropout per microbatch, like separate steps
                    (_, (l, a)), g = grad_fn(state.params, xc, yc,
                                             jax.random.fold_in(rng, i))
                    if reduce_in_scan:
                        # overlap mode: exchange THIS microbatch's buckets
                        # now — data-independent of the next microbatch's
                        # backward, so the scheduler can overlap them.
                        # Independent rounding key per microbatch.
                        g = codec.all_reduce_sum(
                            g, axis, rng=jax.random.fold_in(codec_key, i))
                    return (jax.tree.map(jnp.add, g_acc, g),
                            l_acc + l, a_acc + a, i + 1), None

                zero = jnp.zeros((), jnp.float32)
                init = (jax.tree.map(jnp.zeros_like, state.params),
                        zero, zero, jnp.zeros((), jnp.int32))
                (g_local, loss, acc, _), _ = jax.lax.scan(micro, init,
                                                          (xm, ym))
                loss, acc = loss / K, acc / K

            if K > 1 and reduce_in_scan:
                # already reduced per microbatch inside the scan; the
                # 1/(n·K) scale made the K-sum of psums the global mean
                grads = g_local
            else:
                # the whole cross-device cost: one compressed allreduce
                grads = codec.all_reduce_sum(g_local, axis, rng=codec_key)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            metrics = coll.all_reduce_mean({"loss": loss, "accuracy": acc}, axis)
            new_state = state.replace(
                step=state.step + 1, params=params, opt_state=opt_state)
            return new_state, metrics

        smapped = jax.shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P(), P(self.axis), P(self.axis)),
            out_specs=(P(), P()),
            check_vma=False,  # value-replicated outputs the checker can't
            #                   prove (gather-based codec collectives)
        )
        return jax.jit(smapped, donate_argnums=0)

    # ------------------------------------------------------ overlap probe
    def _build_collective_only(self, codec):
        """The gradient exchange ALONE, over param-shaped values: the
        codec's collective under the same shard_map rendering as the
        step, nothing else in the program.  Deterministic rounding (no
        rng) — the probe times it, nothing consumes the values."""
        axis = self.axis

        def device_collective(tree):
            return codec.all_reduce_sum(tree, axis)

        smapped = jax.shard_map(
            device_collective, mesh=self.mesh,
            in_specs=(P(),), out_specs=P(),
            check_vma=False,  # same unprovable-replication story as the
            #                   compressed step's codec collectives
        )
        return jax.jit(smapped)

    def build_overlap_probe_fns(self):
        """The three programs parallel/overlap.probe_engine_overlap times
        to split exposed vs hidden collective seconds:

        * ``full``       — the codec-active step (the engine's real
          program when a codec/bucketing is on; the same math rendered
          through the explicit-collective step otherwise, so the probe
          always has a collective it can elide);
        * ``compute``    — the same step with every collective elided
          (ProbeLocalCodec): the compute-only twin;
        * ``collective`` — the gradient exchange alone.

        All three are fresh jitted programs — nothing here touches the
        engine's cached step, and the probe's states are its own copies
        (the step programs donate their inputs)."""
        codec = (self.grad_codec
                 if compression.codec_active(self.grad_codec)
                 else compression.GradCodec())
        reduce_in_scan = bool(getattr(self.grad_codec, "bucketed", False))
        return {
            "full": self._build_step_compressed(
                codec=codec, reduce_in_scan=reduce_in_scan),
            "compute": self._build_step_compressed(
                codec=overlap.ProbeLocalCodec(),
                reduce_in_scan=reduce_in_scan),
            "collective": self._build_collective_only(codec),
        }
