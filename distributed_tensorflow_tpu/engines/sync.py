"""Sync data-parallel engine.

TPU-native replacement for the reference's *sync parameter server*: where the
reference's N worker threads serially `apply_gradients` on one shared model
under a lock and barrier (reference server.py:90-96), here each device
computes gradients on its batch shard, `pmean` combines them over ICI, and
every device applies one identical optimizer update — standard sync-SGD
semantics (the deliberate semantic delta from the reference's
sequential-apply is documented in SURVEY.md §2.4(1)).

Also serves as the math core of the 'allreduce' mode (the
MultiWorkerMirroredStrategy RING replacement, reference dist_keras.py:77-78):
`pmean` of gradients *is* a ring allreduce on a TPU torus.
"""

from __future__ import annotations

import jax
import optax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.engines.base import Engine, TrainState, make_loss_fn
from distributed_tensorflow_tpu.parallel import collectives as coll


class SyncEngine(Engine):
    def _build_step(self):
        loss_fn = make_loss_fn(self.model.apply)
        tx, axis = self.tx, self.axis

        def device_step(state: TrainState, x, y):
            rng = self._per_device_rng(state.rng, state.step)
            n = jax.lax.axis_size(axis)

            def scaled_loss(params):
                loss, acc = loss_fn(params, x, y, rng)
                # scale so the cross-device SUM of per-device losses is the
                # global batch mean: under shard_map's varying-axes typing,
                # grad-of-replicated-params IS psum'd over the data axis by
                # the AD transpose (the varying→invariant boundary).  That
                # implicit psum is the allreduce of sync DP — the XLA
                # equivalent of the reference's per-batch TCP round-trip of
                # pickled grads up + weights down (reference client.py:85-90).
                # An explicit pmean here would silently no-op (invariant
                # input), wrecking the scale — tested against single-device
                # training with SGD in tests/test_engines.py.
                return loss / n, (loss, acc)

            (_, (loss, acc)), grads = jax.value_and_grad(
                scaled_loss, has_aux=True)(state.params)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            metrics = coll.all_reduce_mean({"loss": loss, "accuracy": acc}, axis)
            new_state = state.replace(
                step=state.step + 1, params=params, opt_state=opt_state)
            return new_state, metrics

        smapped = jax.shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P(), P(self.axis), P(self.axis)),
            out_specs=(P(), P()),
        )
        return jax.jit(smapped, donate_argnums=0)
