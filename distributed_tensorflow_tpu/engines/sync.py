"""Sync data-parallel engine.

TPU-native replacement for the reference's *sync parameter server*: where the
reference's N worker threads serially `apply_gradients` on one shared model
under a lock and barrier (reference server.py:90-96), here each device
computes gradients on its batch shard, `pmean` combines them over ICI, and
every device applies one identical optimizer update — standard sync-SGD
semantics (the deliberate semantic delta from the reference's
sequential-apply is documented in SURVEY.md §2.4(1)).

Also serves as the math core of the 'allreduce' mode (the
MultiWorkerMirroredStrategy RING replacement, reference dist_keras.py:77-78):
`pmean` of gradients *is* a ring allreduce on a TPU torus.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.engines.base import Engine, TrainState, make_loss_fn
from distributed_tensorflow_tpu.parallel import collectives as coll


class SyncEngine(Engine):
    """``grad_accum`` K > 1 splits each device's batch shard into K
    microbatches and accumulates their gradients inside one jitted step
    before the single optimizer update — identical math to K=1 on the same
    global batch (mean of equal-sized chunk means; parity-tested with SGD in
    tests/test_engines.py), but peak activation memory drops ~K×.  This is
    the standard large-batch-beyond-HBM device-side technique; the reference
    has no counterpart (its batch lives on the host and grads stream out
    per-batch, reference client.py:78-95)."""

    def __init__(self, *args, grad_accum: int = 1, **kw):
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        super().__init__(*args, **kw)
        self.grad_accum = grad_accum

    def _build_step(self):
        loss_fn = make_loss_fn(self.model.apply)
        tx, axis, K = self.tx, self.axis, self.grad_accum

        def device_step(state: TrainState, x, y):
            rng = self._per_device_rng(state.rng, state.step)
            n = jax.lax.axis_size(axis)

            def scaled_loss(params, xc, yc, rng_c):
                loss, acc = loss_fn(params, xc, yc, rng_c)
                # scale so the cross-device AND cross-microbatch SUM of
                # losses is the global batch mean: under shard_map's
                # varying-axes typing, grad-of-replicated-params IS psum'd
                # over the data axis by the AD transpose (the
                # varying→invariant boundary).  That implicit psum is the
                # allreduce of sync DP — the XLA equivalent of the
                # reference's per-batch TCP round-trip of pickled grads up +
                # weights down (reference client.py:85-90).  An explicit
                # pmean here would silently no-op (invariant input),
                # wrecking the scale — tested against single-device training
                # with SGD in tests/test_engines.py.
                return loss / (n * K), (loss, acc)

            grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)

            if K == 1:
                (_, (loss, acc)), grads = grad_fn(state.params, x, y, rng)
            else:
                if x.shape[0] % K:
                    raise ValueError(
                        f"per-device batch {x.shape[0]} not divisible by "
                        f"grad_accum {K}")
                xm = x.reshape((K, x.shape[0] // K) + x.shape[1:])
                ym = y.reshape((K, y.shape[0] // K) + y.shape[1:])
                # differentiate w.r.t. a VARYING copy of the params so each
                # microbatch's gradient stays device-local (no varying→
                # invariant boundary inside the scan body): the implicit
                # AD-transpose psum would otherwise all-reduce the full
                # gradient K times per step, multiplying DP communication
                # by K — the one explicit psum after the scan is the whole
                # cross-device cost, same as K=1
                params_v = jax.tree.map(
                    lambda p: jax.lax.pcast(p, axis, to="varying"),
                    state.params)

                def micro(carry, chunk):
                    g_acc, l_acc, a_acc, i = carry
                    xc, yc = chunk
                    # independent dropout per microbatch, like separate steps
                    (_, (l, a)), g = grad_fn(params_v, xc, yc,
                                             jax.random.fold_in(rng, i))
                    g_acc = jax.tree.map(jnp.add, g_acc, g)
                    return (g_acc, l_acc + l, a_acc + a, i + 1), None

                # typed carry (weak Python scalars would change dtype after
                # one addition), all varying: grads/loss/acc accumulate
                # per-device values until the final psum
                zeros = jax.tree.map(
                    lambda p: jax.lax.pcast(jnp.zeros_like(p), axis,
                                            to="varying"), state.params)
                var0 = jax.lax.pcast(jnp.zeros((), jnp.float32), axis,
                                     to="varying")
                init = (zeros, var0, var0, jnp.zeros((), jnp.int32))
                (g_local, loss, acc, _), _ = jax.lax.scan(micro, init,
                                                          (xm, ym))
                # the 1/(n·K) loss scale makes this sum the global-batch
                # mean gradient
                grads = jax.tree.map(lambda g: jax.lax.psum(g, axis), g_local)
                loss, acc = loss / K, acc / K

            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            metrics = coll.all_reduce_mean({"loss": loss, "accuracy": acc}, axis)
            new_state = state.replace(
                step=state.step + 1, params=params, opt_state=opt_state)
            return new_state, metrics

        smapped = jax.shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P(), P(self.axis), P(self.axis)),
            out_specs=(P(), P()),
        )
        return jax.jit(smapped, donate_argnums=0)
